"""Unit tests of the local engine's transitions and execution scheduling,
driven directly against a CommandStore (reference: local/CommandsTest.java)."""
from accord_tpu.local import commands
from accord_tpu.local.commands import AcceptOutcome, CommitOutcome
from accord_tpu.local.status import Status
from accord_tpu.primitives.deps import Deps, KeyDeps
from accord_tpu.primitives.keyspace import Keys
from accord_tpu.primitives.timestamp import Ballot, Timestamp, TxnId, TxnKind
from accord_tpu.primitives.txn import Txn
from accord_tpu.sim.cluster import Cluster, ClusterConfig
from accord_tpu.sim.list_store import ListQuery, ListRead, ListUpdate, ListWrite
from accord_tpu.primitives.writes import Writes


def setup_store():
    cluster = Cluster(1, ClusterConfig(num_nodes=1, rf=1, num_shards=1,
                                       stores_per_node=1, progress=False))
    node = cluster.nodes[1]
    return cluster, node, node.command_stores.stores[0]


def mk_txn(keys, value=None):
    k = Keys(keys)
    upd = ListUpdate(k, value) if value is not None else None
    kind = TxnKind.WRITE if value is not None else TxnKind.READ
    return Txn(kind, k, read=ListRead(k), update=upd, query=ListQuery())


def preaccepted(node, store, keys, value=1):
    txn = mk_txn(keys, value)
    txn_id = node.next_txn_id(txn.kind, txn.domain)
    route = node.compute_route(txn)
    partial = txn.slice(store.ranges, include_query=False)
    out = commands.preaccept(store, txn_id, partial, route)
    assert out == AcceptOutcome.SUCCESS
    return txn_id, txn, route


def test_preaccept_fast_path_vote():
    _, node, store = setup_store()
    txn_id, txn, _ = preaccepted(node, store, [5])
    cmd = store.command(txn_id)
    assert cmd.status == Status.PRE_ACCEPTED
    assert cmd.execute_at == txn_id  # uncontended: witnessed at txnId


def test_preaccept_contended_witnesses_later():
    _, node, store = setup_store()
    t1, txn1, _ = preaccepted(node, store, [5])
    # a txn with an OLDER id arriving after t1 was witnessed cannot fast-path
    old_id = TxnId.create(t1.epoch, t1.hlc - 5, 99, TxnKind.WRITE)
    txn2 = mk_txn([5], 2)
    out = commands.preaccept(store, old_id, txn2.slice(store.ranges, False),
                             node.compute_route(txn2))
    assert out == AcceptOutcome.SUCCESS
    cmd = store.command(old_id)
    assert cmd.execute_at > old_id  # witnessed later than its id


def test_preaccept_ballot_rejection():
    _, node, store = setup_store()
    txn_id, txn, route = preaccepted(node, store, [5])
    cmd = store.command(txn_id)
    cmd.promised = Ballot(1, 100, 0, 2)  # a recovery coordinator promised
    out = commands.preaccept(store, txn_id, txn.slice(store.ranges, False), route)
    assert out == AcceptOutcome.REJECTED_BALLOT


def test_deps_calculation_orders_by_txn_id():
    _, node, store = setup_store()
    t1, _, _ = preaccepted(node, store, [5], 1)
    t2, _, _ = preaccepted(node, store, [5], 2)
    deps2 = store.calculate_deps(t2, Keys.of(5), t2)
    assert deps2.for_key(5) == (t1,)
    deps1 = store.calculate_deps(t1, Keys.of(5), t1)
    assert deps1.for_key(5) == ()  # t2 started after t1


def test_read_does_not_witness_read():
    _, node, store = setup_store()
    r1, _, _ = preaccepted(node, store, [5], None)  # read txn
    r2, _, _ = preaccepted(node, store, [5], None)
    w3, _, _ = preaccepted(node, store, [5], 3)
    assert store.calculate_deps(r2, Keys.of(5), r2).for_key(5) == ()
    # the write witnesses both reads
    assert store.calculate_deps(w3, Keys.of(5), w3).for_key(5) == (r1, r2)


def test_execution_waits_for_deps():
    cluster, node, store = setup_store()
    t1, txn1, route1 = preaccepted(node, store, [5], 1)
    t2, txn2, route2 = preaccepted(node, store, [5], 2)
    deps2 = Deps(KeyDeps.of({5: [t1]}))
    # commit t2 (with dep on t1) before t1 commits
    commands.commit(store, t2, route2, txn2.slice(store.ranges, False),
                    t2.as_timestamp(), deps2)
    cmd2 = store.command(t2)
    assert cmd2.status == Status.STABLE
    assert t1 in cmd2.waiting_on.commit
    # commit t1 -> t2 now waits for apply
    commands.commit(store, t1, route1, txn1.slice(store.ranges, False),
                    t1.as_timestamp(), Deps.NONE)
    assert store.command(t1).status == Status.READY_TO_EXECUTE  # no deps
    assert t1 in cmd2.waiting_on.apply and not cmd2.waiting_on.commit
    # apply t1 -> t2 becomes ready
    w1 = Writes(t1, t1.as_timestamp(), Keys.of(5), ListWrite({5: 1}))
    commands.apply(store, t1, route1, txn1.slice(store.ranges, False),
                   t1.as_timestamp(), Deps.NONE, w1, None)
    assert store.command(t1).status == Status.APPLIED
    cluster.drain()  # unblocked executions are deferred through the scheduler
    assert cmd2.status == Status.READY_TO_EXECUTE
    assert node.data_store.snapshot(5) == (1,)


def test_dep_executing_after_is_not_waited_on():
    cluster, node, store = setup_store()
    t1, txn1, route1 = preaccepted(node, store, [5], 1)
    t2, txn2, route2 = preaccepted(node, store, [5], 2)
    # t1 commits with executeAt AFTER t2 (slow path pushed it past t2)
    late = Timestamp(t2.epoch, t2.hlc + 100, 0, 1)
    commands.commit(store, t1, route1, txn1.slice(store.ranges, False),
                    late, Deps.NONE)
    # t2 depends on t1 but t1 executes after t2 -> no wait
    commands.commit(store, t2, route2, txn2.slice(store.ranges, False),
                    t2.as_timestamp(), Deps(KeyDeps.of({5: [t1]})))
    cmd2 = store.command(t2)
    assert cmd2.status == Status.READY_TO_EXECUTE


def test_invalidated_dep_is_dropped():
    cluster, node, store = setup_store()
    t1, txn1, route1 = preaccepted(node, store, [5], 1)
    t2, txn2, route2 = preaccepted(node, store, [5], 2)
    commands.commit(store, t2, route2, txn2.slice(store.ranges, False),
                    t2.as_timestamp(), Deps(KeyDeps.of({5: [t1]})))
    cmd2 = store.command(t2)
    assert t1 in cmd2.waiting_on.commit
    commands.commit_invalidate(store, t1)
    cluster.drain()  # unblocked executions are deferred through the scheduler
    assert cmd2.status == Status.READY_TO_EXECUTE


def test_accept_updates_execute_at():
    _, node, store = setup_store()
    t1, txn1, route1 = preaccepted(node, store, [5], 1)
    ea = Timestamp(t1.epoch, t1.hlc + 50, 0, 2)
    out = commands.accept(store, t1, Ballot.ZERO, route1, Keys.of(5), ea)
    assert out == AcceptOutcome.SUCCESS
    cmd = store.command(t1)
    assert cmd.status == Status.ACCEPTED and cmd.execute_at == ea
    # later preaccept of a new txn must witness a timestamp above ea
    t2, _, _ = preaccepted(node, store, [5], 2)
    cmd2 = store.command(t2)
    assert cmd2.execute_at == t2 or cmd2.execute_at > ea
