"""Sync points, barriers and the ExclusiveSyncPoint floor.

Mirrors the reference's sync-point semantics (coordinate/
CoordinateSyncPoint.java:58, Barrier.java:64, CommandStore.java:301-317):
  - an inclusive sync point captures every conflicting txn started before it
  - a blocking barrier completes only after those deps have applied
  - an ExclusiveSyncPoint advances a reject floor: later-arriving txns with
    older ids are refused and invalidated rather than committed behind it
  - an applied ESP advances RedundantBefore on every owning store
"""
from __future__ import annotations

import pytest

from accord_tpu.coordinate.errors import Invalidated
from accord_tpu.coordinate.syncpoint import Barrier, CoordinateSyncPoint
from accord_tpu.local.status import Status
from accord_tpu.primitives.keyspace import Keys, Range, Ranges
from accord_tpu.primitives.syncpoint import SyncPoint
from accord_tpu.primitives.timestamp import Domain, TxnId, TxnKind
from accord_tpu.primitives.txn import Txn
from accord_tpu.sim.cluster import Cluster, ClusterConfig
from accord_tpu.sim.list_store import ListQuery, ListRead, ListUpdate


def write_txn(keys: Keys, value: int) -> Txn:
    return Txn(TxnKind.WRITE, keys, read=ListRead(keys),
               update=ListUpdate(keys, value), query=ListQuery())


def run(cluster, result, max_events=200_000):
    cluster.drain(max_events)
    cluster.check_no_failures()
    assert result.done, "coordination did not complete"
    return result


def test_inclusive_sync_point_captures_prior_writes():
    cluster = Cluster(seed=42)
    node = cluster.nodes[1]
    keys = Keys([100, 9000])
    r1 = node.coordinate(write_txn(keys, 1))
    cluster.drain()
    sp_result = CoordinateSyncPoint.inclusive(node, keys)
    run(cluster, sp_result)
    sp = sp_result.value()
    assert isinstance(sp, SyncPoint)
    assert sp.sync_id.kind is TxnKind.SYNC_POINT
    # the prior write must be in the waitFor set
    write_id = r1.value().txn_id
    assert sp.wait_for.contains(write_id)


def test_blocking_barrier_waits_for_applies():
    cluster = Cluster(seed=7)
    node = cluster.nodes[2]
    keys = Keys([5, 60000])
    for v in range(1, 4):
        node.coordinate(write_txn(keys, v))
    barrier = Barrier.global_sync(node, keys)
    run(cluster, barrier)
    sp = barrier.value()
    # at barrier completion a quorum has applied the sync point, which can
    # only happen after its deps applied; spot-check the coordinator node
    for store in node.command_stores.all():
        if store.owns(keys):
            cmd = store.command_if_present(sp.sync_id)
            assert cmd is not None and cmd.has_been(Status.APPLIED)
            for dep_id in (cmd.deps.all_txn_ids() if cmd.deps else ()):
                dep = store.command_if_present(dep_id)
                if dep is not None and not dep.status.is_terminal \
                        and store.owns(dep.txn.keys if dep.txn else keys):
                    assert dep.has_been(Status.APPLIED)


def test_local_barrier():
    cluster = Cluster(seed=11)
    node = cluster.nodes[1]
    keys = Keys([1234])
    node.coordinate(write_txn(keys, 9))
    barrier = Barrier.local(node, keys)
    run(cluster, barrier)


def test_exclusive_sync_point_over_ranges():
    cluster = Cluster(seed=13)
    node = cluster.nodes[1]
    ranges = Ranges([Range(0, 1 << 16)])
    for v in range(1, 3):
        node.coordinate(write_txn(Keys([10 + v, 40000 + v]), v))
    sp_result = CoordinateSyncPoint.exclusive(node, ranges)
    run(cluster, sp_result)
    sp = sp_result.value()
    assert sp.sync_id.kind is TxnKind.EXCLUSIVE_SYNC_POINT
    assert sp.sync_id.domain is Domain.RANGE
    # after stabilisation every replica's reject floor covers the ranges
    floors = 0
    for n in cluster.nodes.values():
        for store in n.command_stores.all():
            if store.reject_before.get(100) is not None:
                floors += 1
    assert floors > 0


def test_esp_floor_rejects_older_txn():
    """A txn whose id predates a witnessed ESP must invalidate, not commit."""
    cluster = Cluster(seed=17)
    node = cluster.nodes[1]
    ranges = Ranges([Range(0, 1 << 16)])
    keys = Keys([777])

    # allocate an old txn id NOW (before the ESP) but submit it only after
    old_id = node.next_txn_id(TxnKind.WRITE, Domain.KEY)
    sp_result = CoordinateSyncPoint.exclusive(node, ranges)
    run(cluster, sp_result)
    assert sp_result.value().sync_id > old_id

    late = node.coordinate(write_txn(keys, 999), txn_id=old_id)
    cluster.drain()
    cluster.check_no_failures()
    assert late.done
    assert isinstance(late.failure, Invalidated), f"got {late.failure!r}"
    # and the value 999 must never surface anywhere
    for store in cluster.stores.values():
        for key, entries in store.data.items():
            assert all(v != 999 for _, v in entries)


def test_esp_apply_advances_redundant_before():
    cluster = Cluster(seed=19)
    node = cluster.nodes[1]
    ranges = Ranges([Range(0, 1 << 16)])
    node.coordinate(write_txn(Keys([50, 50000]), 1))
    sp_result = CoordinateSyncPoint.exclusive(node, ranges)
    run(cluster, sp_result)
    sp = sp_result.value()
    # drain the background Apply round; the ESP applies once deps applied
    cluster.drain()
    advanced = 0
    for n in cluster.nodes.values():
        for store in n.command_stores.all():
            cmd = store.command_if_present(sp.sync_id)
            if cmd is not None and cmd.has_been(Status.APPLIED):
                probes = [k for k in (50, 50000) if store.ranges.contains_key(k)]
                if not probes:
                    continue
                assert any(store.redundant_before.get(k) == sp.sync_id.as_timestamp()
                           for k in probes)
                advanced += 1
    assert advanced > 0


def test_wait_until_applied_message():
    """Drive WaitUntilApplied directly: a node replies only after the txn has
    fully applied locally (reference: messages/WaitUntilApplied.java)."""
    from accord_tpu.messages import AppliedOk, WaitUntilApplied
    from accord_tpu.messages.base import Callback

    cluster = Cluster(seed=29)
    node = cluster.nodes[1]
    keys = Keys([42])
    r = node.coordinate(write_txn(keys, 5))
    run(cluster, r)
    txn_id = r.value().txn_id

    got = []

    class Cb(Callback):
        def on_success(self, from_node, reply):
            got.append((from_node, reply))

        def on_failure(self, from_node, failure):
            raise AssertionError(failure)

    for to in (2, 3):
        node.send(to, WaitUntilApplied(txn_id, keys), Cb())
    cluster.drain()
    assert len(got) == 2
    assert all(isinstance(reply, AppliedOk) and reply.txn_id == txn_id
               for _, reply in got)


def test_apply_then_wait_until_applied_teaches_unknown_replica():
    """ApplyThenWaitUntilApplied carries the full decision: a replica that
    never learned the sync point applies it and replies
    (reference: messages/ApplyThenWaitUntilApplied.java)."""
    from accord_tpu.messages import AppliedOk, ApplyThenWaitUntilApplied
    from accord_tpu.messages.base import Callback

    cluster = Cluster(seed=31)
    node = cluster.nodes[1]
    ranges = Ranges([Range(0, 1 << 16)])
    sp_result = CoordinateSyncPoint.exclusive(node, ranges)
    run(cluster, sp_result)
    sp = sp_result.value()
    cluster.drain()

    # simulate a replica that lost all trace of the sync point
    victim = cluster.nodes[3]
    for store in victim.command_stores.all():
        store.commands.pop(sp.sync_id, None)

    got = []

    class Cb(Callback):
        def on_success(self, from_node, reply):
            got.append(reply)

        def on_failure(self, from_node, failure):
            raise AssertionError(failure)

    txn = node.agent.empty_txn(sp.sync_id.kind, sp.seekables)
    node.send(3, ApplyThenWaitUntilApplied(
        sp.sync_id, sp.route, txn, sp.sync_id.as_timestamp(), sp.wait_for), Cb())
    cluster.drain()
    assert len(got) == 1 and isinstance(got[0], AppliedOk)
    for store in victim.command_stores.all():
        cmd = store.command_if_present(sp.sync_id)
        assert cmd is not None and cmd.has_been(Status.APPLIED)


def test_rejection_survives_witness_merge():
    """A rejected witness from one store must not be masked by a later clean
    timestamp from a sibling store (sticky rejection in merge_witnessed)."""
    from accord_tpu.primitives.timestamp import Timestamp

    clean = Timestamp(1, 100, 0, 1)
    rejected = Timestamp(1, 50, 0, 2).as_rejected()
    merged = Timestamp.merge_witnessed(clean, rejected)
    assert merged.is_rejected
    assert merged.hlc == 100  # value is still the max
    merged2 = Timestamp.merge_witnessed(rejected, clean)
    assert merged2.is_rejected


def test_esp_waits_for_later_executing_dep():
    """awaits_only_deps: an ESP whose dep executes AFTER the ESP's id still
    waits for it (reference: PreAccept.java:275-283)."""
    cluster = Cluster(seed=23)
    node = cluster.nodes[1]
    keys = Keys([321])
    # a write that will (very likely) take the fast path and execute quickly
    node.coordinate(write_txn(keys, 1))
    sp_result = CoordinateSyncPoint.exclusive(node, Ranges([Range(0, 1 << 16)]))
    run(cluster, sp_result)
    cluster.drain()
    cluster.check_no_failures()
