"""Protocol megakernel tests (ops/kernels.protocol_tick + megakernel mode
of sim/mesh_burn.ClusterTickEngine): ONE fused device program per cluster
tick -- node-lane resolve, in-kernel finalize compaction, deferred
cmd-plane lanes riding the quorum stage -- against two bit-identical
baselines (the unfused <=2-dispatch merge and the per-node Python loop).
The host twin of cmd_tick's PreAccept lane (CmdPlane.defer_batch) gets its
own unit differential against eval_batch: twin exactness is what reduces
megakernel bit-identity to already-tested kernel equivalences.
"""
from __future__ import annotations

import itertools
import logging

import numpy as np
import pytest

from accord_tpu.sim.mesh_burn import ClusterTickEngine, run_mesh_burn

pytestmark = pytest.mark.megakernel


def _legs(seed, ops, **kw):
    mega, eng = run_mesh_burn(seed, ops, mesh_tick=True, megakernel=True,
                              collect_log=True, **kw)
    unfused, _ = run_mesh_burn(seed, ops, mesh_tick=True,
                               collect_log=True, **kw)
    return mega, eng, unfused


class _RecordingEngine(ClusterTickEngine):
    """Engine that keeps every adopted resolver (for fault-ledger sums)."""

    def __init__(self, **kw):
        super().__init__(**kw)
        self.resolvers = []

    def adopt(self, resolver):
        self.resolvers.append(resolver)
        return super().adopt(resolver)


@pytest.mark.parametrize("seed,ops,ratios", [
    pytest.param(9, 40, dict(range_read_ratio=0.2, range_write_ratio=0.1),
                 id="key+range"),
    pytest.param(4, 80, {}, id="key-only-long", marks=pytest.mark.slow),
])
def test_megakernel_vs_unfused_differential(seed, ops, ratios):
    """Key + range traffic: the fused program commits the exact event log
    of the unfused merge AND the per-node loop, with every dispatching
    tick costing exactly ONE device program launch. (The tier-1 leg mixes
    key and range plans; the longer key-only soak rides the slow lane.)"""
    mega, eng, unfused = _legs(seed, ops, nodes=4, **ratios)
    loop, _ = run_mesh_burn(seed, ops, mesh_tick=False,
                            collect_log=True, nodes=4, **ratios)
    assert mega.log == unfused.log, f"seed {seed}: fused != unfused"
    assert mega.log == loop.log, f"seed {seed}: fused != loop"
    snap = eng.snapshot()
    assert snap["megakernel_dispatches"] > 0
    assert snap["launches_per_tick"] == 1.0, snap
    assert snap["mesh_tick_fallbacks"] == 0


@pytest.mark.parametrize("auth,ops", [
    pytest.param(True, 32, id="authoritative"),
    pytest.param(False, 60, id="advisory-long", marks=pytest.mark.slow),
])
def test_megakernel_cmd_plane_differential(auth, ops):
    """With the device command plane on (and in authoritative mode), the
    drains defer PreAccept spans to the host twin; histories must stay
    bit-identical to the unfused path that dispatches cmd_tick spans
    synchronously, and the deferred lanes must actually reach the fused
    quorum stage."""
    kw = dict(nodes=3, cmd_plane=True, cmd_plane_authoritative=auth)
    mega, eng, unfused = _legs(13, ops, **kw)
    assert mega.log == unfused.log, f"authoritative={auth} diverged"
    snap = eng.snapshot()
    assert snap["launches_per_tick"] == 1.0, snap
    assert snap["fastpath_quorum_txns"] > 0, \
        "no deferred PreAccept lane met the in-kernel quorum"


def test_defer_batch_twin_matches_eval_batch():
    """The host integer twin of cmd_tick's PreAccept lane: defer_batch
    must return the exact results (outcome, status, executeAt) and leave
    the exact shadow/clock state of eval_batch on an identical store,
    without a single device dispatch -- including redundant re-delivery,
    ballot contention, and mixed batches whose non-PreAccept ops flush
    the span to the host handler in order."""
    from accord_tpu.ops.cmd_plane import CmdOp
    from accord_tpu.primitives.deps import Deps
    from accord_tpu.primitives.timestamp import Ballot
    from tests.test_cmd_plane import _env, _mk_txn, _snap

    def _run(defer):
        _cluster, node, store = _env(True)
        plane = store.cmd_plane
        lanes = []
        sink = lambda t, s, c: lanes.append((t.copy(), s.copy(), c.copy()))  # noqa: E731
        txns = []
        for i in range(6):
            txn = _mk_txn([1 + (i % 4), 5], i + 1)
            tid = node.next_txn_id(txn.kind, txn.domain)
            txns.append((tid, txn, node.compute_route(txn)))
        part = lambda t: t.slice(store.ranges, include_query=False)  # noqa: E731
        ev = (lambda b: plane.defer_batch(b, sink=sink)) if defer \
            else plane.eval_batch
        out = []

        def run(batch):
            out.append([(r.outcome,
                         int(r.status) if r.status is not None else None,
                         r.execute_at) for r in ev(batch)])

        # span 1: fresh preaccepts witnessing each other
        run([CmdOp.preaccept(t, part(x), r) for t, x, r in txns[:4]])
        # span 2: redundant re-delivery + ballot contention + fresh
        run([
            CmdOp.preaccept(txns[0][0], part(txns[0][1]), txns[0][2]),
            CmdOp.preaccept(txns[1][0], part(txns[1][1]), txns[1][2],
                            Ballot(1, 5, 0, 1)),
            CmdOp.preaccept(txns[4][0], part(txns[4][1]), txns[4][2]),
        ])
        # span 3: a commit mid-batch flushes the pending span to the host
        # handler in order
        ea = store.command_if_present(txns[2][0]).execute_at
        run([
            CmdOp.preaccept(txns[5][0], part(txns[5][1]), txns[5][2]),
            CmdOp.commit(txns[2][0], txns[2][2], part(txns[2][1]), ea,
                         Deps.NONE),
            CmdOp.preaccept(txns[3][0], part(txns[3][1]), txns[3][2],
                            Ballot(1, 2, 0, 1)),
        ])
        snaps = [_snap(store, node, t) for t, _, _ in txns]
        return out, snaps, plane, lanes

    dev_out, dev_snaps, dev_plane, _ = _run(defer=False)
    twin_out, twin_snaps, twin_plane, lanes = _run(defer=True)
    assert twin_out == dev_out
    assert twin_snaps == dev_snaps
    # device-honest counters: every PreAccept span rode the twin, and the
    # ONLY device dispatch is the mid-batch commit (eval_batch puts it on
    # device, so the twin must too -- host and device Commit handlers
    # differ observably)
    assert int(twin_plane.dispatches) == 1
    assert int(twin_plane.deferred_spans) >= 3
    assert int(twin_plane.deferred_ops) >= 9
    assert int(dev_plane.dispatches) > int(twin_plane.dispatches)
    # the sink lanes mirror the span results: every lane carries (txn, ts)
    # triples plus an outcome code for the fused quorum stage
    assert lanes, "defer_batch never emitted quorum lanes"
    for q_txn, q_ts, q_code in lanes:
        assert q_txn.shape == q_ts.shape and q_txn.shape[1] == 3
        assert q_code.shape[0] == q_txn.shape[0]


def test_protocol_tick_quorum_count():
    """Unit check of the fused quorum stage: votes count SUCCESS lanes
    that echoed their txn id, per distinct txn, against the electorate
    majority; padding and failed lanes are excluded."""
    import jax.numpy as jnp

    from accord_tpu.ops.kernels import protocol_tick

    t1, t2 = (1, 10, 3), (1, 11, 4)
    txn = np.array([t1, t1, t2, t1, (0, 0, 0)], np.int32)
    ts = np.array([t1, t1, t2, (1, 99, 5), (0, 0, 0)], np.int32)
    #      fast    fast   fast   slow-path  pad
    code = np.array([0, 0, 0, 0, 0], np.int32)
    valid = np.array([True, True, True, True, False])
    table = jnp.zeros((8, 8), jnp.bfloat16)
    fast, votes, met = protocol_tick(
        table, quorum=(jnp.asarray(txn), jnp.asarray(ts),
                       jnp.asarray(code), jnp.asarray(valid)),
        quorum_size=2)[4]
    fast, votes, met = (np.asarray(fast), np.asarray(votes),
                        np.asarray(met))
    assert fast.tolist() == [True, True, True, False, False]
    assert votes.tolist()[:3] == [2, 2, 1]
    assert met.tolist() == [True, True, False, False, False]


@pytest.mark.slow
def test_compaction_pin_isolation_megakernel():
    """Tiny arenas force growth/compaction mid-burn: each plan's fused
    inputs are the encode-time snapshot arrays, so arena churn between
    encode and the fused launch must not perturb any sibling plan's
    in-kernel demux span."""
    mega, eng, unfused = _legs(17, 80, nodes=4, key_count=96,
                               resolver_kwargs=dict(initial_cap=128))
    assert mega.acked == unfused.acked == 80
    assert mega.log == unfused.log, \
        "arena churn leaked across plans inside the fused program"
    assert eng.snapshot()["launches_per_tick"] == 1.0


@pytest.mark.chaos
@pytest.mark.slow
def test_megakernel_chaos_parity_and_checksum_fallback():
    """Device-plane fault injection under the megakernel: fault draws ride
    the stock per-plan _launch order, corruption lands on each plan's
    OWN host copy of the shared readback (MergedView returns copies), and
    every corrupted finalize lane is caught by the checksum word computed
    INSIDE the fused program. History is bit-identical to the chaos-free
    run and to the unfused merge under the same chaos schedule."""
    rates = {"dispatch_exc_rate": 0.05, "stuck_rate": 0.05,
             "corrupt_rate": 0.10, "overflow_rate": 0.03}
    kw = dict(nodes=4, key_count=16, write_ratio=0.7)

    def leg(megakernel, chaos):
        eng = _RecordingEngine(mesh_tick=True, megakernel=megakernel)
        rep, _ = run_mesh_burn(23, 80, engine=eng, collect_log=True,
                               device_chaos=chaos,
                               device_fault_rates=rates if chaos else None,
                               **kw)
        return rep, eng

    mega_chaos, eng = leg(True, True)
    mega_clean, _ = leg(True, False)
    unfused_chaos, ueng = leg(False, True)
    assert mega_chaos.log == mega_clean.log, \
        "injected faults leaked into the fused tick's committed history"
    assert mega_chaos.log == unfused_chaos.log, \
        "chaos handling diverged between fused and unfused dispatch"
    inj = mega_chaos.device_faults
    assert inj["corrupt"] > 0, "corrupt draws never fired; rates too low"
    mism = sum(r.checksum_mismatches for r in eng.resolvers)
    assert mism == inj["corrupt"], (mism, inj)
    assert mism == sum(r.checksum_mismatches for r in ueng.resolvers) \
        or unfused_chaos.device_faults["corrupt"] == inj["corrupt"]


def test_mixed_resolver_config_falls_back_warns_once(caplog):
    """Satellite: a cluster whose resolvers disagree on num_buckets cannot
    merge those plans -- they launch unfused (counted in
    mesh_tick_fallbacks), the engine logs the config mismatch ONCE per
    signature, and the committed history is still bit-identical to the
    per-node loop over the same mixed factory."""
    from accord_tpu.ops.resolver import BatchDepsResolver
    from accord_tpu.sim.burn import run_burn
    from accord_tpu.sim.cluster import ClusterConfig

    def burn(megakernel, mesh_tick=True):
        eng = ClusterTickEngine(mesh_tick=mesh_tick, megakernel=megakernel)
        eng.quorum_size = 2
        counter = itertools.count()

        def factory():
            nb = 128 if next(counter) % 2 == 0 else 256
            return eng.adopt(BatchDepsResolver(num_buckets=nb))

        cfg = ClusterConfig(num_nodes=4, rf=3, num_shards=4,
                            stores_per_node=2,
                            deps_resolver_factory=factory,
                            deps_batch_window_ms=2.0,
                            device_latency_ms=4.0)
        rep = run_burn(29, 40, nodes=4, rf=3, key_count=32, concurrency=12,
                       config=cfg, collect_log=True)
        return rep, eng

    with caplog.at_level(logging.WARNING, "accord_tpu.sim.mesh_burn"):
        mega, eng = burn(True)
    # warn-once is per engine: assert on the fused burn's records before
    # the baseline burns mint their own engines (and their own warnings)
    warns = [r for r in caplog.records if "cannot merge" in r.message]
    sigs = {(r.args[0], r.args[1], r.args[2], r.args[3]) for r in warns}
    assert warns, "heterogeneous config never logged"
    assert len(warns) == len(sigs), "config mismatch logged more than once"
    loop, _ = burn(False, mesh_tick=False)
    mesh, _ = burn(False)
    assert mega.log == loop.log, "mixed-config fused burn diverged"
    assert mesh.log == loop.log
    assert eng.snapshot()["mesh_tick_fallbacks"] > 0


@pytest.mark.slow
def test_megakernel_64_nodes_reconcile():
    """The acceptance-bar case: 64 nodes, one fused program per tick,
    bit-identical to the unfused merge, reconcilable with itself, and
    launches_per_tick exactly 1.0 across the whole burn."""
    kw = dict(nodes=64, concurrency=24)
    mega, eng, unfused = _legs(3, 120, **kw)
    assert mega.acked == unfused.acked == 120
    assert mega.log == unfused.log
    again, eng2 = run_mesh_burn(3, 120, mesh_tick=True, megakernel=True,
                                collect_log=True, **kw)
    assert mega.log == again.log, "megakernel burn is not reconcilable"
    for e in (eng, eng2):
        snap = e.snapshot()
        assert snap["launches_per_tick"] == 1.0, snap
        assert snap["megakernel_dispatches"] == snap["cluster_ticks"] or \
            snap["megakernel_dispatches"] > 0
