"""The verifier must catch injected violations -- otherwise burn green means
nothing (the reference validates its checkers the same way)."""
import pytest

from accord_tpu.sim.verifier import HistoryViolation, StrictSerializabilityVerifier


def mk():
    v = StrictSerializabilityVerifier()
    for val, t in [(1, 10), (2, 20), (3, 30)]:
        v.on_issue_write(val, t)
    return v


def test_accepts_consistent_history():
    v = mk()
    v.witness(10, 15, {"k": ()}, {"k": 1})
    v.witness(20, 25, {"k": (1,)}, {"k": 2})
    v.witness(30, 35, {"k": (1, 2)}, {"k": 3})
    v.check_final_state({"k": (1, 2, 3)})


def test_rejects_divergent_order():
    v = mk()
    v.witness(40, 45, {"k": (1, 2)}, {})
    with pytest.raises(HistoryViolation, match="divergent"):
        v.witness(42, 55, {"k": (2, 1)}, {})


def test_rejects_own_write_observed():
    v = mk()
    with pytest.raises(HistoryViolation, match="own write"):
        v.witness(10, 15, {"k": (1,)}, {"k": 1})


def test_rejects_unknown_value():
    v = mk()
    with pytest.raises(HistoryViolation, match="unknown value"):
        v.witness(10, 15, {"k": (99,)}, {})


def test_rejects_stale_read_after_completed_read():
    v = mk()
    # txn A completed at 45 having observed (1, 2)
    v.witness(40, 45, {"k": (1, 2)}, {})
    # txn B started at 50 (> 45) but observed less -> real-time violation
    with pytest.raises(HistoryViolation, match="missing writes"):
        v.witness(50, 55, {"k": (1,)}, {})


def test_rejects_invisible_acked_write():
    v = mk()
    v.witness(10, 15, {}, {"k": 1})  # ack'd write of 1 completed at 15
    with pytest.raises(HistoryViolation, match="not visible"):
        v.witness(20, 25, {"k": ()}, {})


def test_concurrent_reads_may_be_stale():
    v = mk()
    # overlapping txns: B started before A completed -> no real-time edge
    v.witness(40, 60, {"k": (1, 2)}, {})
    v.witness(50, 70, {"k": (1,)}, {})  # fine: started at 50 < 60


def test_rejects_lost_acked_write():
    v = mk()
    v.witness(10, 15, {}, {"k": 2})
    with pytest.raises(HistoryViolation, match="missing from final state"):
        v.check_final_state({"k": (1, 3)})


def test_rejects_final_divergence():
    v = mk()
    v.witness(40, 45, {"k": (1, 2)}, {})
    with pytest.raises(HistoryViolation, match="diverges|shorter"):
        v.check_final_state({"k": (2, 1)})


def test_rejects_cross_key_cycle():
    # classic G-single shape: two concurrent readers observe opposite
    # orderings of two independent writes -- per-key prefixes are fine, only
    # the cross-key happens-before closure can catch it (the reference's
    # max-predecessor graph, verify/StrictSerializabilityVerifier.java:58)
    v = StrictSerializabilityVerifier()
    v.on_issue_write(1, 5)
    v.on_issue_write(2, 5)
    v.witness(10, 90, {"a": (1,), "b": ()}, {})
    with pytest.raises(HistoryViolation, match="cycle"):
        v.witness(11, 91, {"b": (2,), "a": ()}, {})


def test_accepts_concurrent_consistent_snapshots():
    v = StrictSerializabilityVerifier()
    v.on_issue_write(1, 5)
    v.on_issue_write(2, 5)
    v.witness(10, 90, {"a": (1,), "b": ()}, {})
    v.witness(11, 91, {"a": (1,), "b": ()}, {})
    v.witness(12, 92, {"a": (1,), "b": (2,)}, {})
    v.witness(13, 93, {"a": (), "b": ()}, {})  # concurrent: may be behind
    v.check_final_state({"a": (1,), "b": (2,)})


def test_rejects_mutual_write_visibility():
    # T observes U's write and U observes T's write: not serializable even
    # though each key's order alone is consistent
    v = StrictSerializabilityVerifier()
    v.on_issue_write(1, 5)
    v.on_issue_write(2, 5)
    v.witness(10, 90, {"a": (), "b": (2,)}, {"a": 1})
    with pytest.raises(HistoryViolation, match="cycle"):
        v.witness(11, 91, {"b": (), "a": (1,)}, {"b": 2})


def test_accepts_multikey_writes():
    v = StrictSerializabilityVerifier()
    for val in (1, 2, 3):
        v.on_issue_write(val, 5)
    v.witness(10, 20, {"a": (), "b": ()}, {"a": 1, "b": 1})
    v.witness(30, 40, {"a": (1,), "b": (1,)}, {"a": 2, "b": 2})
    v.witness(50, 60, {"a": (1, 2), "b": (1, 2)}, {})
    v.check_final_state({"a": (1, 2), "b": (1, 2)})


def test_rejects_interleaved_multikey_writes():
    # two concurrent multi-key writes that land in OPPOSITE orders on the
    # two keys: per-key orders are fine, the interleaving is not
    v = StrictSerializabilityVerifier()
    v.on_issue_write(1, 5)
    v.on_issue_write(2, 5)
    v.witness(10, 90, {"a": (), "b": (2,)}, {"a": 1, "b": 1})
    with pytest.raises(HistoryViolation, match="cycle"):
        v.witness(11, 91, {"a": (1,), "b": ()}, {"a": 2, "b": 2})


def test_blind_write_position_resolved_via_later_read():
    v = StrictSerializabilityVerifier()
    v.on_issue_write(1, 5)
    v.on_issue_write(2, 6)
    v.witness(10, 20, {}, {"a": 1})        # blind write: position deferred
    v.witness(30, 40, {"a": (1,)}, {})     # resolves it to index 0
    v.check_final_state({"a": (1,)})


def test_blind_write_resolved_at_final_state():
    v = StrictSerializabilityVerifier()
    v.on_issue_write(1, 5)
    v.witness(10, 20, {}, {"a": 1})
    v.check_final_state({"a": (1,)})


def test_rejects_duplicate_position_claim():
    # lost update: two concurrent writers both read () so both claim list
    # index 0 -- impossible in any serial order
    v = StrictSerializabilityVerifier()
    v.on_issue_write(1, 5)
    v.on_issue_write(2, 5)
    v.witness(10, 90, {"a": ()}, {"a": 1})
    with pytest.raises(HistoryViolation, match="both claim"):
        v.witness(11, 91, {"a": ()}, {"a": 2})


def test_rejects_claim_contradicting_order():
    # writer read () claiming index 0, but the observed order puts its value
    # at index 1
    v = StrictSerializabilityVerifier()
    v.on_issue_write(1, 5)
    v.on_issue_write(2, 5)
    v.witness(10, 90, {"a": ()}, {"a": 2})
    with pytest.raises(HistoryViolation, match="claim"):
        v.witness(50, 95, {"a": (1, 2)}, {})


def test_blind_write_resolved_immediately_if_already_observed():
    v = StrictSerializabilityVerifier()
    v.on_issue_write(1, 5)
    v.witness(10, 20, {"a": (1,)}, {})   # reader observes value first
    v.witness(11, 30, {}, {"a": 1})      # blind writer witnessed later
    assert not v._pending                # resolved at witness time
    v.check_final_state({"a": (1,)})
