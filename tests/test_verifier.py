"""The verifier must catch injected violations -- otherwise burn green means
nothing (the reference validates its checkers the same way)."""
import pytest

from accord_tpu.sim.verifier import HistoryViolation, StrictSerializabilityVerifier


def mk():
    v = StrictSerializabilityVerifier()
    for val, t in [(1, 10), (2, 20), (3, 30)]:
        v.on_issue_write(val, t)
    return v


def test_accepts_consistent_history():
    v = mk()
    v.witness(10, 15, {"k": ()}, {"k": 1})
    v.witness(20, 25, {"k": (1,)}, {"k": 2})
    v.witness(30, 35, {"k": (1, 2)}, {"k": 3})
    v.check_final_state({"k": (1, 2, 3)})


def test_rejects_divergent_order():
    v = mk()
    v.witness(40, 45, {"k": (1, 2)}, {})
    with pytest.raises(HistoryViolation, match="divergent"):
        v.witness(42, 55, {"k": (2, 1)}, {})


def test_rejects_own_write_observed():
    v = mk()
    with pytest.raises(HistoryViolation, match="own write"):
        v.witness(10, 15, {"k": (1,)}, {"k": 1})


def test_rejects_unknown_value():
    v = mk()
    with pytest.raises(HistoryViolation, match="unknown value"):
        v.witness(10, 15, {"k": (99,)}, {})


def test_rejects_stale_read_after_completed_read():
    v = mk()
    # txn A completed at 45 having observed (1, 2)
    v.witness(40, 45, {"k": (1, 2)}, {})
    # txn B started at 50 (> 45) but observed less -> real-time violation
    with pytest.raises(HistoryViolation, match="missing writes"):
        v.witness(50, 55, {"k": (1,)}, {})


def test_rejects_invisible_acked_write():
    v = mk()
    v.witness(10, 15, {}, {"k": 1})  # ack'd write of 1 completed at 15
    with pytest.raises(HistoryViolation, match="not visible"):
        v.witness(20, 25, {"k": ()}, {})


def test_concurrent_reads_may_be_stale():
    v = mk()
    # overlapping txns: B started before A completed -> no real-time edge
    v.witness(40, 60, {"k": (1, 2)}, {})
    v.witness(50, 70, {"k": (1,)}, {})  # fine: started at 50 < 60


def test_rejects_lost_acked_write():
    v = mk()
    v.witness(10, 15, {}, {"k": 2})
    with pytest.raises(HistoryViolation, match="missing from final state"):
        v.check_final_state({"k": (1, 3)})


def test_rejects_final_divergence():
    v = mk()
    v.witness(40, 45, {"k": (1, 2)}, {})
    with pytest.raises(HistoryViolation, match="diverges|shorter"):
        v.check_final_state({"k": (2, 1)})
