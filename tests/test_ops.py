"""TPU data-plane kernels: unit tests vs naive models + differential tests
against the host deps scan (runs on the CPU backend; the same jitted code
runs on TPU)."""
import numpy as np
import pytest

from accord_tpu.ops.encoding import TimestampEncoder, WITNESS_TABLE, encode_key_bitmaps
from accord_tpu.primitives.timestamp import Timestamp, TxnId, TxnKind


def test_witness_table_matches_kinds():
    for a in TxnKind:
        for b in TxnKind:
            assert WITNESS_TABLE[int(a), int(b)] == (1 if a.witnesses(b) else 0)


def test_timestamp_encoder_roundtrip_order():
    tss = [Timestamp(1 + i % 2, 1000 + i * 7, i % 3, i % 5) for i in range(50)]
    enc = TimestampEncoder.for_timestamps(tss)
    arr = enc.encode(tss)
    # lexicographic order over the 3 lanes must match timestamp order
    idx = sorted(range(len(tss)), key=lambda i: tuple(arr[i]))
    assert [tss[i] for i in idx] == sorted(tss)


def test_timestamp_encoder_epoch_lane():
    # later epoch with SMALLER hlc must still sort after earlier epoch
    tss = [Timestamp(1, 500, 0, 1), Timestamp(2, 100, 0, 1), Timestamp(2, 600, 0, 2)]
    enc = TimestampEncoder.for_timestamps(tss)
    arr = enc.encode(tss)
    assert tuple(arr[0]) < tuple(arr[1]) < tuple(arr[2])
    far = Timestamp(1, 500 + (1 << 32), 0, 1)
    assert not enc.in_window(far)
    with pytest.raises(ValueError):
        enc.encode([far])


def test_deps_matrix_vs_naive():
    import jax.numpy as jnp
    from accord_tpu.ops.kernels import deps_matrix
    rng = np.random.default_rng(0)
    B, A, K = 5, 16, 128
    sb = (rng.random((B, K)) < 0.05).astype(np.float32)
    ab = (rng.random((A, K)) < 0.05).astype(np.float32)
    s_before = rng.integers(0, 10, (B, 3)).astype(np.int32)
    a_ts = rng.integers(0, 10, (A, 3)).astype(np.int32)
    s_kinds = rng.integers(0, 5, B).astype(np.int32)
    a_kinds = rng.integers(0, 5, A).astype(np.int32)
    valid = rng.random(A) < 0.9
    got = np.asarray(deps_matrix(jnp.asarray(sb), jnp.asarray(s_before),
                                 jnp.asarray(s_kinds), jnp.asarray(ab),
                                 jnp.asarray(a_ts), jnp.asarray(a_kinds),
                                 jnp.asarray(valid), jnp.asarray(WITNESS_TABLE)))
    for b in range(B):
        for a in range(A):
            expect = (bool((sb[b] * ab[a]).sum() > 0)
                      and WITNESS_TABLE[s_kinds[b], a_kinds[a]] == 1
                      and (tuple(a_ts[a]) < tuple(s_before[b]))
                      and bool(valid[a]))
            assert got[b, a] == expect, (b, a)


def test_transitive_closure():
    import jax.numpy as jnp
    from accord_tpu.ops.kernels import transitive_closure
    # chain 0 <- 1 <- 2 <- 3 (i depends on i-1)
    n = 8
    adj = np.zeros((n, n), dtype=bool)
    for i in range(1, 4):
        adj[i, i - 1] = True
    closed = np.asarray(transitive_closure(jnp.asarray(adj), 3))
    assert closed[3, 0] and closed[3, 1] and closed[3, 2]
    assert closed[2, 0] and not closed[0, 3]
    assert not closed[4].any()


def test_execution_wavefronts():
    import jax.numpy as jnp
    from accord_tpu.ops.kernels import execution_wavefronts
    # diamond: 1,2 depend on 0; 3 depends on 1 and 2
    adj = np.zeros((8, 8), dtype=bool)
    adj[1, 0] = adj[2, 0] = adj[3, 1] = adj[3, 2] = True
    levels = np.asarray(execution_wavefronts(jnp.asarray(adj), 8))
    assert levels[0] == 0 and levels[1] == 1 and levels[2] == 1 and levels[3] == 2


def _preaccept_population(store, node, keys_list):
    from accord_tpu.local import commands
    from accord_tpu.primitives.keyspace import Keys
    from tests.test_local_engine import mk_txn
    ids = []
    for i, keys in enumerate(keys_list):
        txn = mk_txn(keys, i + 1)
        txn_id = node.next_txn_id(txn.kind, txn.domain)
        commands.preaccept(store, txn_id, txn.slice(store.ranges, False),
                           node.compute_route(txn))
        ids.append(txn_id)
    return ids


def test_batch_resolver_differential_vs_host():
    """The device resolver must return EXACTLY the host scan's deps."""
    from accord_tpu.ops.resolver import BatchDepsResolver
    from accord_tpu.primitives.keyspace import Keys
    from tests.test_local_engine import setup_store
    rng = np.random.default_rng(7)
    _, node, store = setup_store()
    keys_list = [sorted(set(rng.integers(0, 40, rng.integers(1, 4)).tolist()))
                 for _ in range(60)]
    ids = _preaccept_population(store, node, keys_list)
    resolver = BatchDepsResolver(num_buckets=128)  # buckets < domain: collisions exercised
    for i in rng.choice(len(ids), 20, replace=False):
        subject = ids[i]
        keys = Keys(keys_list[i])
        bound = store.command(subject).execute_at
        host = store.host_calculate_deps(subject, keys, bound)
        dev = resolver.resolve_one(store, subject, keys, bound)
        assert dev == host, f"subject {subject}: {dev} != {host}"


def test_burn_with_device_resolver_matches_host():
    """End-to-end differential in INLINE mode (batch window None): the device
    path answers every query synchronously with exactly the host scan's
    results, so the two event logs must be bit-identical."""
    from accord_tpu.ops.resolver import BatchDepsResolver
    from accord_tpu.sim.burn import run_burn
    from accord_tpu.sim.cluster import ClusterConfig

    host = run_burn(seed=11, ops=40, collect_log=True)
    dev = run_burn(seed=11, ops=40, collect_log=True,
                   config=ClusterConfig(
                       deps_resolver_factory=lambda: BatchDepsResolver(num_buckets=128),
                       deps_batch_window_ms=None))
    assert host.acked == dev.acked == 40
    assert host.log == dev.log


def test_burn_with_batched_device_resolver():
    """End-to-end with the micro-batch tick ON: replies defer to the per-store
    tick, so timing (and thus logs) may differ from host -- but every op still
    acks and strict serializability + convergence hold (checked inside
    run_burn), and the run is deterministic."""
    from accord_tpu.ops.resolver import BatchDepsResolver
    from accord_tpu.sim.burn import run_burn
    from accord_tpu.sim.cluster import ClusterConfig

    def cfg():
        return ClusterConfig(
            deps_resolver_factory=lambda: BatchDepsResolver(num_buckets=128),
            deps_batch_window_ms=0.0)

    a = run_burn(seed=11, ops=40, collect_log=True, config=cfg())
    assert a.acked == 40 and a.lost == 0
    b = run_burn(seed=11, ops=40, collect_log=True, config=cfg())
    assert a.log == b.log  # deterministic under batching


def test_batch_resolver_dense_conflicts_vs_host():
    """Subjects with dependency counts in the hundreds (everything conflicts)
    must still decode exactly from the bit-packed kernel result."""
    from accord_tpu.ops.resolver import BatchDepsResolver
    from accord_tpu.primitives.keyspace import Keys
    from tests.test_local_engine import setup_store
    _, node, store = setup_store()
    # 150 txns all on one key: every subject conflicts with every earlier one
    keys_list = [[0, 1] for _ in range(150)]
    ids = _preaccept_population(store, node, keys_list)
    resolver = BatchDepsResolver(num_buckets=128)
    for i in (120, 130, 149):
        subject = ids[i]
        keys = Keys(keys_list[i])
        bound = store.command(subject).execute_at
        host = store.host_calculate_deps(subject, keys, bound)
        dev = resolver.resolve_one(store, subject, keys, bound)
        assert dev == host, f"subject {subject}"
        assert len(host.key_deps.all_txn_ids()) > 64  # genuinely dense


def test_max_conflict_batch_vs_host():
    """Device max-conflict must agree with the host MaxConflicts scan."""
    from accord_tpu.ops.resolver import BatchDepsResolver
    from accord_tpu.primitives.keyspace import Keys
    from tests.test_local_engine import setup_store
    rng = np.random.default_rng(13)
    _, node, store = setup_store()
    keys_list = [sorted(set(rng.integers(0, 40, rng.integers(1, 4)).tolist()))
                 for _ in range(50)]
    ids = _preaccept_population(store, node, keys_list)
    resolver = BatchDepsResolver(num_buckets=128)
    subjects = []
    for i in rng.choice(len(ids), 15, replace=False):
        subjects.append((ids[i], Keys(keys_list[i])))
    got = resolver.max_conflict_batch(store, subjects)
    for (subj, keys), (handled, ts) in zip(subjects, got):
        host = store.max_conflict_ts(keys)
        if handled:
            assert ts == host, f"{subj}: device {ts} != host {host}"
        else:
            # bucket-collision fallback: the host path is consulted instead
            assert host is not None
