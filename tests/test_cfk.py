"""CommandsForKey unit edge cases: update monotonicity, prune boundaries,
conflict-scan filters (reference: the cfk update/prune unit coverage around
local/cfk/CommandsForKey.java:910, Pruning.java:41)."""
from __future__ import annotations

from accord_tpu.local.cfk import CfkStatus, CommandsForKey
from accord_tpu.primitives.timestamp import Timestamp, TxnId, TxnKind


def tid(hlc, node=1, kind=TxnKind.WRITE):
    return TxnId.create(1, hlc, node, kind)


def ts(hlc):
    return Timestamp(1, hlc, 0, 9)


def test_update_is_status_monotone():
    cfk = CommandsForKey(7)
    t = tid(5)
    cfk.update(t, CfkStatus.COMMITTED, ts(8))
    cfk.update(t, CfkStatus.WITNESSED, None)  # stale report must not regress
    assert cfk.get(t).status == CfkStatus.COMMITTED
    assert cfk.get(t).execute_at == ts(8)
    cfk.update(t, CfkStatus.APPLIED, ts(8))
    assert cfk.get(t).status == CfkStatus.APPLIED


def test_max_applied_write_tracks_only_writes():
    cfk = CommandsForKey(7)
    cfk.update(tid(5, kind=TxnKind.READ), CfkStatus.APPLIED, ts(6))
    assert cfk.max_applied_write is None
    cfk.update(tid(7), CfkStatus.APPLIED, ts(9))
    assert cfk.max_applied_write == ts(9)
    cfk.update(tid(8), CfkStatus.APPLIED, ts(8))  # lower executeAt: no regress
    assert cfk.max_applied_write == ts(9)


def test_prune_keeps_unapplied_and_straddlers():
    """Only APPLIED/INVALIDATED entries WHOLLY below the floor are pruned: a
    txn id below the floor whose executeAt landed above it must survive (its
    ordering is not subsumed by the floor dep)."""
    cfk = CommandsForKey(7)
    done_low = tid(2)
    cfk.update(done_low, CfkStatus.APPLIED, ts(3))
    straddler = tid(4)
    cfk.update(straddler, CfkStatus.APPLIED, ts(50))     # executeAt above floor
    unapplied = tid(5)
    cfk.update(unapplied, CfkStatus.COMMITTED, ts(6))    # not yet applied
    invalidated = tid(6)
    cfk.update(invalidated, CfkStatus.INVALIDATED, None)
    above = tid(40)
    cfk.update(above, CfkStatus.APPLIED, ts(41))

    pruned = cfk.prune_below(ts(10))
    assert set(pruned) == {done_low, invalidated}
    assert cfk.get(done_low) is None
    assert cfk.get(straddler) is not None, "straddler pruned"
    assert cfk.get(unapplied) is not None, "unapplied entry pruned"
    assert cfk.get(above) is not None

    # pruning is idempotent
    assert cfk.prune_below(ts(10)) == []


def test_conflicts_before_filters():
    """The deps scan excludes the subject itself, invalidated entries, ids at
    or above the bound, and kinds the subject does not witness."""
    cfk = CommandsForKey(7)
    w1, w2 = tid(2), tid(4)
    r1 = tid(3, kind=TxnKind.READ)
    dead = tid(5)
    cfk.update(w1, CfkStatus.COMMITTED, ts(2))
    cfk.update(w2, CfkStatus.WITNESSED, None)
    cfk.update(r1, CfkStatus.COMMITTED, ts(3))
    cfk.update(dead, CfkStatus.INVALIDATED, None)

    subject_w = tid(9)
    got = tuple(cfk.conflicts_before(subject_w, ts(100)))
    # a write witnesses both reads and writes; the invalidated id is skipped
    assert got == (w1, r1, w2)

    subject_r = tid(9, kind=TxnKind.READ)
    got_r = tuple(cfk.conflicts_before(subject_r, ts(100)))
    # a read witnesses only writes
    assert got_r == (w1, w2)

    # the bound is exclusive and cuts by txn id
    assert tuple(cfk.conflicts_before(subject_w, tid(4).as_timestamp())) \
        == (w1, r1)
    # the subject never witnesses itself
    assert w2 not in tuple(cfk.conflicts_before(w2, ts(100)))


def test_rewitness_after_prune_recreates_entry():
    """A pruned id re-reported (e.g. by a straggler's late Commit replay)
    re-enters the registry -- prune is a space decision, not a truth one; the
    caller-side floor injection keeps the dep ordering correct."""
    cfk = CommandsForKey(7)
    t = tid(2)
    cfk.update(t, CfkStatus.APPLIED, ts(3))
    assert cfk.prune_below(ts(10)) == [t]
    cfk.update(t, CfkStatus.APPLIED, ts(3))
    assert cfk.get(t) is not None
    assert cfk.prune_below(ts(10)) == [t]


def test_max_conflict_prefers_execute_at():
    cfk = CommandsForKey(7)
    t = tid(5)
    cfk.update(t, CfkStatus.COMMITTED, ts(30))  # executeAt far above id
    assert cfk.max_conflict(TxnKind.WRITE) == ts(30)
    dead = tid(50)
    cfk.update(dead, CfkStatus.INVALIDATED, None)
    assert cfk.max_conflict(TxnKind.WRITE) == ts(30), \
        "invalidated entry contributed to max conflict"
