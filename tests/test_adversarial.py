"""The adversarial simulator: async store-op delays, per-node clock drift,
and protocol fault flags -- alone and combined with chaos/churn (reference:
DelayedCommandStores.java:71 async loads, BurnTest.java:330-340 clock drift,
utils/Faults.java:21 fault flags)."""
from __future__ import annotations

import pytest

from accord_tpu.sim.burn import run_burn
from accord_tpu.sim.cluster import ClusterConfig
from accord_tpu.utils import faults


@pytest.mark.parametrize("seed", (2, 8))
def test_async_store_delays(seed):
    r = run_burn(seed, ops=120, config=ClusterConfig(store_delays=True))
    assert r.acked == 120
    assert r.failed == 0


def test_async_store_delays_deterministic():
    kw = dict(ops=100, collect_log=True)
    a = run_burn(5, config=ClusterConfig(store_delays=True), **kw)
    b = run_burn(5, config=ClusterConfig(store_delays=True), **kw)
    assert a.log == b.log


@pytest.mark.parametrize("seed", (2, 8))
def test_clock_drift(seed):
    r = run_burn(seed, ops=120, config=ClusterConfig(clock_drift=True))
    assert r.acked == 120
    assert r.failed == 0


def test_fast_path_disabled_fault():
    """The fast path is purely an optimization: the protocol must be
    identical with it forced off."""
    with faults.scoped(FAST_PATH_DISABLED=True):
        r = run_burn(3, ops=120, write_ratio=0.8,
                     config=ClusterConfig(durability=True,
                                          durability_interval_ms=400.0))
    assert r.acked == 120
    assert r.failed == 0


def test_unmerged_deps_is_load_bearing_and_caught():
    """In THIS design the Accept-round deps merge is load-bearing (execution
    ordering derives exclusively from committed deps -- see utils/faults.py
    for the divergence from the reference's cfk-implicit ordering). Forcing
    the fault must produce a violation the strict-serializability verifier
    CATCHES -- this guards both the invariant and the checker."""
    from accord_tpu.sim.verifier import HistoryViolation
    with faults.scoped(TRANSACTION_UNMERGED_DEPS=True,
                       SYNCPOINT_UNMERGED_DEPS=True):
        with pytest.raises((HistoryViolation, AssertionError)):
            for seed in (4, 3, 9):   # a few seeds: the race needs contention
                run_burn(seed, ops=150, chaos_drop=0.1, chaos_partitions=True,
                         write_ratio=0.85, key_count=8,
                         config=ClusterConfig(durability=True,
                                              durability_interval_ms=500.0))


def test_aggressive_recovery_races():
    """Near-zero stall threshold: recovery continuously races the live
    coordinators (every in-flight txn gets concurrently probed/recovered)."""
    r = run_burn(11, ops=120,
                 config=ClusterConfig(progress_stall_ms=50.0,
                                      progress_interval_ms=25.0,
                                      durability=True,
                                      durability_interval_ms=400.0))
    assert r.lost == 0
    assert r.failed == 0


@pytest.mark.parametrize("seed", (4, 12))
def test_everything_adversarial(seed):
    """Async store delays + clock drift + forced slow path + chaos at once:
    the burn matrix's deepest interleaving surface."""
    with faults.scoped(FAST_PATH_DISABLED=True):
        r = run_burn(seed, ops=120, chaos_drop=0.1, chaos_partitions=True,
                     config=ClusterConfig(store_delays=True, clock_drift=True,
                                          durability=True,
                                          durability_interval_ms=500.0))
    assert r.lost == 0


def test_adversarial_with_churn():
    r = run_burn(7, ops=150, topology_churn=True, churn_interval_ms=1000.0,
                 config=ClusterConfig(num_nodes=4, rf=3,
                                      store_delays=True, clock_drift=True,
                                      timeout_ms=4000.0,
                                      preaccept_timeout_ms=4000.0))
    assert r.lost == 0


# The FULLY-combined mode -- topology churn + chaos + crash/restart +
# durability rounds simultaneously, the reference burn's default regime
# (BurnTest.java:107, everything on, always). Round 4 tracked this as a
# failing residual; round 5 closed the three holes behind it:
#   1. epoch waiters fired before store ownership applied (a message gated
#      on a new epoch processed against the PREVIOUS epoch's ownership and
#      was silently dropped -- TopologyManager.notify_epoch ordering);
#   2. journal replay raced topology re-learning (records now replay gated
#      on the delivered-epoch they were journaled under);
#   3. restart catch-up marked full-range data gaps and re-bootstrapped,
#      livelocking when restarts overlapped (gapped fetch sources nack each
#      other forever); catch-up is now a dep-driven Barrier + blocked-dep
#      repair, and truncated-write gaps heal by union data repair.
#   4. a probe merging a TRUNCATED reply with a PRE_ACCEPTED reply treated
#      the witnessed executeAt as an applyable outcome and applied a
#      never-committed txn (CheckStatusOk.execute_at_decided);
#   5. half-floored records (one key below the truncation horizon, one not)
#      could neither apply nor resolve (probe->refuse loop; the OUTCOME
#      Propagate now finalizes refused copies when the remote world
#      truncated the txn).
# Seeds beyond (1, 3): 13 hit #5, 21/27 hit #4's fallout; a 30-seed sweep
# of this exact configuration runs green (round-5 log).
@pytest.mark.parametrize("seed", (1, 3, 13, 27))
def test_everything_with_crash_restart(seed):
    r = run_burn(seed, ops=300, topology_churn=True, churn_interval_ms=1000.0,
                 chaos_drop=0.05, chaos_partitions=True, crash_restart=True,
                 config=ClusterConfig(num_nodes=4, rf=3, timeout_ms=4000.0,
                                      preaccept_timeout_ms=4000.0,
                                      durability=True,
                                      durability_interval_ms=500.0))
    assert r.lost == 0
