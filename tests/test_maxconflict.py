"""GetMaxConflict / FetchMaxConflict: the timestamp-only deps-query sibling
(reference: messages/GetMaxConflict.java, coordinate/FetchMaxConflict.java:44)
and its production role -- seeding a bootstrapped range's conflict registry
(reference: local/Bootstrap.java:239)."""
from __future__ import annotations

from accord_tpu.coordinate.maxconflict import FetchMaxConflict
from accord_tpu.primitives.keyspace import Keys, Range, Ranges
from accord_tpu.primitives.timestamp import TxnKind
from accord_tpu.primitives.txn import Txn
from accord_tpu.sim.cluster import Cluster, ClusterConfig
from accord_tpu.sim.list_store import ListQuery, ListRead, ListUpdate
from accord_tpu.topology.shard import Shard
from accord_tpu.topology.topology import Topology


def write_txn(keys: Keys, value: int) -> Txn:
    return Txn(TxnKind.WRITE, keys, read=ListRead(keys),
               update=ListUpdate(keys, value), query=ListQuery())


def test_fetch_max_conflict_sees_committed_writes():
    cl = Cluster(31, ClusterConfig(num_nodes=3, rf=3))
    n1 = cl.node(1)
    keys = Keys([100, 40000])
    results = []
    for v in (1, 2, 3):
        results.append(n1.coordinate(write_txn(keys, v)))
    cl.drain()
    assert all(r.done and r.failure is None for r in results)
    max_exec = max(r.value().txn_id.as_timestamp() for r in results)

    got = FetchMaxConflict.fetch(cl.node(2), Ranges([Range(0, 65536)]))
    cl.drain()
    assert got.done and got.failure is None
    assert got.value() is not None and got.value() >= max_exec

    # untouched ranges know no conflicts
    empty = FetchMaxConflict.fetch(cl.node(2), Ranges([Range(20000, 30000)]))
    cl.drain()
    assert empty.done and empty.value() is None


def test_bootstrap_seeds_max_conflicts():
    """A replica gaining a range must learn its conflict high-water mark, not
    just its data: a fresh store that witnessed nothing would otherwise cast
    preaccept votes below already-committed conflicts."""
    cl = Cluster(32, ClusterConfig(num_nodes=4, rf=3))
    n1 = cl.node(1)
    keys = Keys([10, 500])  # shard 0 = [0, 16384) on nodes (1, 2, 3)
    for v in (1, 2):
        n1.coordinate(write_txn(keys, v))
    cl.drain()
    cl.check_no_failures()
    old_max = max(
        s.max_conflict_ts(keys)
        for s in cl.node(1).command_stores.all()
        if s.max_conflict_ts(keys) is not None)

    t1 = cl.current_topology()
    shards = list(t1.shards)
    shards[0] = Shard(shards[0].range, [2, 3, 4])  # hand shard 0 to node 4
    cl.issue_topology(Topology(2, shards))
    cl.drain()
    cl.check_no_failures()

    seeded = None
    for s in cl.node(4).command_stores.all():
        ts = s.max_conflict_ts(s.owned(keys))
        if ts is not None:
            seeded = ts if seeded is None else max(seeded, ts)
    assert seeded is not None and seeded >= old_max, \
        f"bootstrapped replica's conflict registry not seeded: {seeded}"
