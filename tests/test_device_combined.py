"""BOTH halves of the device data plane at once, under the adversarial burn:
the sharded deps arena (ops/resolver.py over the 8-device virtual mesh)
resolving PreAccept/Accept deps AND the device execution scheduler
(ops/exec_plane.py) releasing the execute DAG, with durability truncation,
topology churn and network chaos running simultaneously (VERDICT r4 item 5;
reference: the execute DAG is always on, local/Commands.java:960, and the
burn runs everything together, burn/BurnTest.java:107).

The exec plane stays opt-in for the REST of the sim suite purely for
wall-clock reasons: the sim's per-tick device dispatch costs ~50x the host
walk on the CPU test mesh (real-chip batching amortizes this; bench.py
measures that side). This module is where the combined configuration is
load-bearing.
"""
from __future__ import annotations

import pytest

from accord_tpu.parallel.mesh import make_mesh
from accord_tpu.sim.burn import run_burn
from accord_tpu.sim.cluster import ClusterConfig


def _combined_config():
    from accord_tpu.ops.resolver import ShardedBatchDepsResolver
    factory = lambda: ShardedBatchDepsResolver(  # noqa: E731
        mesh=make_mesh(), num_buckets=256, initial_cap=512)
    return ClusterConfig(deps_resolver_factory=factory,
                         deps_batch_window_ms=1.0,
                         exec_plane=True,
                         durability=True, durability_interval_ms=400.0)


@pytest.mark.parametrize("seed", (1, 2, 3, 4, 5))
def test_combined_device_plane_burn(seed):
    """Deps arena + exec frontier + durability + churn + chaos, together."""
    r = run_burn(seed, ops=60, key_count=16, concurrency=6, write_ratio=0.8,
                 chaos_drop=0.05, topology_churn=True,
                 churn_interval_ms=1500.0,
                 config=_combined_config())
    assert r.lost == 0
    assert r.acked + r.failed == 60


def test_combined_device_plane_deterministic():
    """The combined device path must replay bit-identically."""
    kw = dict(ops=60, key_count=16, concurrency=6, write_ratio=0.8,
              collect_log=True)
    a = run_burn(2, config=_combined_config(), **kw)
    b = run_burn(2, config=_combined_config(), **kw)
    assert a.log == b.log
