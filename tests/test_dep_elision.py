"""Transitive-dependency elision (reference: CommandsForKey.java
"Transitive Dependency Elision", :146-151): a committed write covers the
deps it really waits for, so later subjects' dep sets stay bounded by the
conflicts since the last committed write instead of the full conflict count
between durability rounds -- the contended-regime growth the r4 VERDICT
called out (missing item 4)."""
from __future__ import annotations

import pytest

from accord_tpu.local.cfk import CfkStatus, CommandsForKey
from accord_tpu.primitives.keyspace import Keys
from accord_tpu.primitives.timestamp import Timestamp, TxnId, TxnKind
from accord_tpu.primitives.txn import Txn
from accord_tpu.sim.cluster import Cluster, ClusterConfig
from accord_tpu.sim.list_store import ListQuery, ListRead, ListUpdate


def tid(hlc, node=1, kind=TxnKind.WRITE):
    return TxnId.create(1, hlc, node, kind)


def ts(hlc):
    return Timestamp(1, hlc, 0, 9)


def test_cfk_cover_and_elide_rules():
    cfk = CommandsForKey(7)
    d1 = tid(2)          # committed, exec below cover's -> coverable
    d2 = tid(3)          # committed, exec ABOVE cover's -> not coverable
    d3 = tid(4)          # still witnessed-only -> not coverable
    cover = tid(5)
    cfk.update(d1, CfkStatus.COMMITTED, ts(6))
    cfk.update(d2, CfkStatus.COMMITTED, ts(20))
    cfk.update(d3, CfkStatus.WITNESSED, None)
    cfk.update(cover, CfkStatus.COMMITTED, ts(10))
    cfk.mark_covered(1, cover, ts(10), [d1, d2, d3])
    assert set(cfk.covered) == {d1}
    assert cfk.covered[d1] == (1, ts(10))

    # a subject whose bound is above the cover's executeAt elides d1; the
    # cover itself and the non-coverable ids remain
    subject = tid(30)
    got = set(cfk.conflicts_before(subject, ts(30)))
    assert got == {d2, d3, cover}

    # a subject whose bound is BELOW the cover's executeAt sees everything
    # (its own executeAt may land below the cover's, so the cover edge may
    # not hold)
    low_subject = tid(9)
    got_low = set(cfk.conflicts_before(low_subject, ts(9)))
    assert d1 in got_low

    # pruning a covered id clears its flag
    cfk.update(d1, CfkStatus.APPLIED, ts(6))
    pruned = cfk.prune_below(ts(8))
    assert d1 in pruned and d1 not in cfk.covered


def _write(keys, v):
    return Txn(TxnKind.WRITE, keys, read=ListRead(keys),
               update=ListUpdate(keys, v), query=ListQuery())


def _dep_counts_per_commit(cluster, key):
    """Stable commands' committed dep-set sizes at `key`, in txn order."""
    out = []
    for store in cluster.node(1).command_stores.all():
        if not store.ranges.contains_key(key):
            continue
        for t, cmd in sorted(store.commands.items()):
            if cmd.deps is not None and cmd.status.is_stable \
                    and cmd.txn is not None and key in cmd.txn.keys:
                out.append((t, len(cmd.deps.for_key(key))))
    return out


@pytest.mark.parametrize("resolver", ("host", "device"))
def test_sequential_writes_have_bounded_deps(resolver):
    """N sequential committed writes on one hot key WITHOUT durability
    rounds: dep sets must stay O(1) via elision, not grow O(N) until the
    next durability floor."""
    factory = None
    window = 0.0
    if resolver == "device":
        from accord_tpu.ops.resolver import BatchDepsResolver
        factory = lambda: BatchDepsResolver(num_buckets=128)  # noqa: E731
        window = None  # inline: bit-identical to host timing
    cl = Cluster(3, ClusterConfig(num_nodes=3, rf=3,
                                  deps_resolver_factory=factory,
                                  deps_batch_window_ms=window))
    keys = Keys([777])
    n = 60
    for v in range(1, n + 1):
        r = cl.node(1 + v % 3).coordinate(_write(keys, v))
        cl.drain()
        assert r.done and r.failure is None
    cl.check_no_failures()

    counts = [c for _, c in _dep_counts_per_commit(cl, 777)]
    assert len(counts) == n
    # steady state: each commit depends on the previous write (+ maybe one
    # straggler), never on the whole history
    tail = counts[10:]
    assert max(tail) <= 4, f"dep sets grew: {counts}"
    # and the data is intact
    assert [v for _, v in cl.stores[1].data[777]] == list(range(1, n + 1))


def test_elision_survives_burn_growth():
    """Contended burn WITHOUT durability: the mean committed dep-set size
    stays small (elision working end-to-end, not just in the unit)."""
    from accord_tpu.sim.burn import run_burn
    report = run_burn(5, ops=250, key_count=4, concurrency=16,
                      write_ratio=0.9, max_keys_per_txn=2)
    assert report.acked == 250
