"""The staged tick pipeline: while device call N is in flight, the host
runs the NEXT tick's preaccept + encode (stage_host) so the host phases
hide inside the device window, and call N launches at the top of the next
tick event (stage_dispatch).

Four load-bearing properties:
  1. overlap_host=True decodes bit-identically to overlap_host=False and
     to the host scan on a randomized mixed key/range two-store workload,
     while the staged launch path actually engages;
  2. a preaccept that raises inside stage_host fails ONLY its own
     AsyncResult -- batchmates complete and the pipeline stays live;
  3. compaction landing BETWEEN encode-ahead (plan cut, pins taken) and
     the deferred launch is absorbed by the plan-time generation pin: the
     harvest translates rows on the device path, no host fallback;
  4. Node.shutdown() drains both stages -- staged (encode-ahead) plans AND
     in-flight calls -- so no enqueued AsyncResult strands.
"""
from __future__ import annotations

import numpy as np

from accord_tpu.local import commands
from accord_tpu.ops.resolver import BatchDepsResolver
from accord_tpu.primitives.keyspace import Keys
from tests.test_fused_dispatch import (
    _attach, _mixed_subjects, _register_mixed_per_store, _run_async,
    _two_store_node)
from tests.test_local_engine import mk_txn, setup_store
from tests.test_ops import _preaccept_population


def test_overlap_vs_serial_differential():
    """Randomized mixed key/range workload over two stores in three waves:
    the staged pipeline (overlap_host=True, the default) must decode
    bit-identically to the serial tick (overlap_host=False) AND to the
    host scan -- and the deferred-launch path must actually engage."""
    rng = np.random.default_rng(61)
    cluster, node, stores = _two_store_node()
    overlap = BatchDepsResolver(num_buckets=128, initial_cap=128)
    assert overlap.overlap_host
    _attach(stores, node, overlap, latency=5.0)
    for s in stores:
        _register_mixed_per_store(s, node, rng)

    waves = []
    for seed in (11, 12, 13):
        wave_rng = np.random.default_rng(seed)
        wave = []
        for s in stores:
            wave.extend(_mixed_subjects(s, node, wave_rng, 8))
        waves.append(wave)

    ov_res = []
    for wave in waves:
        ov_res.extend(_run_async(cluster, overlap, wave))
    # the tentpole: launches came from the encode-ahead stage, not the
    # serial encode+launch fallback
    assert overlap.staged_dispatches > 0
    assert overlap.staged_dispatches == overlap.dispatches
    assert overlap.host_fallbacks == 0 and overlap.range_fallbacks == 0

    serial = BatchDepsResolver(num_buckets=128, initial_cap=128,
                               overlap_host=False)
    sr_res = []
    for wave in waves:
        sr_res.extend(_run_async(cluster, serial, wave))
    assert serial.staged_dispatches == 0
    assert serial.host_fallbacks == 0 and serial.range_fallbacks == 0

    key_seen = range_seen = 0
    for (store, tid, owned, before), ov, sr in zip(
            [x for wave in waves for x in wave], ov_res, sr_res):
        assert ov == sr, f"overlap vs serial diverge on {tid}"
        host = store.host_calculate_deps(tid, owned, before)
        assert ov == host, f"overlap vs host diverge on {tid}"
        key_seen += bool(host.key_deps.all_txn_ids())
        range_seen += bool(host.range_deps.all_txn_ids())
    assert key_seen > 0 and range_seen > 0, "differential vacuous"


def test_staged_preaccept_exception_isolation(monkeypatch):
    """One poisoned preaccept inside stage_host fails only its own
    AsyncResult; every batchmate still completes with host-identical
    (outcome, witnessed, deps), and the NEXT batch through the same
    resolver proceeds normally (the pipeline did not wedge)."""
    cluster, node, store = setup_store()
    resolver = BatchDepsResolver(num_buckets=128, initial_cap=128)
    store.deps_resolver = resolver
    store.batch_window_ms = 0.5
    node.device_latency_ms = 5.0

    txns = []
    for i in range(6):
        txn = mk_txn([2 * i, 2 * i + 1], value=i)
        tid = node.next_txn_id(txn.kind, txn.domain)
        txns.append((tid, txn.slice(store.ranges, include_query=False),
                     node.compute_route(txn)))
    bad_tid = txns[2][0]

    real = commands.preaccept

    def poisoned(store_, txn_id, txn, route, ballot=None):
        if txn_id == bad_tid:
            raise RuntimeError("poisoned preaccept")
        if ballot is None:
            return real(store_, txn_id, txn, route)
        return real(store_, txn_id, txn, route, ballot)

    monkeypatch.setattr(commands, "preaccept", poisoned)

    outs = [store.submit_preaccept(tid, partial, route)
            for tid, partial, route in txns]
    cluster.queue.drain(max_events=100_000)

    assert all(o.done for o in outs)
    bad = outs[2]
    assert not bad.success
    assert "poisoned" in str(bad.failure)
    for i, ((tid, partial, _), out) in enumerate(zip(txns, outs)):
        if i == 2:
            continue
        assert out.success, f"batchmate {tid} infected by the poison"
        outcome, witnessed, deps = out.value()
        assert witnessed == store.command(tid).execute_at
        host = store.host_calculate_deps(
            tid, store.owned(partial.keys), witnessed)
        assert deps == host, f"batchmate {tid} deps diverge"
    assert resolver.host_fallbacks == 0

    # pipeline still live: a fresh wave through the same resolver completes
    monkeypatch.setattr(commands, "preaccept", real)
    txn = mk_txn([3], value=99)
    tid = node.next_txn_id(txn.kind, txn.domain)
    out = store.submit_preaccept(
        tid, txn.slice(store.ranges, include_query=False),
        node.compute_route(txn))
    cluster.queue.drain(max_events=100_000)
    assert out.success
    outcome, witnessed, deps = out.value()
    assert deps == store.host_calculate_deps(tid, store.owned(Keys([3])),
                                             witnessed)


def test_compaction_between_stage_and_dispatch():
    """compact() landing in the gap between encode-ahead (plan cut against
    generation G, pin taken) and the deferred launch must be absorbed by
    the plan-time pin: the harvest translates its rows on the DEVICE path
    (stale_harvests, not host_fallbacks) and matches the host scan."""
    rng = np.random.default_rng(37)
    cluster, node, store = setup_store()
    resolver = BatchDepsResolver(num_buckets=128, initial_cap=128)
    store.deps_resolver = resolver
    store.batch_window_ms = 0.5
    node.device_latency_ms = 50.0

    chaff_keys = [sorted(set(rng.integers(100, 140, 2).tolist()))
                  for _ in range(50)]
    chaff = _preaccept_population(store, node, chaff_keys)
    live_keys = [sorted(set(rng.integers(0, 12, 2).tolist()))
                 for _ in range(40)]
    live = _preaccept_population(store, node, live_keys)
    arena = resolver._arenas[id(store)]
    for t, ks in zip(chaff, chaff_keys):
        resolver.on_prune(store, t, ks)

    subs = []
    for i in range(20, 26):
        t = live[i]
        keys = Keys(live_keys[i])
        before = store.command(t).execute_at
        subs.append((t, keys, before,
                     resolver.enqueue_deps(store, t, keys, before)))

    # pump to the exact pipeline gap: plans staged (pins taken at plan
    # time), deferred launch not yet fired
    while not resolver._staged.get(id(node)):
        assert cluster.queue.process_one(), "stage never cut a plan"
    assert resolver.dispatches == 0

    gen0 = arena.gen
    assert arena.compact(), "compaction should reclaim the pruned chaff"
    assert arena.gen == gen0 + 1
    # the plan-time pin forced a row->txn snapshot of the retired mapping
    assert gen0 in arena.retired_ids

    while not all(out.done for *_, out in subs):
        assert cluster.queue.process_one(), "harvest never fired"
    assert resolver.stale_harvests >= 1
    assert resolver.host_fallbacks == 0
    cluster.queue.drain(max_events=10_000)
    assert gen0 not in arena.retired_ids  # pin released after harvest

    nonempty = 0
    for t, keys, before, out in subs:
        host = store.host_calculate_deps(t, keys, before)
        assert out.value() == host, f"subject {t} diverges post-compaction"
        nonempty += bool(host.key_deps.all_txn_ids())
    assert nonempty > 0, "differential vacuous"


def test_drain_flushes_both_stages():
    """Node.shutdown() with one call in flight AND one encode-ahead plan
    staged must flush both: every AsyncResult completes (host-identical),
    and the pipeline state for the node is empty."""
    rng = np.random.default_rng(53)
    cluster, node, store = setup_store()
    resolver = BatchDepsResolver(num_buckets=128, initial_cap=128)
    store.deps_resolver = resolver
    store.batch_window_ms = 0.5
    node.device_latency_ms = 50.0  # harvest lands far beyond the ticks

    live_keys = [sorted(set(rng.integers(0, 12, 2).tolist()))
                 for _ in range(30)]
    live = _preaccept_population(store, node, live_keys)

    def enqueue(idxs):
        outs = []
        for i in idxs:
            t = live[i]
            keys = Keys(live_keys[i])
            before = store.command(t).execute_at
            outs.append((t, keys, before,
                         resolver.enqueue_deps(store, t, keys, before)))
        return outs

    wave_a = enqueue(range(10, 15))
    while resolver.dispatches < 1:
        assert cluster.queue.process_one(), "first launch never fired"
    wave_b = enqueue(range(20, 25))
    while not resolver._staged.get(id(node)):
        assert cluster.queue.process_one(), "second stage never cut a plan"

    # the exact mid-pipeline state: call in flight + plan staged
    assert len(resolver._inflight[id(node)]) == 1
    assert all(not out.done for *_, out in wave_a + wave_b)

    node.shutdown()

    assert all(out.done for *_, out in wave_a + wave_b)
    assert not resolver._staged.get(id(node))
    assert not resolver._inflight.get(id(node))
    assert resolver.host_fallbacks == 0
    nonempty = 0
    for t, keys, before, out in wave_a + wave_b:
        host = store.host_calculate_deps(t, keys, before)
        assert out.value() == host, f"subject {t} diverges after drain"
        nonempty += bool(host.key_deps.all_txn_ids())
    assert nonempty > 0, "differential vacuous"
    # idempotent
    node.shutdown()
