"""Node crash + restart with journal replay (reference: test
impl/basic/Journal.java:59 + pseudo-restart): a crashed node loses its
in-memory command state and every message delivered while down; on restart
it re-learns the epoch history, replays its journal of side-effect
messages, diffs the rebuilt stable+ command state against the pre-crash
snapshot, and catches up missed data with a bootstrap fetch."""
from __future__ import annotations

import pytest

from accord_tpu.primitives.keyspace import Keys
from accord_tpu.primitives.timestamp import TxnKind
from accord_tpu.primitives.txn import Txn
from accord_tpu.sim.burn import run_burn
from accord_tpu.sim.cluster import Cluster, ClusterConfig
from accord_tpu.sim.list_store import ListQuery, ListRead, ListUpdate


def write_txn(keys, v):
    ks = Keys(keys)
    return Txn(TxnKind.WRITE, ks, read=ListRead(ks),
               update=ListUpdate(ks, v), query=ListQuery())


def test_crash_restart_rebuild_and_catchup():
    """Direct scenario: writes before the crash are rebuilt from the journal
    (same executeAt, stable+), writes during the downtime arrive via the
    restart catch-up fetch, and the cluster converges."""
    c = Cluster(17, ClusterConfig())
    for v in range(1, 8):
        r = c.nodes[1 + v % 3].coordinate(write_txn([100 + v % 3, 5000], v))
        c.drain()
        assert r.done and r.failure is None, r.failure
    snapshot = c.crash_node(2)
    assert snapshot, "no stable commands snapshotted"
    for v in range(8, 12):
        r = c.nodes[1 + (v % 2) * 2].coordinate(write_txn([5000], v))
        c.drain()
        assert r.done and r.failure is None, r.failure
    c.restart_node(2)
    c.drain()
    c.check_no_failures()
    c.verify_rebuild(2, snapshot)
    lists = c.converged_key_lists()
    assert lists[5000] == tuple(range(1, 12))


def test_crashed_node_is_silent():
    """A crashed node neither receives nor sends: messages to it are lost
    (sender timeouts fire) and its residual timers do not act."""
    c = Cluster(23, ClusterConfig())
    r = c.nodes[1].coordinate(write_txn([9000], 1))
    c.drain()
    assert r.failure is None
    c.crash_node(3)
    # quorum 2/3 still commits
    r = c.nodes[1].coordinate(write_txn([9000], 2))
    c.drain()
    assert r.failure is None
    assert c.stores[3].snapshot(9000) == (1,)  # the crashed replica missed it
    c.restart_node(3)
    c.drain()
    c.check_no_failures()
    assert c.stores[3].snapshot(9000) == (1, 2)  # caught up


def test_replay_rebuilds_ranges_gained_in_later_epochs():
    """Targeted regression for the round-4 'lost in rebuild' residual: a
    command on a range the node only gained at epoch 2 must be rebuilt by
    journal replay. Replay gates each record on the delivered-epoch it was
    journaled under, and epoch waiters must fire only AFTER store ownership
    is applied (TopologyManager.notify_epoch) -- otherwise the replayed
    messages process against epoch-1 ownership, find no intersecting store,
    and the command silently vanishes."""
    from accord_tpu.sim.cluster import build_topology
    from accord_tpu.topology.shard import Shard
    from accord_tpu.topology.topology import Topology
    from accord_tpu.primitives.keyspace import Range

    cfg = ClusterConfig(num_nodes=3, rf=2, num_shards=2)
    c = Cluster(29, cfg)
    # epoch 1 (rf=2): shard0 [0,32768) -> nodes 1,2; shard1 [32768,65536)
    # -> nodes 2,3. Epoch 2 moves shard1 to nodes 1,2: node 1 GAINS it.
    t1 = c.current_topology()
    assert 1 not in t1.shards[1].nodes
    c.issue_topology(Topology(2, [Shard(Range(0, 32768), [1, 2]),
                                  Shard(Range(32768, 65536), [1, 2])]))
    c.drain()
    c.check_no_failures()
    # a write on the gained range, witnessed by node 1 at epoch 2
    r = c.nodes[1].coordinate(write_txn([40000], 1))
    c.drain()
    assert r.done and r.failure is None, r.failure
    snapshot = c.crash_node(1)
    assert any(txn_id.epoch >= 2 for (_, txn_id) in snapshot), \
        "scenario not exercised: no epoch-2+ command snapshotted"
    c.restart_node(1)
    c.drain()
    c.check_no_failures()
    c.verify_rebuild(1, snapshot)


@pytest.mark.parametrize("seed", (1, 9, 13))
def test_crash_restart_burn(seed):
    """One crash+restart per node mid-burn (staggered): converges, verifies
    strict serializability, and every rebuild diff passes (verify_rebuild
    raises into cluster failures otherwise)."""
    cfg = ClusterConfig(num_nodes=4, rf=3, timeout_ms=4000.0,
                        preaccept_timeout_ms=4000.0)
    r = run_burn(seed, ops=300, crash_restart=True, config=cfg)
    assert r.lost == 0
    assert r.failed <= 30, f"excessive client loss: {r.failed}/300"


def test_crash_restart_burn_with_durability():
    cfg = ClusterConfig(num_nodes=4, rf=3, timeout_ms=4000.0,
                        preaccept_timeout_ms=4000.0,
                        durability=True, durability_interval_ms=500.0)
    r = run_burn(9, ops=300, crash_restart=True, config=cfg)
    assert r.lost == 0
    assert r.failed <= 30


def test_crash_restart_deterministic():
    cfg = dict(ops=200, crash_restart=True)
    a = run_burn(5, collect_log=True,
                 config=ClusterConfig(num_nodes=4, rf=3), **cfg)
    b = run_burn(5, collect_log=True,
                 config=ClusterConfig(num_nodes=4, rf=3), **cfg)
    assert a.log == b.log


# -- device leg: crash/restart with BatchDepsResolver arenas ------------------

def _device_cfg(**extra):
    from accord_tpu.ops.resolver import BatchDepsResolver
    return ClusterConfig(
        deps_resolver_factory=lambda: BatchDepsResolver(num_buckets=128),
        deps_batch_window_ms=1.0, device_latency_ms=8.0, **extra)


def test_device_crash_restart_rebuilt_arena_matches_replica():
    """Direct device-leg scenario: the restarted node's resolver arenas are
    rebuilt purely from journal-replay re-registrations (the fresh resolver
    never saw the live traffic). Post-restart device harvests must be
    bit-identical to the SAME store's host scan (arena rebuild fidelity),
    and must cover every dep the never-crashed replica reports -- full
    replica-to-replica equality is deliberately NOT asserted, because
    dep-elision floors advance per replica (the rebuilt node's fresh floor
    legitimately reports supersets on applied txns)."""
    from accord_tpu.primitives.timestamp import Domain, Timestamp, TxnKind

    c = Cluster(17, _device_cfg())
    for v in range(1, 8):
        r = c.nodes[1 + v % 3].coordinate(write_txn([100 + v % 3, 5000], v))
        c.drain()
        assert r.done and r.failure is None, r.failure
    snapshot = c.crash_node(2)
    assert snapshot, "no stable commands snapshotted"
    for v in range(8, 12):
        r = c.nodes[1 + (v % 2) * 2].coordinate(write_txn([5000], v))
        c.drain()
        assert r.done and r.failure is None, r.failure
    c.restart_node(2)
    c.drain()
    c.check_no_failures()
    c.verify_rebuild(2, snapshot)
    assert c.converged_key_lists()[5000] == tuple(range(1, 12))

    # same subject (same txn id, same bound) against the rebuilt replica
    # and a never-crashed one: the device decodes must agree with each
    # other and with the host differential scan on both stores
    node2 = c.nodes[2]
    far = Timestamp(node2.epoch, node2.time_service.now_micros() + 50_000,
                    0, node2.id)
    checked = 0
    for key in (5000, 100, 101, 102):
        ks = Keys([key])
        s2 = next(s for s in c.nodes[2].command_stores.all()
                  if not s.owned(ks).is_empty())
        s3 = next(s for s in c.nodes[3].command_stores.all()
                  if not s.owned(ks).is_empty())
        tid = node2.next_txn_id(TxnKind.WRITE, Domain.KEY)
        fin0 = s2.deps_resolver.finalized_decodes
        d2 = s2.deps_resolver.resolve_one(s2, tid, s2.owned(ks), far)
        assert s2.deps_resolver.finalized_decodes > fin0, \
            "rebuilt node answered outside the device path"
        d3 = s3.deps_resolver.resolve_one(s3, tid, s3.owned(ks), far)
        # device decode == host scan on BOTH replicas: the rebuilt arena
        # holds exactly what the rebuilt store holds
        assert d2 == s2.host_calculate_deps(tid, s2.owned(ks), far)
        assert d3 == s3.host_calculate_deps(tid, s3.owned(ks), far)
        # and the rebuild lost nothing the live replica still reports
        missing = set(d3.key_deps.all_txn_ids()) \
            - set(d2.key_deps.all_txn_ids())
        assert not missing, \
            f"rebuilt replica lost deps on key {key}: {missing}"
        checked += bool(d2.key_deps.all_txn_ids())
    assert checked > 0, "differential vacuous: no deps seen"


def test_device_crash_restart_burn_deterministic():
    """Crash+restart burn on the device leg: every node's resolver arena is
    torn down and journal-rebuilt once mid-burn; the run converges, every
    rebuild diff passes, and two runs are bit-identical."""
    kw = dict(ops=200, crash_restart=True, collect_log=True)
    a = run_burn(13, config=_device_cfg(num_nodes=4, rf=3, timeout_ms=4000.0,
                                        preaccept_timeout_ms=4000.0), **kw)
    b = run_burn(13, config=_device_cfg(num_nodes=4, rf=3, timeout_ms=4000.0,
                                        preaccept_timeout_ms=4000.0), **kw)
    assert a.lost == 0
    assert a.failed <= 20, f"excessive client loss: {a.failed}/200"
    assert a.log == b.log


@pytest.mark.chaos
def test_device_crash_restart_under_device_chaos():
    """Crash/restart and device-plane fault injection TOGETHER: journal
    rebuilds race injected dispatch faults, and the run still converges
    with an exact injection ledger and a deterministic history."""
    kw = dict(ops=200, crash_restart=True, collect_log=True,
              device_chaos=True,
              device_fault_rates={"dispatch_exc_rate": 0.05,
                                  "stuck_rate": 0.05, "corrupt_rate": 0.05})
    a = run_burn(13, config=_device_cfg(num_nodes=4, rf=3, timeout_ms=4000.0,
                                        preaccept_timeout_ms=4000.0), **kw)
    b = run_burn(13, config=_device_cfg(num_nodes=4, rf=3, timeout_ms=4000.0,
                                        preaccept_timeout_ms=4000.0), **kw)
    assert a.lost == 0
    assert a.log == b.log
    assert sum(a.device_faults.values()) > 0
