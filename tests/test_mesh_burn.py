"""Cluster-on-mesh burn tests: the node-lane merged dispatch
(sim/mesh_burn.py + ops/node_lane.py) against the per-node Python launch
loop. Both modes share one event schedule (the ClusterTickEngine drains,
stages, and launches every pending node either way), so the differential
is exact: bit-identical event logs, not statistical agreement.
"""
from __future__ import annotations

import pytest

from accord_tpu.sim.mesh_burn import ClusterTickEngine, run_mesh_burn

pytestmark = pytest.mark.mesh_burn


def _logs(seed, ops, **kw):
    mesh, emesh = run_mesh_burn(seed, ops, mesh_tick=True,
                                collect_log=True, **kw)
    loop, _ = run_mesh_burn(seed, ops, mesh_tick=False,
                            collect_log=True, **kw)
    return mesh, emesh, loop


def test_mesh_vs_loop_differential_small():
    """Key + range traffic at 4 nodes: the merged node-lane dispatch
    commits the exact event log of the per-node launch loop, and every
    plan rode the merge (no fallbacks)."""
    mesh, eng, loop = _logs(11, 90, nodes=4,
                            range_read_ratio=0.15, range_write_ratio=0.1)
    assert mesh.acked == loop.acked == 90
    assert mesh.log == loop.log, "node-lane burn diverged from Python loop"
    snap = eng.snapshot()
    assert snap["node_lane_dispatches"] > 0
    assert snap["mesh_tick_fallbacks"] == 0
    assert snap["nodes_per_dispatch"] > 1.0, \
        "merge never carried more than one node"


def test_randomized_differential_seeds():
    """A seed sweep of the plain workload: determinism and equivalence are
    properties of the engine, not of one lucky schedule."""
    for seed in (2, 5, 8):
        mesh, _eng, loop = _logs(seed, 50, nodes=3)
        assert mesh.log == loop.log, f"diverged at seed {seed}"


@pytest.mark.slow
def test_mesh_vs_loop_differential_64_nodes_reconcile():
    """The acceptance-bar case: at 64 nodes the node-lane burn commits a
    bit-identical history to the per-node loop, and each mode reconciles
    with itself (same seed twice -> same log)."""
    kw = dict(nodes=64, concurrency=24)
    mesh, eng, loop = _logs(3, 120, **kw)
    assert mesh.acked == loop.acked == 120
    assert mesh.log == loop.log
    again, _ = run_mesh_burn(3, 120, mesh_tick=True, collect_log=True, **kw)
    assert mesh.log == again.log, "node-lane burn is not reconcilable"
    assert eng.snapshot()["nodes_per_dispatch"] > 2.0


def test_compaction_pin_isolation_across_nodes():
    """Tiny arenas force growth/compaction generations mid-burn on every
    node. Each plan's merge inputs are the SNAPSHOT arrays pinned at
    encode time, so one node's arena churn must not perturb another
    node's lane: histories stay bit-identical to the per-node loop, which
    pins the very same snapshots."""
    rkw = dict(initial_cap=128)
    mesh, eng, loop = _logs(17, 80, nodes=4, key_count=96,
                            resolver_kwargs=rkw)
    assert mesh.acked == loop.acked == 80
    assert mesh.log == loop.log, \
        "arena churn leaked across node lanes in the merged dispatch"
    assert eng.snapshot()["mesh_tick_fallbacks"] == 0


def test_crash_restart_lane_pads_out_without_recompile():
    """A crashed node drops out of the cluster tick (its lane pads out of
    the merge); the restarted incarnation's fresh resolver re-adopts the
    engine via the factory. With pad_node_tiers fixing the block-count
    tier, the shrink and regrow mint NO new node-kernel compiles after
    the warm run -- and the history still matches the per-node loop."""
    from accord_tpu.ops.node_lane import node_lane_cache_sizes

    kw = dict(nodes=4, crash_restart=True, crash_down_ms=400.0,
              pad_node_tiers=8)
    warm, _ = run_mesh_burn(21, 70, mesh_tick=True, collect_log=True, **kw)
    sizes = dict(node_lane_cache_sizes())
    mesh, eng, loop = _logs(29, 70, **kw)
    assert mesh.log == loop.log
    after = node_lane_cache_sizes()
    for k in ("node_fused_deps_resolve", "node_fused_range_deps_resolve"):
        assert after[k] == sizes[k], \
            f"{k} minted compiles across node-count churn: " \
            f"{sizes[k]} -> {after[k]}"


def test_cluster_tick_counters_fold_into_report():
    """The engine's glossary counters ride the burn report, and the
    padded-row accounting is internally consistent."""
    rep, eng = run_mesh_burn(13, 40, nodes=3)
    for k in ("node_lane_dispatches", "nodes_per_dispatch",
              "node_pad_fraction", "mesh_tick_fallbacks"):
        assert k in rep.counters
    assert 0.0 <= rep.counters["node_pad_fraction"] < 1.0
    assert rep.counters["node_lane_dispatches"] == \
        eng.snapshot()["node_lane_dispatches"]


def test_cli_reconcile():
    """The module CLI's --reconcile leg: two runs of each seed, identical
    logs, exit 0."""
    from accord_tpu.sim import mesh_burn
    rc = mesh_burn.main(["--seed", "1", "--ops", "40", "--nodes", "3",
                         "--reconcile"])
    assert rc == 0


@pytest.mark.slow
def test_sharded_node_tick_matches_single_device():
    """sharded_node_tick (node-major block axis over 'data', buckets over
    'model') commits the same history as the single-device node lane and
    the per-node loop."""
    rkw = dict(num_buckets=256, initial_cap=512)
    kw = dict(nodes=4, resolver_kwargs=rkw)
    sh, eng, shloop = _logs(5, 50, sharded=True, **kw)
    assert sh.log == shloop.log
    single, _ = run_mesh_burn(5, 50, mesh_tick=True, collect_log=True, **kw)
    assert sh.log == single.log
    assert eng.snapshot()["node_lane_dispatches"] > 0


def test_engine_reuse_rejected_reentry_safe():
    """note_work during a firing tick arms the NEXT tick (no lost work):
    exercised implicitly by every burn above; here assert the engine's
    dedupe keeps one armed event per window and the pending map clears."""
    eng = ClusterTickEngine()
    rep, eng2 = run_mesh_burn(31, 30, nodes=3, engine=eng)
    assert eng2 is eng
    assert not eng._pending, "pending work left behind at quiescence"
    assert not eng._armed
