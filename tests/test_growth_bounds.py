"""State growth bounds: per-key conflict registries prune behind the
durability floor (reference: cfk prunedBefore, local/cfk/Pruning.java:41) and
the device deps arena compacts dead rows instead of growing forever."""
from __future__ import annotations

from accord_tpu.local.cfk import CfkStatus, CommandsForKey
from accord_tpu.primitives.timestamp import Domain, TxnId, TxnKind
from accord_tpu.sim.burn import run_burn
from accord_tpu.sim.cluster import Cluster, ClusterConfig


def _tid(hlc, node=1, kind=TxnKind.WRITE):
    return TxnId.create(1, hlc, node, kind, Domain.KEY)


def test_cfk_prune_below():
    c = CommandsForKey(1)
    applied = _tid(10)
    pending = _tid(20)
    invalid = _tid(30)
    above = _tid(90)
    c.update(applied, CfkStatus.APPLIED, applied.as_timestamp())
    c.update(pending, CfkStatus.COMMITTED, pending.as_timestamp())
    c.update(invalid, CfkStatus.INVALIDATED, None)
    c.update(above, CfkStatus.APPLIED, above.as_timestamp())
    pruned = c.prune_below(_tid(50).as_timestamp())
    assert set(pruned) == {applied, invalid}
    # committed-not-applied survives any floor; above-floor applied survives
    assert c.get(pending) is not None
    assert c.get(above) is not None
    # the max-applied-write aggregate is monotone and retained
    assert c.max_applied_write == above.as_timestamp()


def test_long_burn_bounded_state():
    """5k ops, slow durability cadence, small device arena: per-key sets and
    the arena capacity must stay bounded by pruning/compaction rather than
    growing with total txn count."""
    from accord_tpu.ops.resolver import BatchDepsResolver
    resolvers = []

    def factory():
        r = BatchDepsResolver(num_buckets=256, initial_cap=256)
        resolvers.append(r)
        return r

    _last = {}
    orig = Cluster.__init__

    def spy(self, *a, **k):
        orig(self, *a, **k)
        _last["c"] = self

    Cluster.__init__ = spy
    try:
        r = run_burn(9, ops=5000, key_count=12, concurrency=24,
                     config=ClusterConfig(
                         deps_resolver_factory=factory,
                         deps_batch_window_ms=2.0,
                         durability=True, durability_interval_ms=300.0))
    finally:
        Cluster.__init__ = orig
    assert r.lost == 0
    assert r.failed == 0
    c = _last["c"]
    # cfk per-key sets: bounded by the inter-durability-round arrival rate,
    # not by the 5000-txn history
    worst_key = max((len(cfk) for n in c.nodes.values()
                     for s in n.command_stores.all()
                     for cfk in s.cfks.values()), default=0)
    assert worst_key < 1500, f"cfk grew with history: {worst_key} entries"
    # device arena: compaction must have held the capacity well below
    # one-row-per-txn (5000 txns x rf over 3 nodes)
    worst_cap = max(a.cap for res in resolvers for a in res._arenas.values())
    assert worst_cap <= 2048, f"arena grew unboundedly: cap={worst_cap}"
    # reclamation must actually have cycled (5000 rows through a 2048 cap)
    assert any(a.gen >= 1 for res in resolvers for a in res._arenas.values()), \
        "no arena ever compacted"
