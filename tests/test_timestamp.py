from accord_tpu.primitives import Ballot, Domain, Timestamp, TxnId, TxnKind


def test_total_order():
    a = Timestamp(1, 5, 0, 1)
    b = Timestamp(1, 5, 0, 2)
    c = Timestamp(1, 6, 0, 1)
    d = Timestamp(2, 0, 0, 0)
    assert a < b < c < d
    assert max(a, b, c, d) == d
    assert Timestamp.merge_max(a, c) == c
    assert Timestamp.merge_max(None, a) == a
    assert Timestamp.merge_max(a, None) == a


def test_pack_unpack_roundtrip():
    for ts in [Timestamp(0, 0, 0, 0), Timestamp(3, 123456789, 7, 42),
               Timestamp((1 << 48) - 1, (1 << 48) - 1, (1 << 16) - 1, (1 << 16) - 1)]:
        msb, lsb = ts.pack()
        assert Timestamp.unpack(msb, lsb) == ts


def test_pack_order_preserving():
    import random
    rng = random.Random(0)
    tss = [Timestamp(rng.randrange(4), rng.randrange(100), rng.randrange(4), rng.randrange(8))
           for _ in range(200)]
    by_value = sorted(tss)
    by_packed = sorted(tss, key=lambda t: t.pack())
    assert by_value == by_packed


def test_txnid_kind_domain():
    t = TxnId.create(epoch=2, hlc=99, node=3, kind=TxnKind.WRITE, domain=Domain.RANGE)
    assert t.kind == TxnKind.WRITE
    assert t.domain == Domain.RANGE
    assert t.is_write
    r = TxnId.create(1, 1, 1, TxnKind.READ)
    assert r.kind == TxnKind.READ and r.domain == Domain.KEY and r.is_read


def test_witness_rules():
    # exact mirror of reference Txn.Kind.witnesses (primitives/Txn.java:224)
    R, W, ER = TxnKind.READ, TxnKind.WRITE, TxnKind.EPHEMERAL_READ
    SP, XSP = TxnKind.SYNC_POINT, TxnKind.EXCLUSIVE_SYNC_POINT
    assert R.witnesses(W) and not R.witnesses(R)
    assert not R.witnesses(XSP) and not R.witnesses(SP)
    assert ER.witnesses(W) and not ER.witnesses(R)
    assert W.witnesses(R) and W.witnesses(W)
    assert not W.witnesses(SP) and not W.witnesses(XSP) and not W.witnesses(ER)
    assert SP.witnesses(R) and SP.witnesses(W) and not SP.witnesses(SP)
    assert XSP.witnesses(R) and XSP.witnesses(W)
    assert XSP.witnesses(SP) and XSP.witnesses(XSP)  # AnyGloballyVisible
    assert not XSP.witnesses(ER)
    assert W.witnessed_by(R)
    assert not ER.witnessed_by(W)  # nothing witnesses ephemeral reads


def test_ballot():
    assert Ballot.ZERO < Ballot(1, 0, 0, 0) < Ballot.MAX
    assert isinstance(Ballot.ZERO, Timestamp)


def test_hlc_derivation():
    t = Timestamp(1, 10, 0, 3)
    n = t.with_next_hlc()
    assert n.hlc == 11 and n.epoch == 1
    assert t.with_epoch_at_least(5).epoch == 5
    assert t.with_epoch_at_least(0) is t
