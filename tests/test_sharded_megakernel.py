"""Sharded protocol megakernel tests: parallel/mesh.sharded_protocol_tick
(one shard_map program per cluster tick) against the single-device
megakernel and the per-node host loop.

conftest.py forces a virtual 8-device CPU mesh, so every test here runs
the genuinely sharded lowering (data=4, model=2) in-process. The contract
is the megakernel's, extended across shards: bit-identical committed
histories, exactly one launch per dispatching tick, and the cross-shard
mailbox hop (lax.all_to_all over 'data') landing every payload on its
destination shard's ring.

Tier-1 budget note: the full tier-1 suite runs within ~2% of its hard
timeout on the reference box, so only the compile-free unit tests ride
tier 1 here; every differential that compiles a sharded program is
marked slow. Run the whole module (no -m filter) for the multichip
smoke -- bench.py's MULTICHIP legs gate the same contract on every
bench run regardless.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accord_tpu.ops.encoding import WITNESS_TABLE
from accord_tpu.ops.kernels import protocol_tick
from accord_tpu.ops.mailbox import MailboxPlane
from accord_tpu.parallel.mesh import (make_mesh, mesh_supports_message_plane,
                                      sharded_protocol_tick)
from accord_tpu.sim.mesh_burn import run_mesh_burn
from accord_tpu.sim.network import _MailMsg

pytestmark = pytest.mark.sharded_megakernel


@pytest.fixture(scope="module")
def mesh():
    m = make_mesh()
    assert len(jax.devices()) >= 8, "conftest should force 8 virtual devices"
    assert m.shape["data"] > 1, "mesh must actually shard the node axis"
    return m


def _gate_fused(counters):
    assert counters["megakernel_dispatches"] > 0
    assert counters["launches_per_tick"] == 1.0
    assert counters["sharded_megakernel_fallbacks"] == 0


# -- compile-free units (tier 1) ----------------------------------------------

def test_mesh_reports_message_plane_support(mesh):
    assert mesh_supports_message_plane(mesh)


def test_mailbox_sharded_staging_layout():
    """The sharded emit-lane layout, host side only: lanes grouped by
    (src shard, dst shard) at segment (s*S+t)*bcap, each entry's return
    position receiver-major at (t*S+s)*bcap + j, node v owning rows on
    shard v // npsh -- and the shards=1 layout degenerating to the flat
    staging order."""
    rng = np.random.default_rng(4)
    n, S = 6, 4

    def mk_entries():
        ents = []
        for i in range(12):
            e = _MailMsg(kind=1, src=int(rng.integers(1, n + 1)),
                         dst=int(rng.integers(1, n + 1)),
                         payload=bytes([i]) * 8)
            e.ticket = i
            ents.append(e)
        return ents

    state = rng.bit_generator.state
    ents = mk_entries()
    p = MailboxPlane(n, depth=8, words=16, shards=S)
    assert p.npsh == 2 and p.rows_nodes == 8
    out = p.stage_batch(ents)
    assert out is not None
    e_src, e_dst, e_keep = (np.asarray(out[2]), np.asarray(out[3]),
                            np.asarray(out[5]))
    bcap = len(e_src) // (S * S)
    for e in ents:
        _batch, pos, dst, idx = e.slot
        s, t = e.src // p.npsh, e.dst // p.npsh
        # send position lives in segment (s, t); the return position is
        # the same lane index in the receiver-major segment (t, s)
        j = pos - (t * S + s) * bcap
        assert 0 <= j < bcap
        send = (s * S + t) * bcap + j
        assert e_keep[send]
        assert e_src[send] == e.src and e_dst[send] == e.dst
        assert dst == e.dst
    # every kept lane sits inside its group's segment
    for pos in np.flatnonzero(e_keep):
        s, t = e_src[pos] // p.npsh, e_dst[pos] // p.npsh
        assert (s * S + t) * bcap <= pos < (s * S + t) * bcap + bcap

    # shards=1: one group, positions are exactly the staging order
    rng.bit_generator.state = state
    ents1 = mk_entries()
    p1 = MailboxPlane(n, depth=8, words=16, shards=1)
    assert p1.npsh == n + 1 and p1.rows_nodes == n + 1
    p1.stage_batch(ents1)
    for j, e in enumerate(ents1):
        assert e.slot[1] == j


# -- tick-level differentials (sharded program vs single-device program) ------

@pytest.mark.slow
def test_sharded_tick_key_finalize_matches_single_device(mesh):
    """Key resolve + two finalize-CSR compactions on different store spans:
    the sharded program's packed bitmap and CSR outputs must equal the
    single-device protocol_tick's bit for bit."""
    data = mesh.shape["data"]
    table = jnp.asarray(WITNESS_TABLE)
    rng = np.random.default_rng(1)
    cap = 32 * data * 2
    K = 8 * mesh.shape["model"]
    b, z, ns, kc, oc = 16, 32, 2, 8, 64
    w = cap // 32
    arenas = tuple(
        (jnp.asarray((rng.random((cap, K)) < 0.1).astype(np.float32)),
         jnp.asarray(rng.integers(0, 100, (cap, 3)).astype(np.int32)),
         jnp.asarray(rng.integers(0, 6, cap).astype(np.int32)),
         jnp.asarray(rng.random(cap) < 0.9)) for _ in range(ns))
    sof = rng.integers(0, b, z).astype(np.int32)
    sk = rng.integers(0, K, z).astype(np.int32)
    sst = rng.integers(0, ns, b).astype(np.int32)
    sb = rng.integers(50, 150, (b, 3)).astype(np.int32)
    sknd = rng.integers(0, 6, b).astype(np.int32)
    slots = np.arange(ns, dtype=np.int32)
    key_in = tuple(map(jnp.asarray, (sof, sk, sst, sb, sknd, slots))) \
        + (arenas,)
    kid_rows = jnp.asarray(
        rng.integers(0, 2**32, (kc, w), dtype=np.uint64).astype(np.uint32))
    j_subj = jnp.asarray(rng.integers(0, b, 12).astype(np.int32))
    j_kid = jnp.asarray(rng.integers(0, kc, 12).astype(np.int32))
    j_srow = jnp.asarray(rng.integers(-1, cap, b).astype(np.int32))
    act_ts = arenas[0][1]
    fins = (("key", 0, 0, b, w, 0, kid_rows, j_subj, j_kid, j_srow,
             act_ts, oc),
            ("key", 0, w, b, w, 0, kid_rows, j_subj, j_kid, j_srow,
             act_ts, oc))
    ref = protocol_tick(table, key_in=key_in, fins=fins)
    got = sharded_protocol_tick(mesh, table, key_in=key_in, fins=fins)
    np.testing.assert_array_equal(np.asarray(ref[0]), np.asarray(got[0]))
    for fr, fg in zip(ref[2], got[2]):
        for a, c in zip(fr, fg):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(c))


@pytest.mark.slow
def test_sharded_tick_range_resolve_matches_single_device(mesh):
    data = mesh.shape["data"]
    table = jnp.asarray(WITNESS_TABLE)
    rng = np.random.default_rng(2)
    cap = 32 * data * 2
    K = 8 * mesh.shape["model"]
    b, z, ns = 16, 32, 2
    arenas = tuple(
        (jnp.asarray((rng.random((cap, K)) < 0.1).astype(np.float32)),
         jnp.asarray(rng.integers(0, 100, (cap, 3)).astype(np.int32)),
         jnp.asarray(rng.integers(0, 6, cap).astype(np.int32)),
         jnp.asarray(rng.random(cap) < 0.9)) for _ in range(ns))
    rcap = max(64, 32 * data)
    nrs = 2
    rars = tuple(
        (jnp.asarray(rng.integers(0, 50, rcap).astype(np.int32)),
         jnp.asarray(rng.integers(50, 100, rcap).astype(np.int32)),
         jnp.asarray(rng.integers(0, 100, (rcap, 3)).astype(np.int32)),
         jnp.asarray(rng.integers(0, 6, rcap).astype(np.int32)),
         jnp.asarray(rng.random(rcap) < 0.9)) for _ in range(nrs))
    sst = rng.integers(0, ns, b).astype(np.int32)
    sb = rng.integers(50, 150, (b, 3)).astype(np.int32)
    sknd = rng.integers(0, 6, b).astype(np.int32)
    slots = np.arange(ns, dtype=np.int32)
    iv_of = rng.integers(0, b, z).astype(np.int32)
    iv_s = rng.integers(0, 80, z).astype(np.int32)
    iv_e = iv_s + rng.integers(1, 20, z).astype(np.int32)
    srng = rng.random(b) < 0.5
    rng_in = (tuple(map(jnp.asarray,
                        (iv_of, iv_s, iv_e, sst, sb, sknd, srng)))
              + (jnp.asarray(slots[:nrs]), rars,
                 jnp.asarray(slots), arenas))
    ref = protocol_tick(table, rng_in=rng_in)
    got = sharded_protocol_tick(mesh, table, rng_in=rng_in)
    for a, c in zip(ref[1], got[1]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(c))


@pytest.mark.slow
def test_mailbox_cross_shard_parity(mesh):
    """The same staged entries routed through the shards=1 single-device
    layout and the shards=data sharded layout must land identically --
    including a partition whose endpoints live on DIFFERENT shards."""
    data = mesh.shape["data"]
    table = jnp.asarray(WITNESS_TABLE)
    rng = np.random.default_rng(3)
    n = 6

    def mk_entries():
        ents = []
        for i in range(24):
            src = int(rng.integers(1, n + 1))
            dst = int(rng.integers(1, n + 1))
            e = _MailMsg(kind=1 + i % 3, src=src, dst=dst,
                         payload=bytes(
                             rng.integers(0, 256, 20).astype(np.uint8)))
            e.ticket = i
            ents.append(e)
        return ents

    state = rng.bit_generator.state
    ents1 = mk_entries()
    rng.bit_generator.state = state
    ents_s = mk_entries()
    # nodes 1 and 4 land on different shards (npsh = ceil(7/4) = 2)
    parts = {frozenset((1, 4))}

    p1 = MailboxPlane(n, depth=8, words=16, shards=1)
    p1.set_partitions(parts, version=1)
    p1.adopt(protocol_tick(table, mailbox=p1.stage_batch(ents1))[5])

    ps = MailboxPlane(n, depth=8, words=16, shards=data)
    ps.set_partitions(parts, version=1)
    ps.adopt(sharded_protocol_tick(
        mesh, table, mailbox=ps.stage_batch(ents_s))[5])

    for e1, es in zip(ents1, ents_s):
        r1, rs = p1.read_landed(e1), ps.read_landed(es)
        assert r1 == rs, (e1.src, e1.dst)
        if frozenset((e1.src, e1.dst)) == frozenset((1, 4)):
            assert r1 is None
        else:
            assert r1 == e1.payload


# -- burn differentials (sharded engine vs single-device vs host loop) --------

@pytest.mark.slow
def test_sharded_burn_matches_single_device_and_host():
    kw = dict(ops=30, nodes=3, collect_log=True)
    host, _ = run_mesh_burn(5, megakernel=False, mesh_tick=False, **kw)
    single, _ = run_mesh_burn(5, megakernel=True, **kw)
    sh, _ = run_mesh_burn(5, megakernel=True, sharded=True, **kw)
    assert host.log == single.log
    assert host.log == sh.log
    _gate_fused(sh.counters)


@pytest.mark.slow
def test_sharded_burn_range_traffic():
    kw = dict(ops=25, nodes=3, range_read_ratio=0.3,
              range_write_ratio=0.2, collect_log=True)
    loop, _ = run_mesh_burn(9, megakernel=False, mesh_tick=False, **kw)
    sh, _ = run_mesh_burn(9, megakernel=True, sharded=True, **kw)
    assert loop.log == sh.log
    _gate_fused(sh.counters)


@pytest.mark.slow
def test_sharded_device_messages_match_host():
    kw = dict(ops=30, nodes=3, megakernel=True, collect_log=True)
    host, _ = run_mesh_burn(5, **kw)
    dev, _ = run_mesh_burn(5, device_messages=True, sharded=True, **kw)
    assert host.log == dev.log
    c = dev.counters
    _gate_fused(c)
    assert c["device_messages_delivered"] > 0
    assert c["mailbox_verify_fallbacks"] == 0
    assert c["mailbox_overflow_spills"] == 0


@pytest.mark.slow
def test_sharded_chaos_crash_restart_parity():
    """Seeded drops + partitions (masks spanning shard boundaries) +
    crash/restart must stay bit-identical through the sharded plane."""
    kw = dict(ops=30, nodes=4, megakernel=True, collect_log=True,
              chaos_drop=0.05, chaos_partitions=True, crash_restart=True)
    host, _ = run_mesh_burn(23, **kw)
    dev, _ = run_mesh_burn(23, device_messages=True, sharded=True, **kw)
    assert host.log == dev.log
    assert dev.counters["mailbox_verify_fallbacks"] == 0


@pytest.mark.slow
def test_tiny_ring_spills_degrade_not_diverge():
    """A 2-slot ring cannot hold the traffic: entries spill to the host
    path (counted) and the committed history must not move."""
    kw = dict(ops=25, nodes=3, megakernel=True, collect_log=True,
              mailbox_depth=2, mailbox_words=16)
    host, _ = run_mesh_burn(5, **kw)
    dev, _ = run_mesh_burn(5, device_messages=True, sharded=True, **kw)
    assert host.log == dev.log
    c = dev.counters
    assert c["mailbox_overflow_spills"] > 0
    assert c["mailbox_verify_fallbacks"] == 0


# -- slow legs ----------------------------------------------------------------

@pytest.mark.slow
def test_sharded_chaos_seed_sweep():
    kw = dict(ops=40, nodes=4, megakernel=True, collect_log=True,
              chaos_drop=0.05, chaos_partitions=True)
    for seed in (7, 8, 9, 10):
        host, _ = run_mesh_burn(seed, **kw)
        dev, _ = run_mesh_burn(seed, device_messages=True, sharded=True,
                               **kw)
        assert host.log == dev.log, f"seed {seed} diverged"
        assert dev.counters["mailbox_verify_fallbacks"] == 0


@pytest.mark.slow
def test_sharded_reconcile_64_nodes():
    """The --reconcile contract at cluster scale: two same-seed sharded
    megakernel burns are bit-identical, and match the per-node loop."""
    kw = dict(ops=40, nodes=64, rf=5, collect_log=True)
    a, _ = run_mesh_burn(11, megakernel=True, sharded=True, **kw)
    b, _ = run_mesh_burn(11, megakernel=True, sharded=True, **kw)
    assert a.log == b.log, "sharded megakernel burn is non-deterministic"
    loop, _ = run_mesh_burn(11, megakernel=False, mesh_tick=False, **kw)
    assert a.log == loop.log
    _gate_fused(a.counters)
