"""The device execution scheduler (ops/exec_plane.py): the execute-order DAG
release frontier computed on device, differentially validated against the
host WaitingOn machinery.

Every burn here runs with the plane PRIMARY (releases come only from
harvested frontiers) while the host wait-graph stays live as the oracle:
ExecPlane._harvest asserts wo.is_done() at every release, so a premature
device release fails loudly under the test paranoia level.
"""
from __future__ import annotations

import numpy as np
import pytest

from accord_tpu.sim.burn import run_burn
from accord_tpu.sim.cluster import ClusterConfig


def test_frontier_kernel_matches_host_model():
    """Randomized differential test of execution_frontier against a naive
    host model of the gating rule."""
    import jax.numpy as jnp
    from accord_tpu.ops.kernels import execution_frontier

    rng = np.random.default_rng(7)
    cap = 64
    for trial in range(8):
        adj_bool = rng.random((cap, cap)) < 0.08
        np.fill_diagonal(adj_bool, False)
        exec_ts = rng.integers(-5, 5, (cap, 3)).astype(np.int32)
        undecided = rng.random(cap) < 0.2
        exec_ts[undecided] = np.iinfo(np.int32).min
        applied = rng.random(cap) < 0.4
        pending = rng.random(cap) < 0.6
        awaits_all = rng.random(cap) < 0.15

        def lex_le(a, b):
            return tuple(a) <= tuple(b)

        expect = np.zeros(cap, dtype=bool)
        for w in range(cap):
            if not pending[w]:
                continue
            gated = False
            for d in range(cap):
                if not adj_bool[w, d] or applied[d]:
                    continue
                if awaits_all[w] or lex_le(exec_ts[d], exec_ts[w]):
                    gated = True
                    break
            expect[w] = not gated

        out = np.asarray(execution_frontier(
            jnp.asarray(adj_bool), jnp.asarray(exec_ts),
            jnp.asarray(applied), jnp.asarray(pending),
            jnp.asarray(awaits_all)))
        got = np.unpackbits(out.view(np.uint8), bitorder="little")[:cap] > 0
        assert (got == expect).all(), f"trial {trial}: {np.nonzero(got != expect)}"


def test_burn_with_exec_plane_matches_host():
    host = run_burn(11, ops=80)
    dev = run_burn(11, ops=80, config=ClusterConfig(exec_plane=True))
    assert dev.acked == host.acked == 80
    assert dev.failed == host.failed == 0


def test_exec_plane_deterministic():
    a = run_burn(13, ops=80, collect_log=True,
                 config=ClusterConfig(exec_plane=True))
    b = run_burn(13, ops=80, collect_log=True,
                 config=ClusterConfig(exec_plane=True))
    assert a.log == b.log


@pytest.mark.parametrize("seed", (3, 9))
def test_exec_plane_under_chaos(seed):
    r = run_burn(seed, ops=100, chaos_drop=0.1, chaos_partitions=True,
                 config=ClusterConfig(exec_plane=True,
                                      durability=True,
                                      durability_interval_ms=500.0))
    assert r.lost == 0


def test_exec_plane_with_durability_truncation():
    r = run_burn(17, ops=120,
                 config=ClusterConfig(exec_plane=True, durability=True,
                                      durability_interval_ms=300.0))
    assert r.lost == 0
    assert r.failed == 0


def test_exec_plane_arena_stays_bounded():
    """A long burn with small initial capacity must compact dead history
    instead of growing without bound (rows live only while pending or
    referenced by a pending wait set)."""
    from accord_tpu.ops.exec_plane import ExecPlane
    orig_init = ExecPlane.__init__
    planes = []

    def spy(self, store, **kw):
        kw["initial_cap"] = 64
        orig_init(self, store, **kw)
        planes.append(self)

    ExecPlane.__init__ = spy
    try:
        r = run_burn(21, ops=400,
                     config=ClusterConfig(exec_plane=True, durability=True,
                                          durability_interval_ms=300.0))
    finally:
        ExecPlane.__init__ = orig_init
    assert r.lost == 0
    assert planes
    # 400 txns x rf over the cluster vastly exceeds 64 rows/store: without
    # compaction every plane would have doubled several times
    worst = max(p.cap for p in planes)
    assert worst <= 512, f"exec arena grew to {worst} despite compaction"


def test_dag_wavefronts_packed_matches_host_topo():
    """The 100k-DAG bench kernel (packed-word wavefronts) against a naive
    host topological-level model."""
    import jax.numpy as jnp
    from accord_tpu.ops.kernels import dag_wavefronts_packed

    n = 128
    rng = np.random.default_rng(3)
    adj_bool = np.zeros((n, n), bool)
    for w in range(1, n):
        for d in rng.integers(0, w, rng.integers(0, 4)):
            adj_bool[w, d] = True
    levels = np.zeros(n, int)
    for w in range(n):
        deps = np.nonzero(adj_bool[w])[0]
        levels[w] = 1 + max((levels[d] for d in deps), default=-1)
    packed = np.zeros((n, n // 32), np.uint32)
    for w, d in zip(*np.nonzero(adj_bool)):
        packed[w, d // 32] |= np.uint32(1 << (d % 32))
    got = np.asarray(dag_wavefronts_packed(jnp.asarray(packed), 64))
    assert (got == levels).all()
