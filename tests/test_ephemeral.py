"""Ephemeral reads: linearizable reads with no durable protocol state
(reference: CoordinateEphemeralRead + GetEphemeralReadDeps + the burn's
ephemeral generation, BurnTest.java:123). Single-key ephemeral reads are
strict-serializable, so the full cross-key verifier applies to every burn
here."""
from __future__ import annotations

import pytest

from accord_tpu.sim.burn import run_burn
from accord_tpu.sim.cluster import Cluster, ClusterConfig


def test_burn_with_ephemeral_reads():
    r = run_burn(5, ops=150, ephemeral_read_ratio=0.2)
    assert r.acked == 150
    assert r.failed == 0
    assert r.lost == 0


@pytest.mark.parametrize("seed", (3, 11, 19))
def test_ephemeral_reads_under_chaos(seed):
    r = run_burn(seed, ops=150, ephemeral_read_ratio=0.2,
                 chaos_drop=0.1, chaos_partitions=True,
                 config=ClusterConfig(durability=True,
                                      durability_interval_ms=500.0))
    assert r.lost == 0


def test_ephemeral_reads_deterministic():
    kw = dict(ops=120, ephemeral_read_ratio=0.25, collect_log=True)
    a = run_burn(7, **kw)
    b = run_burn(7, **kw)
    assert a.log == b.log


def test_ephemeral_read_sees_committed_write():
    """Real-time visibility: an ephemeral read issued after a write's ack
    must observe it (enforced by the verifier inside the burn, but assert
    the mechanism directly once)."""
    from accord_tpu.primitives.keyspace import Keys
    from accord_tpu.primitives.timestamp import TxnKind
    from accord_tpu.primitives.txn import Txn
    from accord_tpu.sim.list_store import ListQuery, ListRead, ListUpdate

    cluster = Cluster(1, ClusterConfig())
    node = cluster.nodes[1]
    key = 1234
    results = []
    write = Txn(TxnKind.WRITE, Keys([key]), read=ListRead(Keys([key])),
                update=ListUpdate(Keys([key]), 42), query=ListQuery())

    def after_write(result, failure):
        assert failure is None, failure
        eph = Txn(TxnKind.EPHEMERAL_READ, Keys([key]),
                  read=ListRead(Keys([key])), query=ListQuery())
        node.coordinate(eph).add_callback(
            lambda r, f: results.append((r, f)))

    node.coordinate(write).add_callback(after_write)
    cluster.drain(max_events=100000)
    assert results, "ephemeral read never completed"
    result, failure = results[0]
    assert failure is None, failure
    assert result.reads[key] == (42,), result.reads


def test_ephemeral_leaves_no_durable_state():
    """After an ephemeral-read-heavy burn, no command record for an
    EPHEMERAL_READ id exists on any store: the path persists nothing."""
    from accord_tpu.primitives.timestamp import TxnKind
    _last = {}
    orig = Cluster.__init__

    def spy(self, *a, **k):
        orig(self, *a, **k)
        _last["c"] = self

    Cluster.__init__ = spy
    try:
        r = run_burn(9, ops=100, ephemeral_read_ratio=0.3)
    finally:
        Cluster.__init__ = orig
    assert r.failed == 0
    for node in _last["c"].nodes.values():
        for store in node.command_stores.all():
            for txn_id in store.commands:
                assert txn_id.kind is not TxnKind.EPHEMERAL_READ, \
                    f"ephemeral read {txn_id} left a command record"


def test_ephemeral_reads_with_device_resolver():
    """Timestamp.MAX bounds are unencodable on device: the resolver must
    fall back to the host scan, not time out."""
    from accord_tpu.ops.resolver import BatchDepsResolver
    r = run_burn(5, ops=80, ephemeral_read_ratio=0.3,
                 config=ClusterConfig(
                     deps_resolver_factory=lambda: BatchDepsResolver(
                         num_buckets=256, initial_cap=512),
                     deps_batch_window_ms=2.0))
    assert r.acked == 80
    assert r.failed == 0


def test_ephemeral_reads_under_churn():
    r = run_burn(9, ops=150, ephemeral_read_ratio=0.2, topology_churn=True,
                 churn_interval_ms=1000.0,
                 config=ClusterConfig(num_nodes=4, rf=3, timeout_ms=4000.0,
                                      preaccept_timeout_ms=4000.0))
    assert r.lost == 0
