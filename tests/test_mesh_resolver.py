"""The multi-chip deps data plane IN the suite: the sharded resolver must be
differentially identical to the single-device kernel and the host scan, and
must carry a full burn. Runs on the conftest 8-device virtual CPU mesh
(reference scale analog: CommandStores range-splitting,
local/CommandStores.java:79 -- here the split is arena rows over 'data' and
key buckets over 'model')."""
from __future__ import annotations

import numpy as np
import pytest

from accord_tpu.parallel.mesh import make_mesh, sharded_deps_resolve
from accord_tpu.sim.burn import run_burn
from accord_tpu.sim.cluster import Cluster, ClusterConfig


def test_sharded_kernel_matches_single_device():
    """Pure kernel differential: sharded == unsharded on random arenas."""
    import jax
    import jax.numpy as jnp
    from accord_tpu.ops.kernels import deps_resolve

    mesh = make_mesh()
    assert mesh.shape["data"] * mesh.shape["model"] == len(jax.devices())
    kern = sharded_deps_resolve(mesh)
    from accord_tpu.parallel.mesh import example_resolve_batch
    for trial in range(3):
        args = tuple(jnp.asarray(a) for a in example_resolve_batch(
            cap=512, k=256, b=16, seed=trial))
        single = np.asarray(deps_resolve(*args))
        sharded = np.asarray(kern(*args))
        assert np.array_equal(single, sharded), f"trial {trial} diverged"


def _drive_writes(cluster, n):
    from accord_tpu.primitives.keyspace import Keys
    from accord_tpu.primitives.timestamp import TxnKind
    from accord_tpu.primitives.txn import Txn
    from accord_tpu.sim.list_store import ListQuery, ListRead, ListUpdate
    for v in range(1, n + 1):
        ks = Keys(sorted({100 + v % 7, 9000 + v % 3}))
        r = cluster.nodes[1 + v % 3].coordinate(
            Txn(TxnKind.WRITE, ks, read=ListRead(ks),
                update=ListUpdate(ks, v), query=ListQuery()))
        cluster.drain()
        assert r.done and r.failure is None, r.failure


def test_sharded_resolver_matches_host_and_single_device():
    """Same live store state, three resolvers, identical deps answers."""
    from accord_tpu.ops.resolver import (BatchDepsResolver,
                                         ShardedBatchDepsResolver)
    from accord_tpu.primitives.timestamp import Timestamp, TxnKind, Domain

    c = Cluster(31, ClusterConfig())
    _drive_writes(c, 24)
    node = c.nodes[1]
    single = BatchDepsResolver(num_buckets=256, initial_cap=512)
    sharded = ShardedBatchDepsResolver(mesh=make_mesh(),
                                       num_buckets=256, initial_cap=512)
    before = Timestamp(node.epoch, node.time_service.now_micros() + 10_000,
                       0, node.id)
    checked = 0
    for store in node.command_stores.all():
        for key, cfk in store.cfks.items():
            from accord_tpu.primitives.keyspace import Keys
            subj = node.next_txn_id(TxnKind.WRITE, Domain.KEY)
            owned = store.owned(Keys([key]))
            host = store.host_calculate_deps(subj, owned, before)
            d_single = single.resolve_one(store, subj, owned, before)
            d_sharded = sharded.resolve_one(store, subj, owned, before)
            def as_map(d):
                kd = d.key_deps
                return {k: kd.for_key(k) for k in kd.keys}
            assert as_map(d_single) == as_map(host), \
                f"single-device != host at key {key}"
            assert as_map(d_sharded) == as_map(host), \
                f"sharded != host at key {key}"
            checked += 1
    assert checked >= 5, f"only {checked} keys exercised"


def test_burn_with_sharded_resolver():
    """A full burn (with durability) on the mesh-sharded data plane."""
    from accord_tpu.ops.resolver import ShardedBatchDepsResolver

    factory = lambda: ShardedBatchDepsResolver(  # noqa: E731
        mesh=make_mesh(), num_buckets=256, initial_cap=512)
    r = run_burn(5, ops=120, write_ratio=0.8, key_count=16,
                 config=ClusterConfig(deps_resolver_factory=factory,
                                      deps_batch_window_ms=1.0,
                                      durability=True,
                                      durability_interval_ms=500.0))
    assert r.acked == 120
    assert r.failed == 0


def test_burn_sharded_matches_host_resolver_log():
    """Determinism ACROSS resolvers: the sharded device path must produce
    the exact event log of the host scan path (deps supersets could reorder
    execution; exact per-key decode means they must not)."""
    from accord_tpu.ops.resolver import ShardedBatchDepsResolver

    kw = dict(ops=80, write_ratio=0.8, key_count=12, collect_log=True)
    host = run_burn(9, config=ClusterConfig(), **kw)
    factory = lambda: ShardedBatchDepsResolver(  # noqa: E731
        mesh=make_mesh(), num_buckets=256, initial_cap=512)
    dev = run_burn(9, config=ClusterConfig(deps_resolver_factory=factory,
                                           deps_batch_window_ms=None),
                   **kw)
    assert host.acked == dev.acked == 80
