"""The multi-chip deps data plane IN the suite: the sharded resolver must be
differentially identical to the single-device kernel and the host scan, and
must carry a full burn. Runs on the conftest 8-device virtual CPU mesh
(reference scale analog: CommandStores range-splitting,
local/CommandStores.java:79 -- here the split is arena rows over 'data' and
key buckets over 'model')."""
from __future__ import annotations

import numpy as np
import pytest

from accord_tpu.parallel.mesh import make_mesh, sharded_deps_resolve
from accord_tpu.sim.burn import run_burn
from accord_tpu.sim.cluster import Cluster, ClusterConfig


def test_sharded_kernel_matches_single_device():
    """Pure kernel differential: sharded == unsharded on random arenas."""
    import jax
    import jax.numpy as jnp
    from accord_tpu.ops.kernels import deps_resolve

    mesh = make_mesh()
    assert mesh.shape["data"] * mesh.shape["model"] == len(jax.devices())
    kern = sharded_deps_resolve(mesh)
    from accord_tpu.parallel.mesh import example_resolve_batch
    for trial in range(3):
        args = tuple(jnp.asarray(a) for a in example_resolve_batch(
            cap=512, k=256, b=16, seed=trial))
        single = np.asarray(deps_resolve(*args))
        sharded = np.asarray(kern(*args))
        assert np.array_equal(single, sharded), f"trial {trial} diverged"


def _drive_writes(cluster, n):
    from accord_tpu.primitives.keyspace import Keys
    from accord_tpu.primitives.timestamp import TxnKind
    from accord_tpu.primitives.txn import Txn
    from accord_tpu.sim.list_store import ListQuery, ListRead, ListUpdate
    for v in range(1, n + 1):
        ks = Keys(sorted({100 + v % 7, 9000 + v % 3}))
        r = cluster.nodes[1 + v % 3].coordinate(
            Txn(TxnKind.WRITE, ks, read=ListRead(ks),
                update=ListUpdate(ks, v), query=ListQuery()))
        cluster.drain()
        assert r.done and r.failure is None, r.failure


def test_sharded_resolver_matches_host_and_single_device():
    """Same live store state, three resolvers, identical deps answers."""
    from accord_tpu.ops.resolver import (BatchDepsResolver,
                                         ShardedBatchDepsResolver)
    from accord_tpu.primitives.timestamp import Timestamp, TxnKind, Domain

    c = Cluster(31, ClusterConfig())
    _drive_writes(c, 24)
    node = c.nodes[1]
    single = BatchDepsResolver(num_buckets=256, initial_cap=512)
    sharded = ShardedBatchDepsResolver(mesh=make_mesh(),
                                       num_buckets=256, initial_cap=512)
    before = Timestamp(node.epoch, node.time_service.now_micros() + 10_000,
                       0, node.id)
    checked = 0
    for store in node.command_stores.all():
        for key, cfk in store.cfks.items():
            from accord_tpu.primitives.keyspace import Keys
            subj = node.next_txn_id(TxnKind.WRITE, Domain.KEY)
            owned = store.owned(Keys([key]))
            host = store.host_calculate_deps(subj, owned, before)
            d_single = single.resolve_one(store, subj, owned, before)
            d_sharded = sharded.resolve_one(store, subj, owned, before)
            def as_map(d):
                kd = d.key_deps
                return {k: kd.for_key(k) for k in kd.keys}
            assert as_map(d_single) == as_map(host), \
                f"single-device != host at key {key}"
            assert as_map(d_sharded) == as_map(host), \
                f"sharded != host at key {key}"
            checked += 1
    assert checked >= 5, f"only {checked} keys exercised"


def test_burn_with_sharded_resolver():
    """A full burn (with durability) on the mesh-sharded data plane."""
    from accord_tpu.ops.resolver import ShardedBatchDepsResolver

    factory = lambda: ShardedBatchDepsResolver(  # noqa: E731
        mesh=make_mesh(), num_buckets=256, initial_cap=512)
    r = run_burn(5, ops=120, write_ratio=0.8, key_count=16,
                 config=ClusterConfig(deps_resolver_factory=factory,
                                      deps_batch_window_ms=1.0,
                                      durability=True,
                                      durability_interval_ms=500.0))
    assert r.acked == 120
    assert r.failed == 0


def test_burn_sharded_matches_host_resolver_log():
    """Determinism ACROSS resolvers: the sharded device path must produce
    the exact event log of the host scan path (deps supersets could reorder
    execution; exact per-key decode means they must not)."""
    from accord_tpu.ops.resolver import ShardedBatchDepsResolver

    kw = dict(ops=80, write_ratio=0.8, key_count=12, collect_log=True)
    host = run_burn(9, config=ClusterConfig(), **kw)
    factory = lambda: ShardedBatchDepsResolver(  # noqa: E731
        mesh=make_mesh(), num_buckets=256, initial_cap=512)
    dev = run_burn(9, config=ClusterConfig(deps_resolver_factory=factory,
                                           deps_batch_window_ms=None),
                   **kw)
    assert host.acked == dev.acked == 80


def test_sharded_finalize_kernel_matches_single_device():
    """The sharded compaction twin: per-shard popcount/prefix fragments
    gather-merged into the global CSR must be BIT-identical to
    kernels.finalize_csr -- indptr, dep_rows, dep_ts and the fused bound
    scalar -- including fused word spans (word_off != 0) and overflow
    (where both sides must still report the exact total)."""
    import jax.numpy as jnp
    from accord_tpu.ops.kernels import finalize_csr
    from accord_tpu.parallel.mesh import sharded_finalize_csr

    mesh = make_mesh()
    data = mesh.shape["data"]
    cap = 32 * data * 4
    w = cap // 32
    kern = sharded_finalize_csr(mesh)
    rng = np.random.default_rng(23)
    overflowed = fit = 0
    for trial, (density, out_cap, spans, off) in enumerate(
            ((0.004, 256, 1, 0), (0.02, 256, 2, w), (0.5, 64, 1, 0))):
        b, s, kc = 8, 32, 64
        packed = (rng.random((b, spans * w, 32)) < density)
        packed = np.packbits(packed, axis=-1, bitorder="little") \
            .view(np.uint32).reshape(b, spans * w)
        kid = (rng.random((kc, w, 32)) < 0.1)
        kid = np.packbits(kid, axis=-1, bitorder="little") \
            .view(np.uint32).reshape(kc, w)
        args = (jnp.asarray(packed), jnp.asarray(off, jnp.int32),
                jnp.asarray(kid),
                jnp.asarray(rng.integers(-1, b + 2, s), jnp.int32),
                jnp.asarray(rng.integers(0, kc + 1, s), jnp.int32),
                jnp.asarray(rng.integers(-1, cap, b), jnp.int32),
                jnp.asarray(rng.integers(0, 1 << 20, (cap, 3)), jnp.int32))
        single = finalize_csr(*args, out_cap=out_cap)
        sharded = kern(*args, out_cap=out_cap)
        for name, a, c in zip(("indptr", "dep_rows", "dep_ts", "bound"),
                              single, sharded):
            assert np.array_equal(np.asarray(a), np.asarray(c)), \
                f"trial {trial}: sharded {name} != single-device"
        total = int(np.asarray(single[0])[-1])
        overflowed += total > out_cap
        fit += 0 < total <= out_cap
    assert overflowed and fit, "differential vacuous"


def test_model_sharded_kid_bound_matches_single_device():
    """The kid-table out-cap bound is popcounted over 'model'-axis slot
    blocks (each model replica sums a contiguous slice, psum merges):
    across nnz tiers and slot paddings the merged bound must stay
    BIT-identical to the single-device kernel's full reduction -- integer
    partial sums, so this is equality, not tolerance."""
    import jax.numpy as jnp
    from accord_tpu.ops.kernels import finalize_csr
    from accord_tpu.parallel.mesh import sharded_finalize_csr

    mesh = make_mesh()
    assert mesh.shape["model"] > 1, \
        "conftest mesh must exercise a real model axis"
    data = mesh.shape["data"]
    cap = 32 * data * 4
    w = cap // 32
    kern = sharded_finalize_csr(mesh)
    rng = np.random.default_rng(31)
    for s in (32, 64, 256):        # every nnz tier divides by the model axis
        b, kc = 16, 128
        packed = (rng.random((b, w, 32)) < 0.05)
        packed = np.packbits(packed, axis=-1, bitorder="little") \
            .view(np.uint32).reshape(b, w)
        kid = (rng.random((kc, w, 32)) < 0.2)
        kid = np.packbits(kid, axis=-1, bitorder="little") \
            .view(np.uint32).reshape(kc, w)
        args = (jnp.asarray(packed), jnp.asarray(0, jnp.int32),
                jnp.asarray(kid),
                jnp.asarray(rng.integers(-1, b + 2, s), jnp.int32),
                jnp.asarray(rng.integers(0, kc + 1, s), jnp.int32),
                jnp.asarray(rng.integers(-1, cap, b), jnp.int32),
                jnp.asarray(rng.integers(0, 1 << 20, (cap, 3)), jnp.int32))
        single = finalize_csr(*args, out_cap=2048)
        sharded = kern(*args, out_cap=2048)
        assert int(np.asarray(single[3])) == int(np.asarray(sharded[3])), \
            f"nnz {s}: model-sharded bound != single-device bound"
        assert int(np.asarray(single[3])) > 0, f"nnz {s}: bound vacuous"
        for name, a, c in zip(("indptr", "dep_rows", "dep_ts"),
                              single, sharded):
            assert np.array_equal(np.asarray(a), np.asarray(c)), \
                f"nnz {s}: sharded {name} != single-device"


def test_sharded_finalize_e2e_and_zero_recompiles():
    """The sharded resolver rides the finalized-CSR harvest end to end
    (answers == single-device == host, zero legacy decodes), and after
    warmup_sharded(out_tiers=...) the live workload mints NO new sharded
    finalize compiles -- the OutCapTiers rungs are the whole shape space."""
    from accord_tpu.ops.resolver import (BatchDepsResolver,
                                         ShardedBatchDepsResolver)
    from accord_tpu.parallel.mesh import sharded_finalize_csr, warmup_sharded
    from accord_tpu.primitives.keyspace import Keys
    from accord_tpu.primitives.timestamp import Domain, Timestamp, TxnKind

    c = Cluster(37, ClusterConfig())
    _drive_writes(c, 24)
    node = c.nodes[1]
    mesh = make_mesh()
    # resolve_one dispatches pad to batch tier 8 / nnz tier 32; the cold
    # first pick seeds from the exact bound (small workload -> first rung)
    warmup_sharded(mesh, num_buckets=256, cap=512, batch_tiers=(8,),
                   nnz_tiers=(32,), store_tiers=(1,), out_tiers=(256,))
    fin = sharded_finalize_csr(mesh)
    warmed = fin._cache_size()
    assert warmed > 0

    sharded = ShardedBatchDepsResolver(mesh=mesh, num_buckets=256,
                                       initial_cap=512)
    single = BatchDepsResolver(num_buckets=256, initial_cap=512)
    before = Timestamp(node.epoch, node.time_service.now_micros() + 10_000,
                       0, node.id)
    checked = 0
    for store in node.command_stores.all():
        for key in store.cfks:
            subj = node.next_txn_id(TxnKind.WRITE, Domain.KEY)
            owned = store.owned(Keys([key]))
            host = store.host_calculate_deps(subj, owned, before)
            assert single.resolve_one(store, subj, owned, before) == host
            assert sharded.resolve_one(store, subj, owned, before) == host
            checked += 1
    assert checked >= 5, f"only {checked} keys exercised"
    assert sharded.finalized_decodes > 0, "sharded finalize never engaged"
    assert sharded.legacy_decodes == 0
    assert sharded.finalize_fallbacks == 0
    assert sharded.host_fallbacks == 0
    assert sharded.shard_merge_s > 0.0, "sharded merge timer never ran"
    assert fin._cache_size() == warmed, \
        "live workload minted sharded finalize compiles past warmup"
