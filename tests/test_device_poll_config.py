"""device_poll_ms wiring (ROADMAP item 3): the readiness-poll cadence is a
node-construction parameter plumbed through ClusterConfig. Default OFF under
the sim scheduler (poll events occupy event-queue slots, so a polled burn's
history differs from an unpolled one -- each is internally deterministic,
but the default must not perturb existing seeds); defaulted ON for the
maelstrom real-device deploy, where there is no simulated history to
protect."""
from __future__ import annotations

from accord_tpu.sim.cluster import Cluster, ClusterConfig


def test_sim_default_is_off():
    c = Cluster(1, ClusterConfig(num_nodes=2, rf=2))
    assert all(n.device_poll_ms is None for n in c.nodes.values())


def test_cluster_config_plumbs_poll_to_nodes():
    c = Cluster(1, ClusterConfig(num_nodes=2, rf=2, device_poll_ms=1.5))
    assert all(n.device_poll_ms == 1.5 for n in c.nodes.values())


def test_maelstrom_node_defaults_poll_on():
    from accord_tpu.maelstrom.runner import Runner
    runner = Runner(seed=3, num_nodes=2)
    for mn in runner.nodes.values():
        assert mn.node.device_poll_ms is not None


def test_polled_burn_arms_prefetch_and_replays_bit_identically():
    """With device_poll_ms set via ClusterConfig, the async pipeline arms
    the readiness poll on every node, and two identically-seeded burns stay
    bit-identical (the poll only fills host-side caches)."""
    from accord_tpu.ops.resolver import BatchDepsResolver
    from accord_tpu.sim.burn import run_burn

    def leg():
        resolvers = []

        def factory():
            r = BatchDepsResolver(num_buckets=128)
            resolvers.append(r)
            return r

        cfg = ClusterConfig(deps_resolver_factory=factory,
                            deps_batch_window_ms=1.0,
                            device_latency_ms=8.0,
                            device_poll_ms=1.0)
        rep = run_burn(17, ops=60, key_count=8, concurrency=6,
                       collect_log=True, config=cfg)
        return rep, resolvers

    rep_a, res_a = leg()
    rep_b, _ = leg()
    assert rep_a.acked == rep_b.acked == 60
    assert rep_a.lost == 0
    assert rep_a.log == rep_b.log
    # the poll actually armed at least once (dispatches happened with the
    # per-node cadence configured)
    assert sum(r.dispatches for r in res_a) > 0
    assert any(r.polls_armed > 0 for r in res_a)


def test_unpolled_burn_unperturbed_by_config_default():
    """The config default (None) reproduces the pre-wiring histories: a burn
    with an explicit None matches one built with no mention of the knob."""
    from accord_tpu.ops.resolver import BatchDepsResolver
    from accord_tpu.sim.burn import run_burn

    def leg(**extra):
        cfg = ClusterConfig(
            deps_resolver_factory=lambda: BatchDepsResolver(num_buckets=128),
            deps_batch_window_ms=1.0, device_latency_ms=8.0, **extra)
        return run_burn(23, ops=50, key_count=8, concurrency=6,
                        collect_log=True, config=cfg)

    assert leg().log == leg(device_poll_ms=None).log
