"""Device-compacted execution frontier + recovery scans (ops/kernels
frontier_compact / recovery_scan, ops/exec_plane compacted harvests,
ops/cmd_plane + impl/progress candidate scans).

Tier-1 legs here are compile-free: numpy checksum twins, stub-store
counter paths, the _consume_compact degradation contract driven with
hand-built host lanes, and the progress-sweep filter over stub planes.
Every leg that compiles a kernel or runs a burn is marked `slow` (the
tier-1 suite sits ~2% under its timeout).
"""
from __future__ import annotations

import numpy as np
import pytest


# -- tier 1: compile-free units ---------------------------------------------

def test_frontier_checksum_host_position_weighted():
    """The host checksum twin must be order- and value-sensitive (a swap
    or a bit flip in either lane changes the fold) and deterministic."""
    from accord_tpu.ops.kernels import frontier_checksum_host

    indptr = np.asarray([0, 3, 5], np.int32)
    rows = np.asarray([1, 4, 9, 2, 7, 0, 0, 0], np.int32)
    base = frontier_checksum_host(indptr, rows)
    assert base == frontier_checksum_host(indptr.copy(), rows.copy())
    swapped = rows.copy()
    swapped[0], swapped[1] = swapped[1], swapped[0]
    assert frontier_checksum_host(indptr, swapped) != base
    bumped = rows.copy()
    bumped[2] += 1
    assert frontier_checksum_host(indptr, bumped) != base
    assert frontier_checksum_host(
        np.asarray([0, 2, 5], np.int32), rows) != base


class _Sched:
    def __init__(self):
        self.calls = []

    def once(self, delay_ms, fn):
        self.calls.append((delay_ms, fn))


class _Node:
    def __init__(self):
        self.scheduler = _Sched()
        self.device_poll_ms = None


class _Store:
    def __init__(self):
        self.node = _Node()

    def command_if_present(self, txn_id):
        return None


def test_gen_drop_counts_and_reticks():
    """A frontier harvested after compaction bumped the generation is
    dropped, counted (exec.dropped_frontiers), and re-arms the tick --
    previously the drop was silent."""
    from accord_tpu.ops.exec_plane import ExecPlane

    plane = ExecPlane(_Store(), initial_cap=64)
    plane._gen = 3
    plane._apply_rows([1, 2, 5], gen=2)
    assert plane.dropped_frontiers == 1
    assert plane.releases == 0
    assert plane.store.node.scheduler.calls, "drop must re-arm the tick"
    # the legacy bitmask path shares the same drop gate
    plane._apply_frontier(np.zeros(2, np.uint32), gen=0)
    assert plane.dropped_frontiers == 2


class _RecPlane:
    """Recording stand-in for an ExecPlane inside _consume_compact."""

    def __init__(self):
        self.rows_calls = []
        self.frontier_calls = []

    def _apply_rows(self, rows, gen):
        self.rows_calls.append((list(rows), gen))

    def _apply_frontier(self, packed, gen):
        self.frontier_calls.append((np.asarray(packed).copy(), gen))


class _Owner:
    def __init__(self):
        self.readback_bytes = 0
        self.readback_full_equiv = 0
        self.compact_fallbacks = 0
        self.compact_overflows = 0
        self.observed = []
        self._out_tiers = None

    def _observe_bound(self, total):
        self.observed.append(total)


def _two_plane_fixture():
    """Two 64-row planes (2 u32 words each): plane0 releases rows {1, 40},
    plane1 releases {3}. Returns (host lanes, packed bitmask, planes)."""
    from accord_tpu.ops.kernels import frontier_checksum_host

    packed = np.zeros(4, np.uint32)
    packed[0] = 1 << 1          # plane0 row 1   (global bit 1)
    packed[1] = 1 << 8          # plane0 row 40  (global bit 40)
    packed[2] = 1 << 3          # plane1 row 3   (global bit 67)
    indptr = np.asarray([0, 2, 3], np.int32)
    rows = np.zeros(8, np.int32)
    rows[:3] = (1, 40, 67)
    csum = frontier_checksum_host(indptr, rows)
    return (indptr, rows, csum), packed, (_RecPlane(), _RecPlane())


def test_consume_compact_direct_slice():
    """Good checksum, within cap: each plane gets its compaction segment
    rebased to local rows; the retained bitmask is never fetched."""
    from accord_tpu.ops.exec_plane import _consume_compact

    host, packed, (p0, p1) = _two_plane_fixture()
    owner = _Owner()
    entries = [(p0, (0, 2), 7), (p1, (2, 4), 9)]
    _consume_compact(owner, (None, None, None, packed), host, entries, 8)
    assert p0.rows_calls == [([1, 40], 7)]
    assert p1.rows_calls == [([3], 9)]
    assert not p0.frontier_calls and not p1.frontier_calls
    assert owner.readback_full_equiv == 4 * 4
    assert owner.readback_bytes == host[0].nbytes + host[1].nbytes + 4
    assert owner.observed == [3]
    assert owner.compact_fallbacks == 0 and owner.compact_overflows == 0


def test_consume_compact_checksum_fallback():
    """A corrupt readback falls back to decoding the retained bitmask --
    counted, and the release set is identical to the direct slice."""
    from accord_tpu.ops.exec_plane import _consume_compact

    host, packed, (p0, p1) = _two_plane_fixture()
    indptr, rows, csum = host
    bad = (indptr, rows, csum ^ 0x5A5A)
    owner = _Owner()
    entries = [(p0, (0, 2), 7), (p1, (2, 4), 9)]
    _consume_compact(owner, (None, None, None, packed), bad, entries, 8)
    assert owner.compact_fallbacks == 1
    assert not p0.rows_calls and not p1.rows_calls
    (pk0, g0), = p0.frontier_calls
    (pk1, g1), = p1.frontier_calls
    assert (g0, g1) == (7, 9)
    # the spans decode to the same release set the direct slice carries
    def decode(pk):
        return np.nonzero(np.unpackbits(pk.view(np.uint8),
                                        bitorder="little"))[0].tolist()
    assert decode(pk0) == [1, 40]
    assert decode(pk1) == [3]
    # fallback pays the full-bitmask fetch on top of the compact lanes
    assert owner.readback_bytes > host[0].nbytes + host[1].nbytes + 4


def test_consume_compact_overflow_bumps_tier():
    """indptr's bound is exact even past out_cap: the overflow is counted,
    observed, and the tier ladder bumps for the next dispatch."""
    from accord_tpu.ops.exec_plane import _consume_compact
    from accord_tpu.ops.kernels import FRONTIER_OUT_TIERS
    from accord_tpu.ops.tiers import OutCapTiers

    host, packed, (p0, p1) = _two_plane_fixture()
    owner = _Owner()
    owner._out_tiers = OutCapTiers(FRONTIER_OUT_TIERS,
                                   FRONTIER_OUT_TIERS[-1] * 2)
    before = owner._out_tiers.pick(1)
    entries = [(p0, (0, 2), 7), (p1, (2, 4), 9)]
    _consume_compact(owner, (None, None, None, packed), host, entries, 2)
    assert owner.compact_overflows == 1
    assert owner.observed == [3]
    assert p0.frontier_calls and p1.frontier_calls  # legacy decode served it
    assert owner._out_tiers.pick(1) > before


def test_recovery_scan_host_predicate_twin():
    """CmdPlane.recovery_scan_host against a pure-python fold of the same
    predicate: live status band (terminals above APPLIED excluded) and
    stall age, candidates row-ascending."""
    from accord_tpu.ops.cmd_plane import CmdPlane
    from accord_tpu.ops.kernels import (CMD_ST_APPLIED,
                                        CMD_ST_PRE_ACCEPTED)

    plane = CmdPlane(_Store(), initial_cap=64, apply_to_store=False)
    rng = np.random.default_rng(11)
    n = 40
    plane.n_rows = n
    plane.status_h[:n] = rng.integers(0, 12, n)
    plane.touched_h[:n] = rng.integers(0, 900, n)
    tids = [f"t{i}" for i in range(n)]
    plane.tid_by_row = list(tids)
    plane.row_of = {t: i for i, t in enumerate(tids)}
    now, stall = 1000, 300
    expect = [tids[i] for i in range(n)
              if CMD_ST_PRE_ACCEPTED <= plane.status_h[i] < CMD_ST_APPLIED
              and now - plane.touched_h[i] >= stall]
    assert plane.recovery_scan_host(now, stall) == expect
    assert expect, "fixture must produce candidates"


def test_sweep_waiters_scan_filter():
    """Under a recovery-scan mode the sweep walks scan candidates still in
    the live-waiter index, plus any waiter the arena has never seen."""
    from accord_tpu.impl.progress import ProgressEngine

    class _CmdPlaneStub:
        row_of = {"a": 0, "b": 1, "c": 2}

        def recovery_scan_host(self, now, stall):
            return ["a", "b", "c"]

    class _StoreStub:
        cmd_plane = _CmdPlaneStub()
        live_waiters = {"a", "c", "unrowed"}

    class _NodeStub:
        @staticmethod
        def now_millis():
            return 1000.0

    eng = ProgressEngine(interval_ms=10.0, recovery_scan="host")
    eng.node = _NodeStub()
    got = eng._sweep_waiters(_StoreStub())
    assert got == ["a", "c", "unrowed"]
    # reference mode: the whole index, untouched
    eng.recovery_scan = None
    assert sorted(eng._sweep_waiters(_StoreStub())) == \
        sorted(["a", "c", "unrowed"])


# -- slow: compiled differentials -------------------------------------------

@pytest.mark.slow
def test_frontier_compact_matches_bitmask_randomized():
    """Randomized compacted-vs-bitmask differential across plane counts
    and out caps, including the overflow regime (indptr stays exact)."""
    import jax.numpy as jnp
    from accord_tpu.ops.kernels import (execution_frontier,
                                        frontier_checksum_host,
                                        frontier_compact)

    rng = np.random.default_rng(23)
    cap = 64
    w = cap // 32

    def rand_plane():
        adj = rng.random((cap, cap)) < 0.06
        np.fill_diagonal(adj, False)
        ets = rng.integers(-5, 40, (cap, 3)).astype(np.int32)
        ets[rng.random(cap) < 0.2] = np.iinfo(np.int32).min
        return (jnp.asarray(adj), jnp.asarray(ets),
                jnp.asarray(rng.random(cap) < 0.35),
                jnp.asarray(rng.random(cap) < 0.6),
                jnp.asarray(rng.random(cap) < 0.1))

    for n_planes in (1, 2):
        for trial in range(4):
            planes = tuple(rand_plane() for _ in range(n_planes))
            legacy = []
            for pl in planes:
                packed = np.asarray(execution_frontier(*pl))
                legacy.append(np.nonzero(np.unpackbits(
                    packed.view(np.uint8), bitorder="little"))[0])
            total = sum(len(r) for r in legacy)
            for out_cap in (4, 128):
                indptr, rows, csum, pk = frontier_compact(
                    planes, out_cap=out_cap)
                indptr = np.asarray(indptr)
                rows = np.asarray(rows)
                assert int(indptr[-1]) == total  # exact even on overflow
                assert frontier_checksum_host(indptr, rows) == \
                    int(np.asarray(csum))
                if total <= out_cap:
                    for i, exp in enumerate(legacy):
                        seg = rows[indptr[i]:indptr[i + 1]] - 32 * (i * w)
                        assert seg.tolist() == exp.tolist(), \
                            (n_planes, trial, out_cap, i)


@pytest.mark.slow
def test_recovery_scan_kernel_matches_host_twin():
    """kernels.recovery_scan vs CmdPlane._stalled_mask over random arenas."""
    import jax.numpy as jnp
    from accord_tpu.ops.cmd_plane import CmdPlane
    from accord_tpu.ops.kernels import (frontier_checksum_host,
                                        recovery_scan)

    rng = np.random.default_rng(31)
    plane = CmdPlane(_Store(), initial_cap=128, apply_to_store=False)
    for trial in range(4):
        plane.status_h[:] = rng.integers(0, 12, plane.cap)
        plane.touched_h[:] = rng.integers(0, 2000, plane.cap)
        now, stall = 2500, 600
        expect = np.nonzero(plane._stalled_mask(now, stall))[0]
        indptr, rows, csum = recovery_scan(
            jnp.asarray(plane.status_h), jnp.asarray(plane.touched_h),
            np.int32(now), np.int32(stall), out_cap=plane.cap)
        indptr, rows = np.asarray(indptr), np.asarray(rows)
        assert frontier_checksum_host(indptr, rows) == \
            int(np.asarray(csum))
        assert rows[:int(indptr[-1])].tolist() == expect.tolist(), trial


@pytest.mark.slow
def test_exec_megakernel_bit_identical():
    """Standalone compact coordinator vs exec-in-megakernel staging: same
    histories, launches_per_tick == 1.0 with exec traffic included, and
    the engine ledger shows exec blocks riding fused launches."""
    from accord_tpu.sim.mesh_burn import run_mesh_burn

    base = dict(ops=40, nodes=4, rf=3, stores_per_node=2, key_count=24,
                concurrency=8, collect_log=True, exec_plane=True,
                exec_compact=True)
    r0, _ = run_mesh_burn(13, megakernel=True, **base)
    r1, _ = run_mesh_burn(13, megakernel=True, exec_in_megakernel=True,
                          **base)
    assert r0.log == r1.log
    assert r1.counters["launches_per_tick"] == 1.0
    assert r1.counters["exec_scan_blocks"] > 0
    assert r1.counters.get("exec_coord.staged_blocks", 0) > 0
    assert r1.counters.get("exec_coord.compact_fallbacks", 0) == 0


@pytest.mark.slow
def test_recovery_scan_burn_device_matches_host():
    """Crash-restart burn: device recovery scan commits bit-identical
    histories to the host-scan baseline, with zero counted fallbacks."""
    from accord_tpu.sim.mesh_burn import run_mesh_burn

    base = dict(ops=40, nodes=4, rf=3, stores_per_node=2, key_count=24,
                concurrency=8, collect_log=True, cmd_plane=True,
                crash_restart=True)
    rh, _ = run_mesh_burn(17, megakernel=True, recovery_scan="host", **base)
    rd, _ = run_mesh_burn(17, megakernel=True, recovery_scan="device",
                          **base)
    assert rh.log == rd.log
    assert rd.counters.get("recovery_scan_dispatches", 0) > 0
    assert rd.counters.get("recovery_scan_fallbacks", 0) == 0
    assert rd.counters.get("recovery_scan_overflows", 0) == 0
