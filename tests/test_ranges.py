"""Range transactions end-to-end: range-domain reads in the burn (alone,
under churn+chaos, with durability, and with the device resolver), plus the
interval index (reference: SearchableRangeList/CINTIA) unit-tested against a
naive model."""
from __future__ import annotations

import pytest

from accord_tpu.sim.burn import run_burn
from accord_tpu.sim.cluster import ClusterConfig
from accord_tpu.utils.interval_index import IntervalIndex
from accord_tpu.utils.rng import RandomSource


def test_interval_index_vs_naive():
    rng = RandomSource(3)
    idx = IntervalIndex()
    model = {}
    for i in range(300):
        op = rng.next_int(10)
        if op < 7 or not model:
            s = rng.next_int(1000)
            e = s + 1 + rng.next_int(50)
            idx.add(i, s, e)
            model.setdefault(i, []).append((s, e))
        else:
            victim = rng.pick(sorted(model))
            idx.remove(victim)
            del model[victim]
        if i % 20 == 0:
            for probe in (rng.next_int(1100) for _ in range(10)):
                got = set(idx.stab(probe))
                want = {v for v, ivs in model.items()
                        if any(s <= probe < e for s, e in ivs)}
                assert got == want, (probe, got, want)
            s = rng.next_int(1000)
            e = s + 1 + rng.next_int(80)
            got = set(idx.over(s, e))
            want = {v for v, ivs in model.items()
                    if any(a < e and b > s for a, b in ivs)}
            assert got == want


def test_range_read_burn():
    r = run_burn(3, ops=200, range_read_ratio=0.25)
    assert r.acked == 200 and r.lost == 0


def test_range_reads_with_durability():
    r = run_burn(7, ops=300, range_read_ratio=0.25,
                 config=ClusterConfig(durability=True,
                                      durability_interval_ms=500.0))
    assert r.acked == 300 and r.lost == 0


@pytest.mark.parametrize("seed", (3, 8, 13))
def test_range_reads_under_churn_chaos(seed):
    cfg = ClusterConfig(num_nodes=4, rf=3, timeout_ms=4000.0,
                        preaccept_timeout_ms=4000.0)
    r = run_burn(seed, ops=300, range_read_ratio=0.25, topology_churn=True,
                 churn_interval_ms=1000.0, chaos_drop=0.05,
                 chaos_partitions=True, config=cfg)
    assert r.lost == 0
    assert r.failed <= 60


def test_range_reads_device_differential():
    """Inline device mode must be bit-identical to the host path with range
    reads mixed in (range subjects ride the host scan; key subjects ride the
    kernel; range txns union in via host_range_deps)."""
    from accord_tpu.ops.resolver import BatchDepsResolver
    host = run_burn(11, ops=80, range_read_ratio=0.25, collect_log=True)
    dev = run_burn(11, ops=80, range_read_ratio=0.25, collect_log=True,
                   config=ClusterConfig(
                       deps_resolver_factory=lambda: BatchDepsResolver(num_buckets=128),
                       deps_batch_window_ms=None))
    assert host.acked == dev.acked == 80
    assert host.log == dev.log


def test_range_reads_device_async_deterministic():
    from accord_tpu.ops.resolver import BatchDepsResolver

    def cfg():
        return ClusterConfig(
            deps_resolver_factory=lambda: BatchDepsResolver(num_buckets=128),
            deps_batch_window_ms=2.0, device_latency_ms=8.0)

    a = run_burn(11, ops=80, range_read_ratio=0.25, collect_log=True,
                 config=cfg())
    b = run_burn(11, ops=80, range_read_ratio=0.25, collect_log=True,
                 config=cfg())
    assert a.acked == 80 and a.lost == 0
    assert a.log == b.log


def test_range_write_burn():
    """Range-domain WRITES through the RangeDeps machinery (VERDICT r4 item
    8: the burn previously generated range READS only)."""
    r = run_burn(5, ops=200, range_read_ratio=0.1, range_write_ratio=0.2,
                 write_ratio=0.6)
    assert r.acked == 200 and r.lost == 0


def test_range_writes_with_durability_truncation():
    r = run_burn(9, ops=300, range_read_ratio=0.1, range_write_ratio=0.2,
                 config=ClusterConfig(durability=True,
                                      durability_interval_ms=400.0))
    assert r.acked == 300 and r.lost == 0


@pytest.mark.parametrize("seed", (4, 11, 19))
def test_range_writes_under_churn_chaos(seed):
    cfg = ClusterConfig(num_nodes=4, rf=3, timeout_ms=4000.0,
                        preaccept_timeout_ms=4000.0)
    r = run_burn(seed, ops=250, range_read_ratio=0.1, range_write_ratio=0.2,
                 topology_churn=True, churn_interval_ms=1000.0,
                 chaos_drop=0.05, chaos_partitions=True, config=cfg)
    assert r.lost == 0


def test_range_writes_deterministic():
    kw = dict(ops=150, range_read_ratio=0.1, range_write_ratio=0.2,
              collect_log=True)
    a = run_burn(6, **kw)
    b = run_burn(6, **kw)
    assert a.log == b.log
