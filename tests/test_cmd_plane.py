"""Differential tests of the device command plane (ops/cmd_plane.py): with
and without cmd_plane the engine must produce BIT-identical outcomes, status
histories, executeAt choices, promised/accepted ballots and HLC clocks -- the
kernel (ops/kernels.cmd_tick) re-expresses local/commands.py, it does not
approximate it. The randomized script deliberately drives the awkward
interleavings: ballot contention, redundant re-delivery, compaction in
flight, truncation floors (where the plane must FALL BACK, identically)."""
from __future__ import annotations

import random

import pytest

from accord_tpu.local import commands
from accord_tpu.local.commands import AcceptOutcome, CommitOutcome
from accord_tpu.local.status import Status
from accord_tpu.primitives.deps import Deps, KeyDeps
from accord_tpu.primitives.keyspace import Keys
from accord_tpu.primitives.timestamp import Ballot, Timestamp, TxnKind
from accord_tpu.primitives.txn import Txn
from accord_tpu.sim.cluster import Cluster, ClusterConfig
from accord_tpu.sim.list_store import ListQuery, ListRead, ListUpdate

pytestmark = pytest.mark.cmd_plane


def _env(cmd_plane: bool):
    cluster = Cluster(1, ClusterConfig(num_nodes=1, rf=1, num_shards=1,
                                       stores_per_node=1, progress=False,
                                       cmd_plane=cmd_plane))
    node = cluster.nodes[1]
    return cluster, node, node.command_stores.stores[0]


def _mk_txn(keys, value):
    k = Keys(sorted(keys))
    return Txn(TxnKind.WRITE, k, read=ListRead(k),
               update=ListUpdate(k, value), query=ListQuery())


def _snap(store, node, tid):
    cmd = store.command_if_present(tid)
    if cmd is None:
        return ("absent", node._last_hlc)
    return (int(cmd.status), cmd.execute_at, cmd.promised,
            cmd.accepted_ballot, cmd.txn is not None, int(cmd.durability),
            node._last_hlc)


def _script(rng: random.Random, n_ops: int):
    """Abstract op script over txn refs; realized identically per env."""
    ops = []
    n_txns = 0
    live = []
    for _ in range(n_ops):
        r = rng.random()
        if r < 0.35 or not live:
            ref = n_txns
            n_txns += 1
            live.append(ref)
            keys = rng.sample(range(1, 9), rng.randint(1, 3))
            ops.append(("new", ref, tuple(keys), ref + 1))
        else:
            ref = rng.choice(live)
            r2 = rng.random()
            if r2 < 0.2:
                # ballot contention: recovery-style re-preaccept (possibly
                # a LOWER ballot, which must be rejected)
                ops.append(("re_pa", ref, rng.choice((0, 1, 2, 5))))
            elif r2 < 0.45:
                ops.append(("accept", ref, rng.choice((1, 2, 5)),
                            rng.randint(0, 50), rng.random() < 0.5))
            elif r2 < 0.75:
                ops.append(("commit", ref, rng.random() < 0.2))
            else:
                ops.append(("apply", ref))
        if rng.random() < 0.06:
            ops.append(("compact",))
    return ops


def _realize(env, script, batch_plane: bool, compact_live: bool):
    """Run the script against one env; returns the full history. With
    batch_plane the device side routes contiguous runs through
    CmdPlane.eval_batch (exercising the multi-op kernel carry); the host
    side always calls the Python handlers one by one."""
    cluster, node, store = env
    hist = []
    tids, txns, routes = {}, {}, {}

    def _ids(ref):
        return tids[ref], txns[ref], routes[ref]

    def run_one(op):
        kind = op[0]
        if kind == "compact":
            if compact_live and store.cmd_plane is not None:
                store.cmd_plane.compact()
            hist.append(("compacted",))
            return
        ref = op[1]
        if kind == "new":
            txn = _mk_txn(op[2], op[3])
            tid = node.next_txn_id(txn.kind, txn.domain)
            tids[ref], txns[ref] = tid, txn
            routes[ref] = node.compute_route(txn)
            out = store.submit_preaccept(
                tid, txn.slice(store.ranges, include_query=False),
                routes[ref])
            got = {}
            out.on_success(lambda v: got.update(v=v))
            assert "v" in got or out.done
            outcome = got["v"][0]
        elif kind == "re_pa":
            tid, txn, route = _ids(ref)
            ballot = Ballot.ZERO if op[2] == 0 else Ballot(1, op[2], 0, 1)
            if store.cmd_plane is not None and batch_plane:
                from accord_tpu.ops.cmd_plane import CmdOp
                outcome = store.cmd_plane.eval_batch([CmdOp.preaccept(
                    tid, txn.slice(store.ranges, include_query=False),
                    route, ballot)])[0].outcome
            else:
                outcome = commands.preaccept(
                    store, tid,
                    txn.slice(store.ranges, include_query=False), route,
                    ballot)
        elif kind == "accept":
            tid, txn, route = _ids(ref)
            cmd = store.command_if_present(tid)
            base = cmd.execute_at if cmd is not None \
                and cmd.execute_at is not None else tid
            proposal = Timestamp(base.epoch, base.hlc + op[3], 0, 1)
            deps = Deps(KeyDeps.of(
                {sorted(txn.keys)[0]: [tid]})) if op[4] else None
            outcome = store.accept_op(tid, Ballot(1, op[2], 0, 1), route,
                                      store.owned(txn.keys), proposal, deps)
        elif kind == "commit":
            tid, txn, route = _ids(ref)
            cmd = store.command_if_present(tid)
            ea = cmd.execute_at if cmd is not None \
                and cmd.execute_at is not None else tid.as_timestamp()
            if op[2]:   # inconsistent-timestamp probe on redundant delivery
                ea = Timestamp(ea.epoch, ea.hlc + 1, ea.flags, ea.node)
            outcome = store.commit_op(
                tid, route, txn.slice(store.ranges, include_query=False),
                ea, Deps.NONE)
        else:   # apply
            tid, txn, route = _ids(ref)
            cmd = store.command_if_present(tid)
            ea = cmd.execute_at if cmd is not None \
                and cmd.execute_at is not None else tid.as_timestamp()
            outcome = store.apply_op(
                tid, route, txn.slice(store.ranges, include_query=False),
                ea, Deps.NONE, None, None)
        hist.append((kind, ref, outcome, _snap(store, node, tids[ref])))
        cluster.drain()

    for op in script:
        run_one(op)
    return hist


def _differential(seed: int, compact_live: bool = True,
                  truncate: bool = False) -> None:
    rng = random.Random(seed)
    script = _script(rng, 60)
    hists = []
    for flag in (False, True):
        env = _env(flag)
        if truncate:
            # a live truncation floor makes every op inadmissible: the plane
            # must FALL BACK to the handlers and still match bit for bit
            _c, node, store = env
            floor = Timestamp(1, 10, 0, 1)
            store.truncated_before = store.truncated_before.with_range(
                1, 5, floor, Timestamp.merge_max)
        hists.append(_realize(env, script, batch_plane=True,
                              compact_live=compact_live))
        if flag and truncate:
            assert env[2].cmd_plane.fallbacks > 0, \
                "truncation floor never forced a fallback"
    assert len(hists[0]) == len(hists[1])
    for i, (a, b) in enumerate(zip(*hists)):
        assert a == b, (f"seed {seed} diverged at step {i}:\n "
                        f"host {a}\n dev  {b}")


def test_randomized_differential():
    """Ballot contention + redundant deliveries + compaction in flight:
    identical histories across random interleavings."""
    for seed in (3, 17, 40, 71):
        _differential(seed)


def test_differential_under_truncation():
    """With a truncation floor active the plane admits nothing; the host
    fallback path must keep the histories identical."""
    _differential(9, truncate=True)


def test_compaction_in_flight():
    """Ops hold TxnIds, not rows: compacting between op construction and
    eval_batch must not corrupt evaluation (rows re-resolve at dispatch,
    applied txns re-seed from the store's Command objects)."""
    from accord_tpu.ops.cmd_plane import CmdOp
    _cluster, node, store = _env(True)
    plane = store.cmd_plane
    txn = _mk_txn([3], 1)
    tid = node.next_txn_id(txn.kind, txn.domain)
    route = node.compute_route(txn)
    part = txn.slice(store.ranges, include_query=False)
    assert plane.eval_batch([CmdOp.preaccept(tid, part, route)])[0] \
        .outcome == AcceptOutcome.SUCCESS
    ea = store.command(tid).execute_at
    # construct the commit+apply ops FIRST, compact while they're in flight
    ops = [CmdOp.commit(tid, route, part, ea, Deps.NONE),
           CmdOp.apply(tid, route, part, ea, Deps.NONE)]
    plane.compact()
    before = plane.compactions
    res = plane.eval_batch(ops)
    assert [r.outcome for r in res] == [CommitOutcome.SUCCESS,
                                       CommitOutcome.SUCCESS]
    _cluster.drain()
    assert store.command(tid).status == Status.APPLIED
    # applied rows drop at the next compaction; a redundant re-delivery
    # re-seeds the row from the Command and stays REDUNDANT
    plane.compact()
    assert plane.compactions == before + 1
    assert tid not in plane.row_of
    res = plane.eval_batch([CmdOp.commit(tid, route, part, ea, Deps.NONE)])
    assert res[0].outcome == CommitOutcome.REDUNDANT
    assert tid in plane.row_of


def test_burn_differential():
    """Full-cluster end-to-end: identical burn event logs with the plane
    threaded under every replica's PreAccept/Accept/Commit/Apply."""
    from accord_tpu.sim.burn import run_burn
    kw = dict(ops=60, write_ratio=0.85, key_count=6, collect_log=True)
    host = run_burn(7, config=ClusterConfig(), **kw)
    dev = run_burn(7, config=ClusterConfig(cmd_plane=True), **kw)
    assert host.acked == dev.acked == 60
    assert host.log == dev.log, "cmd_plane burn diverged from host burn"


def test_burn_differential_contended():
    """High write ratio on few keys: the slow path (witness bumps, accept
    rounds, recovery ballots) must stay bit-identical too."""
    from accord_tpu.sim.burn import run_burn
    kw = dict(ops=80, write_ratio=0.95, key_count=3, collect_log=True)
    host = run_burn(23, config=ClusterConfig(durability=True), **kw)
    dev = run_burn(23, config=ClusterConfig(durability=True,
                                            cmd_plane=True), **kw)
    assert host.acked == dev.acked == 80
    assert host.log == dev.log


def test_burn_differential_authoritative():
    """The `cmd_plane_authoritative` cluster flag: device promotions decide
    status transitions WITH the store attached (host handlers replay side
    effects only). The promotion predicates are >=-band status compares, so
    arena rows running ahead of the store must never change a decision --
    the burn history stays bit-identical to the host baseline."""
    from accord_tpu.sim.burn import run_burn
    kw = dict(ops=60, write_ratio=0.85, key_count=6, collect_log=True)
    host = run_burn(7, config=ClusterConfig(), **kw)
    auth = run_burn(7, config=ClusterConfig(
        cmd_plane=True, cmd_plane_authoritative=True), **kw)
    assert host.acked == auth.acked == 60
    assert host.log == auth.log, \
        "authoritative cmd_plane burn diverged from host burn"
    assert auth.counters.get("cmd_plane_dispatches", 0) > 0


def test_warmup_zero_recompiles():
    """After warmup_cmd_plane at the exact arena/op tiers, a live workload
    mints no new cmd_tick compiles (the bench's recompile gate)."""
    from accord_tpu.ops.cmd_plane import warmup_cmd_plane
    from accord_tpu.ops.kernels import jit_cache_sizes
    warmup_cmd_plane(caps=(1024,), key_caps=(1024,), kpad=4,
                     op_tiers=(8,), promote_modes=(False,))
    warmed = jit_cache_sizes()["cmd_tick"]
    assert warmed > 0
    _cluster, node, store = _env(True)
    from accord_tpu.ops.cmd_plane import CmdOp
    for v in range(6):
        txn = _mk_txn([v + 1], v)
        tid = node.next_txn_id(txn.kind, txn.domain)
        part = txn.slice(store.ranges, include_query=False)
        out = store.cmd_plane.eval_batch(
            [CmdOp.preaccept(tid, part, node.compute_route(txn))])
        assert out[0].outcome == AcceptOutcome.SUCCESS
    assert store.cmd_plane.dispatches >= 6
    assert jit_cache_sizes()["cmd_tick"] == warmed, \
        "live cmd_plane workload minted compiles past warmup"


def test_plane_metrics_reach_node_snapshot():
    """The four glossary counters surface through Node.metrics_snapshot."""
    _cluster, node, store = _env(True)
    txn = _mk_txn([2], 1)
    tid = node.next_txn_id(txn.kind, txn.domain)
    store.submit_preaccept(tid, txn.slice(store.ranges, include_query=False),
                           node.compute_route(txn))
    snap = node.metrics_snapshot()
    assert snap.get("cmd_plane_dispatches", 0) >= 1
    assert snap.get("cmd_plane_upload_bytes", 0) > 0
    assert snap.get("cmd_fastpath_device_evals", 0) >= 1
