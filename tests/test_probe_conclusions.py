"""Partial-knowledge repair must CONCLUDE, not retry: a single MaybeRecover
probe round resolves a stalled txn whenever the merged Known vector permits
(reference: the Known lattice local/Status.java:126-133 + Infer.java:61 +
Propagate.java:64). Each test builds real cluster state, runs ONE probe, and
asserts the conclusion without further probe rounds."""
from __future__ import annotations

import pytest

from accord_tpu.coordinate.recover import MaybeRecover, Outcome
from accord_tpu.primitives.keyspace import Keys
from accord_tpu.primitives.timestamp import TxnKind
from accord_tpu.primitives.txn import Txn
from accord_tpu.sim.cluster import Cluster, ClusterConfig
from accord_tpu.sim.list_store import ListQuery, ListRead, ListUpdate


def _mk_cluster(seed=3):
    return Cluster(seed, ClusterConfig(num_nodes=3, rf=3, progress=False))


def _write_txn(key, value):
    return Txn(TxnKind.WRITE, Keys([key]), read=ListRead(Keys([key])),
               update=ListUpdate(Keys([key]), value), query=ListQuery())


def _run_probe(cluster, node, txn_id, participants):
    """One probe; returns (value, failure, extra_probe_rounds)."""
    rounds = [0]
    orig = MaybeRecover.probe.__func__

    def counting(cls, n, t, p, allow_invalidate=True):
        rounds[0] += 1
        return orig(cls, n, t, p, allow_invalidate)

    MaybeRecover.probe = classmethod(counting)
    out = []
    try:
        MaybeRecover.probe(node, txn_id, participants) \
            .add_callback(lambda v, f: out.append((v, f)))
        cluster.drain(max_events=200000)
    finally:
        MaybeRecover.probe = classmethod(orig)
    assert out, "probe never completed"
    v, f = out[0]
    return v, f, rounds[0] - 1


def _commit_one(cluster, key, value):
    """Run a write to completion; return its txn_id."""
    node = cluster.nodes[1]
    done = []
    txn = _write_txn(key, value)
    txn_id = node.next_txn_id(txn.kind, txn.domain)
    node.coordinate(txn, txn_id=txn_id).add_callback(
        lambda v, f: done.append((v, f)))
    cluster.drain(max_events=200000)
    assert done and done[0][1] is None, f"setup write failed: {done}"
    return txn_id


def test_outcome_propagates_in_one_probe():
    """A txn APPLIED on its peers repairs a replica that lost its record:
    the merged reply carries a full Known outcome, applied locally without
    recovery rounds (reference: Propagate)."""
    cluster = _mk_cluster()
    key = 100
    txn_id = _commit_one(cluster, key, 7)
    # amnesiac replica: node 3 forgets the txn entirely
    victim = cluster.nodes[3]
    for store in victim.command_stores.all():
        if store.command_if_present(txn_id) is not None:
            del store.commands[txn_id]
    v, f, extra = _run_probe(cluster, victim, txn_id, Keys([key]))
    assert f is None, f
    assert v == Outcome.APPLIED
    assert extra == 0, f"{extra} extra probe rounds"
    for store in victim.command_stores.all():
        if store.owns(Keys([key])):
            cmd = store.command_if_present(txn_id)
            assert cmd is not None and cmd.has_been(
                __import__("accord_tpu.local.status",
                           fromlist=["Status"]).Status.APPLIED)


def test_unwitnessed_txn_invalidates_in_one_probe():
    """A txn id no replica ever witnessed: the probe concludes INVALIDATED
    (Infer IfUndecided -- nothing decided anywhere, all replicas answered)
    without extra probe rounds."""
    cluster = _mk_cluster()
    node = cluster.nodes[1]
    key = 200
    ghost = node.next_txn_id(TxnKind.WRITE, Keys([key]).domain)
    v, f, extra = _run_probe(cluster, node, ghost, Keys([key]))
    assert f is None, f
    assert v == Outcome.INVALIDATED
    assert extra == 0, f"{extra} extra probe rounds"


def test_preaccepted_only_invalidates_in_one_probe():
    """Witnessed on ONE replica but never accepted anywhere: with every
    reachable replica answered and the electorate's fast path decisively
    dead (promises block future votes), the probe race-invalidates instead
    of retrying forever (the round-3 livelock shape)."""
    from accord_tpu.local import commands
    cluster = _mk_cluster()
    node = cluster.nodes[1]
    key = 300
    txn = _write_txn(key, 9)
    txn_id = node.next_txn_id(txn.kind, txn.domain)
    route = node.compute_route(txn)
    # witness on node 2 only (the abandoned coordinator's lone PreAccept)
    for store in cluster.nodes[2].command_stores.all():
        if store.owns(Keys([key])):
            commands.preaccept(store, txn_id, txn.slice(store.ranges, False),
                               route)
    v, f, extra = _run_probe(cluster, node, txn_id, Keys([key]))
    assert f is None, f
    assert v == Outcome.INVALIDATED
    assert extra == 0, f"{extra} extra probe rounds"


def test_truncated_everywhere_concludes_in_one_probe():
    """Every replica truncated the record (outcome durably applied and
    erased): the probe concludes TRUNCATED from the merged knowledge."""
    cluster = _mk_cluster()
    key = 400
    txn_id = _commit_one(cluster, key, 11)
    for n in cluster.nodes.values():
        for store in n.command_stores.all():
            cmd = store.command_if_present(txn_id)
            if cmd is None:
                continue
            del store.commands[txn_id]
            ts = txn_id.as_timestamp().with_next_hlc()
            from accord_tpu.primitives.timestamp import Timestamp
            store.truncated_before = store.truncated_before.with_range(
                key, key + 1, ts, Timestamp.merge_max)
    victim = cluster.nodes[2]
    v, f, extra = _run_probe(cluster, victim, txn_id, Keys([key]))
    assert f is None, f
    assert v in (Outcome.TRUNCATED, Outcome.APPLIED)
    assert extra == 0, f"{extra} extra probe rounds"


def test_witnessed_timestamp_is_not_an_outcome():
    """A PRE_ACCEPTED record's witnessed executeAt is a PROPOSAL: merging it
    with a TRUNCATED sibling reply must NOT produce an 'applyable outcome'
    (known_outcome), or the probe would APPLY a never-committed txn -- the
    seed-3 split-brain where a preaccepted-then-rejected sync point was
    invalidated on one shard and probe-applied on another."""
    from accord_tpu.local.status import Status
    from accord_tpu.messages.recover import CheckStatusOk
    from accord_tpu.primitives.timestamp import Ballot

    cluster = _mk_cluster()
    node = cluster.nodes[1]
    key = 500
    txn = _write_txn(key, 21)
    txn_id = node.next_txn_id(txn.kind, txn.domain)
    route = node.compute_route(txn)
    preaccepted = CheckStatusOk(
        txn_id, Status.PRE_ACCEPTED, Ballot.ZERO,
        txn_id.as_timestamp(),  # witnessed-only
        route, txn.slice(route.participants.to_ranges(), False), None,
        None, None, execute_at_decided=False)
    truncated = CheckStatusOk(txn_id, Status.TRUNCATED, Ballot.ZERO,
                              None, None, None, None, None, None)
    merged = CheckStatusOk.merge(truncated, preaccepted)
    assert merged.status == Status.TRUNCATED
    assert not merged.known_outcome, \
        "witnessed-only executeAt leaked into an applyable outcome"
    # a DECIDED executeAt must win the merge over a witnessed one
    decided = CheckStatusOk(
        txn_id, Status.PRE_APPLIED, Ballot.ZERO,
        txn_id.as_timestamp().with_next_hlc(), route,
        txn.slice(route.participants.to_ranges(), False), None,
        None, None, execute_at_decided=True)
    merged2 = CheckStatusOk.merge(preaccepted, decided)
    assert merged2.execute_at_decided
    assert merged2.execute_at == decided.execute_at
