"""The overlapped device pipeline: multiple calls in flight at once, arena
compaction racing them, and the readiness poll -- the paths the double-
buffered dispatch added on top of the single-outstanding-call resolver.

The compaction test is the load-bearing one: a call encoded against
generation G must, after compact() bumps to G+1 mid-flight, still decode on
the DEVICE path (row translation through the pinned snapshot), not fall back
to the host scan -- and the translated result must equal the host scan
exactly."""
from __future__ import annotations

import numpy as np

from accord_tpu.ops.resolver import BatchDepsResolver
from accord_tpu.primitives.keyspace import Keys
from tests.test_local_engine import setup_store
from tests.test_ops import _preaccept_population


def _pipelined_store():
    """A store wired for the async pipeline: resolver attached, a real batch
    window, device latency long enough to stack dispatches, poll armed."""
    cluster, node, store = setup_store()
    resolver = BatchDepsResolver(num_buckets=128, initial_cap=128)
    store.deps_resolver = resolver
    store.batch_window_ms = 0.5
    node.device_latency_ms = 50.0   # harvests land well after both ticks
    node.device_poll_ms = 1.0       # exercise the readiness-poll prefetch
    return cluster, node, store, resolver


def test_compaction_with_two_calls_in_flight():
    """Compact the arena while >= 2 calls are in flight; both harvests must
    translate their retired-generation rows (no host fallback) and match the
    host scan bit-for-bit."""
    rng = np.random.default_rng(21)
    cluster, node, store, resolver = _pipelined_store()
    # chaff on a disjoint key range [100, 140): pruned from the arena below
    # to make compaction reclaim >= half the capacity. Subjects only ever
    # query keys < 12, so pruning these arena-side cannot perturb the
    # host-vs-device differential.
    chaff_keys = [sorted(set(rng.integers(100, 140, 2).tolist()))
                  for _ in range(50)]
    chaff = _preaccept_population(store, node, chaff_keys)
    live_keys = [sorted(set(rng.integers(0, 12, 2).tolist()))
                 for _ in range(40)]
    live = _preaccept_population(store, node, live_keys)

    arena = resolver._arenas[id(store)]
    for t, ks in zip(chaff, chaff_keys):
        resolver.on_prune(store, t, ks)

    def enqueue(idxs):
        outs = []
        for i in idxs:
            t = live[i]
            keys = Keys(live_keys[i])
            before = store.command(t).execute_at
            outs.append((t, keys, before,
                         resolver.enqueue_deps(store, t, keys, before)))
        return outs

    batch_a = enqueue(range(20, 26))
    while resolver.dispatches < 1:
        assert cluster.queue.process_one(), "tick never fired"
    batch_b = enqueue(range(30, 36))
    while resolver.dispatches < 2:
        assert cluster.queue.process_one(), "second tick never fired"

    # both calls in flight, poll armed, nothing harvested yet
    assert len(resolver._inflight[id(node)]) == 2
    assert id(node) in resolver._polling
    assert all(not out.done for *_, out in batch_a + batch_b)

    gen0 = arena.gen
    assert arena.compact(), "compaction should reclaim the pruned chaff"
    assert arena.gen == gen0 + 1
    # the in-flight pins forced a row->txn snapshot of the retired mapping
    assert gen0 in arena.retired_ids

    while not all(out.done for *_, out in batch_a + batch_b):
        assert cluster.queue.process_one(), "harvest never fired"

    # both harvests crossed the compaction on the DEVICE path
    assert resolver.stale_harvests == 2
    assert resolver.host_fallbacks == 0
    # drained: pins released, snapshot dropped, poll disarmed
    cluster.queue.drain(max_events=10_000)
    assert gen0 not in arena.retired_ids
    assert id(node) not in resolver._polling

    nonempty = 0
    for t, keys, before, out in batch_a + batch_b:
        host = store.host_calculate_deps(t, keys, before)
        got = out.value()
        assert got == host, f"subject {t}: {got} != {host}"
        nonempty += bool(got.key_deps.all_txn_ids())
    assert nonempty > 0, "differential vacuous: every subject had no deps"


def test_harvest_order_and_reuse_after_compaction():
    """After the stale harvests drain, the SAME resolver must keep answering
    exactly on the new generation (fresh dispatch, no translation)."""
    rng = np.random.default_rng(5)
    cluster, node, store, resolver = _pipelined_store()
    chaff_keys = [[100 + int(k)] for k in rng.integers(0, 30, 50)]
    chaff = _preaccept_population(store, node, chaff_keys)
    live_keys = [sorted(set(rng.integers(0, 8, 2).tolist()))
                 for _ in range(30)]
    live = _preaccept_population(store, node, live_keys)
    arena = resolver._arenas[id(store)]
    for t, ks in zip(chaff, chaff_keys):
        resolver.on_prune(store, t, ks)

    t0 = live[25]
    out0 = resolver.enqueue_deps(store, t0, Keys(live_keys[25]),
                                 store.command(t0).execute_at)
    while resolver.dispatches < 1:
        assert cluster.queue.process_one()
    assert arena.compact()
    cluster.queue.drain(max_events=10_000)
    assert out0.done and resolver.stale_harvests == 1

    # second wave on the compacted arena: normal (non-stale) decode
    t1 = live[28]
    before1 = store.command(t1).execute_at
    out1 = resolver.enqueue_deps(store, t1, Keys(live_keys[28]), before1)
    cluster.queue.drain(max_events=10_000)
    assert out1.done
    assert resolver.stale_harvests == 1  # unchanged
    assert resolver.host_fallbacks == 0
    host = store.host_calculate_deps(t1, Keys(live_keys[28]), before1)
    assert out1.value() == host


def test_pipeline_burn_deterministic():
    """Two burns with the overlapped pipeline (batch window + readiness poll)
    must produce bit-identical histories under the same seed: the poll only
    fills host-side caches, never simulated state."""
    from accord_tpu.sim.burn import run_burn
    from accord_tpu.sim.cluster import ClusterConfig

    class PollingResolver(BatchDepsResolver):
        def _dispatch(self, node, items):
            if getattr(node, "device_poll_ms", None) is None:
                node.device_poll_ms = 1.0
            super()._dispatch(node, items)

    def cfg():
        return ClusterConfig(
            deps_resolver_factory=lambda: PollingResolver(num_buckets=128),
            deps_batch_window_ms=1.0)

    kw = dict(ops=60, key_count=8, concurrency=6, collect_log=True)
    a = run_burn(17, config=cfg(), **kw)
    b = run_burn(17, config=cfg(), **kw)
    assert a.acked == b.acked == 60
    assert a.lost == 0
    assert a.log == b.log
