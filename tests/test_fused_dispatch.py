"""Cross-store fused dispatch: a multi-store node's tick drains EVERY
store's pending items into one device call (store-id lane + per-group word
spans route results back), with generation pins isolating compaction per
store -- plus the field-granular arena deltas that ride the same PR.

Three load-bearing properties:
  1. one fused call per tick across stores, and compacting ONE store's
     arena mid-flight must not disturb the other store's pins or force a
     host fallback;
  2. fused dispatch decodes bit-identically to per-store dispatch
     (fuse_cross_store=False) on a randomized mixed key/range workload;
  3. status-bump updates ship one int32 lane, not the full row --
     upload_bytes stays strictly below the full-row-equivalent baseline.
"""
from __future__ import annotations

import numpy as np

from accord_tpu.local.cfk import CfkStatus
from accord_tpu.ops.resolver import BatchDepsResolver
from accord_tpu.primitives.keyspace import Keys, Range, Ranges
from accord_tpu.primitives.timestamp import Domain, Timestamp, TxnId, TxnKind
from accord_tpu.sim.cluster import Cluster, ClusterConfig


def _two_store_node():
    cluster = Cluster(1, ClusterConfig(num_nodes=1, rf=1, num_shards=1,
                                       stores_per_node=2, progress=False))
    node = cluster.nodes[1]
    stores = node.command_stores.stores
    assert len(stores) == 2
    return cluster, node, stores


def _attach(stores, node, resolver, window=0.5, latency=50.0):
    for s in stores:
        s.deps_resolver = resolver
        s.batch_window_ms = window
    node.device_latency_ms = latency


def _store_lo(store):
    return min(int(r.start) for r in store.ranges)


def _register_keys(store, node, key_lists, status=CfkStatus.WITNESSED):
    tids = []
    for ks in key_lists:
        ts = node.unique_now()
        tid = TxnId.create(ts.epoch, ts.hlc, ts.node, TxnKind.WRITE,
                           Domain.KEY)
        store.register(tid, Keys(ks), status, ts)
        tids.append(tid)
    return tids


def _far(node):
    return Timestamp(node.epoch, node.time_service.now_micros() + 50_000,
                     0, node.id)


def test_fused_tick_with_per_store_compaction_in_flight():
    """Items from both stores ride ONE dispatch; compacting store A's arena
    while that call is in flight leaves store B's generation untouched, and
    every answer still decodes on the device path (no host fallback)."""
    rng = np.random.default_rng(23)
    cluster, node, stores = _two_store_node()
    resolver = BatchDepsResolver(num_buckets=128, initial_cap=128)
    _attach(stores, node, resolver)
    sa, sb = stores
    lo_a, lo_b = _store_lo(sa), _store_lo(sb)

    # store A: prunable chaff (disjoint keys) so compaction can reclaim
    # >= half its arena, plus live rows the subjects query
    chaff_keys = [sorted({lo_a + int(k) for k in rng.integers(100, 140, 2)})
                  for _ in range(50)]
    chaff = _register_keys(sa, node, chaff_keys)
    live_a = [sorted({lo_a + int(k) for k in rng.integers(0, 12, 2)})
              for _ in range(30)]
    _register_keys(sa, node, live_a)
    live_b = [sorted({lo_b + int(k) for k in rng.integers(0, 12, 2)})
              for _ in range(30)]
    _register_keys(sb, node, live_b)
    for t, ks in zip(chaff, chaff_keys):
        resolver.on_prune(sa, t, ks)

    arena_a = resolver._arenas[id(sa)]
    arena_b = resolver._arenas[id(sb)]
    assert arena_a is not arena_b

    far = _far(node)
    subs = []
    for i in range(4):
        tid = node.next_txn_id(TxnKind.WRITE, Domain.KEY)
        keys = Keys(live_a[10 + i])
        subs.append((sa, tid, keys, far,
                     resolver.enqueue_deps(sa, tid, keys, far)))
    for i in range(4):
        tid = node.next_txn_id(TxnKind.WRITE, Domain.KEY)
        keys = Keys(live_b[10 + i])
        subs.append((sb, tid, keys, far,
                     resolver.enqueue_deps(sb, tid, keys, far)))

    while resolver.dispatches < 1:
        assert cluster.queue.process_one(), "tick never fired"
    # the tentpole: both stores' items fused into one call
    assert resolver.ticks == 1
    assert resolver.dispatches == 1
    call = resolver._inflight[id(node)][0]
    assert len(call.groups) == 2
    assert {g.store for g in call.groups} == {sa, sb}
    assert all(not out.done for *_, out in subs)

    # compact store A mid-flight; store B's generations must not move
    gen_a0, gen_b0 = arena_a.gen, arena_b.gen
    assert arena_a.compact(), "compaction should reclaim the pruned chaff"
    assert arena_a.gen == gen_a0 + 1
    assert gen_a0 in arena_a.retired_ids  # pinned snapshot forced
    assert arena_b.gen == gen_b0
    assert not arena_b.retired_ids

    while not all(out.done for *_, out in subs):
        assert cluster.queue.process_one(), "harvest never fired"
    assert resolver.stale_harvests == 1
    assert resolver.host_fallbacks == 0
    cluster.queue.drain(max_events=10_000)
    assert gen_a0 not in arena_a.retired_ids  # pin released

    nonempty = 0
    for store, tid, keys, before, out in subs:
        host = store.host_calculate_deps(tid, keys, before)
        assert out.value() == host, f"subject {tid} ({store})"
        nonempty += bool(host.key_deps.all_txn_ids())
    assert nonempty > 0, "differential vacuous"


def _register_mixed_per_store(store, node, rng, n_key=25, n_range=15):
    lo = _store_lo(store)
    span = 4096
    for i in range(n_key):
        ts = node.unique_now()
        kind = TxnKind.WRITE if i % 3 else TxnKind.READ
        tid = TxnId.create(ts.epoch, ts.hlc, ts.node, kind, Domain.KEY)
        width = 20 if i % 9 == 0 else 1 + int(rng.integers(0, 4))
        keys = Keys(sorted({lo + int(k)
                            for k in rng.integers(0, span, width)}))
        store.register(tid, keys, CfkStatus.WITNESSED, ts)
    for i in range(n_range):
        ts = node.unique_now()
        kind = TxnKind.WRITE if i % 2 else TxnKind.READ
        tid = TxnId.create(ts.epoch, ts.hlc, ts.node, kind, Domain.RANGE)
        s = lo + int(rng.integers(0, span))
        store.register(tid, Ranges([Range(s, s + 1 + int(
            rng.integers(0, 1024)))]), CfkStatus.WITNESSED, ts)


def _mixed_subjects(store, node, rng, n):
    lo = _store_lo(store)
    span = 4096
    far = _far(node)
    subs = []
    for i in range(n):
        kind = TxnKind.WRITE if i % 2 else TxnKind.READ
        if i % 3 == 0:
            s = lo + int(rng.integers(0, span))
            owned = store.owned(
                Ranges([Range(s, s + 1 + int(rng.integers(0, 2048)))]))
            tid = node.next_txn_id(kind, Domain.RANGE)
        else:
            width = 1 + int(rng.integers(0, 4))
            owned = store.owned(Keys(sorted(
                {lo + int(k) for k in rng.integers(0, span, width)})))
            tid = node.next_txn_id(kind, Domain.KEY)
        subs.append((store, tid, owned, far))
    return subs


def _run_async(cluster, resolver, subs):
    outs = [resolver.enqueue_deps(store, tid, owned, before)
            for store, tid, owned, before in subs]
    cluster.queue.drain(max_events=100_000)
    assert all(o.done for o in outs)
    return [o.value() for o in outs]


def test_fused_vs_per_store_differential():
    """Randomized mixed key/range workload over two stores: the fused
    cross-store dispatch must decode bit-identically to the per-store
    dispatch (fuse_cross_store=False) AND to the host scan, while issuing
    fewer device calls than store-count x ticks."""
    rng = np.random.default_rng(31)
    cluster, node, stores = _two_store_node()
    fused = BatchDepsResolver(num_buckets=128, initial_cap=128)
    _attach(stores, node, fused, latency=5.0)
    for s in stores:
        _register_mixed_per_store(s, node, rng)

    # interleave both stores' subjects, two waves (two fused ticks)
    subs = []
    for wave_rng in (np.random.default_rng(7), np.random.default_rng(8)):
        wave = []
        for s in stores:
            wave.extend(_mixed_subjects(s, node, wave_rng, 9))
        subs.append(wave)

    fused_res = []
    for wave in subs:
        fused_res.extend(_run_async(cluster, fused, wave))
    assert fused.ticks >= 2
    assert fused.dispatches < 2 * fused.ticks, "fused path disengaged"
    assert fused.host_fallbacks == 0 and fused.range_fallbacks == 0

    # per-store baseline: a fresh resolver (adopts the same store state)
    # with fusion off -- the old one-dispatch-per-store drain
    per_store = BatchDepsResolver(num_buckets=128, initial_cap=128,
                                  fuse_cross_store=False)
    ps_res = []
    for wave in subs:
        ps_res.extend(_run_async(cluster, per_store, wave))
    assert per_store.dispatches > fused.dispatches

    key_seen = range_seen = 0
    for (store, tid, owned, before), fd, pd in zip(
            [x for wave in subs for x in wave], fused_res, ps_res):
        assert fd == pd, f"fused vs per-store diverge on {tid}"
        host = store.host_calculate_deps(tid, owned, before)
        assert fd == host, f"fused vs host diverge on {tid}"
        key_seen += bool(host.key_deps.all_txn_ids())
        range_seen += bool(host.range_deps.all_txn_ids())
    assert key_seen > 0 and range_seen > 0, "differential vacuous"


def test_sharded_fused_two_store_differential():
    """The mesh-sharded resolver's fused cross-store dispatch must also
    decode bit-identically to the host scans on a mixed two-store workload
    (this exercises the per-store block concat in parallel/mesh.py, which
    must dodge the sharded-axis concatenate miscompile -- see
    _concat_lane_blocks)."""
    from accord_tpu.ops.resolver import ShardedBatchDepsResolver
    from accord_tpu.parallel.mesh import make_mesh
    rng = np.random.default_rng(41)
    cluster, node, stores = _two_store_node()
    res = ShardedBatchDepsResolver(mesh=make_mesh(), num_buckets=128,
                                   initial_cap=128)
    _attach(stores, node, res, latency=5.0)
    for s in stores:
        _register_mixed_per_store(s, node, rng)
    subs = []
    for s in stores:
        subs.extend(_mixed_subjects(s, node, np.random.default_rng(9), 9))
    outs = _run_async(cluster, res, subs)
    assert res.dispatches < 2 * res.ticks, "fused path disengaged"
    assert res.host_fallbacks == 0 and res.range_fallbacks == 0
    key_seen = range_seen = 0
    for (store, tid, owned, before), dv in zip(subs, outs):
        host = store.host_calculate_deps(tid, owned, before)
        assert dv == host, f"sharded fused diverges from host on {tid}"
        key_seen += bool(host.key_deps.all_txn_ids())
        range_seen += bool(host.range_deps.all_txn_ids())
    assert key_seen > 0 and range_seen > 0, "differential vacuous"


def test_field_granular_upload_accounting():
    """A status bump re-registration dirties only the exec-ts lane: the next
    device sync ships the int32 lane (upload_bytes_by_field['ts']) instead
    of full rows, and total upload_bytes stays strictly below the
    full-row-equivalent baseline."""
    from tests.test_local_engine import setup_store
    rng = np.random.default_rng(13)
    _, node, store = setup_store()
    resolver = BatchDepsResolver(num_buckets=128, initial_cap=128)
    store.deps_resolver = resolver

    key_lists = [sorted({int(k) for k in rng.integers(0, 64, 3)})
                 for _ in range(30)]
    tids = _register_keys(store, node, key_lists)

    def probe():
        tid = node.next_txn_id(TxnKind.WRITE, Domain.KEY)
        keys = Keys(key_lists[int(rng.integers(0, len(key_lists)))])
        far = _far(node)
        dev = resolver.resolve_one(store, tid, keys, far)
        assert dev == store.host_calculate_deps(tid, keys, far)

    probe()  # initial full upload
    by0 = dict(resolver.upload_bytes_by_field)
    ub0 = resolver.upload_bytes
    eq0 = resolver.upload_bytes_full_equiv
    assert by0["full"] > 0
    assert ub0 == eq0  # full uploads ARE the baseline

    # status bumps: same keys, later witnessed_at -> exec-ts lane only
    for tid, ks in list(zip(tids, key_lists))[:10]:
        store.register(tid, Keys(ks), CfkStatus.COMMITTED, node.unique_now())
    # and a couple of invalidations -> valid lane only
    for tid, ks in list(zip(tids, key_lists))[10:13]:
        store.register(tid, Keys(ks), CfkStatus.INVALIDATED,
                       node.unique_now())

    probe()  # granular delta upload
    by1 = dict(resolver.upload_bytes_by_field)
    assert by1["full"] == by0["full"], "bump re-uploaded full rows"
    assert by1["ts"] > by0["ts"]
    assert by1["valid"] > by0["valid"]
    # the delta cost strictly undercuts what full-row chunks would have paid
    granular = resolver.upload_bytes - ub0
    baseline = resolver.upload_bytes_full_equiv - eq0
    assert 0 < granular < baseline
    assert resolver.upload_bytes < resolver.upload_bytes_full_equiv
