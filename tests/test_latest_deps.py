"""LatestDeps-grade recovery merge: per-range, ballot-aware deps
reconstruction during recovery.

Mirrors the reference's LatestDeps (primitives/LatestDeps.java:40): when
different ranges of a txn were decided at different ballots/phases on
different replicas, the merge resolves the best (tier, ballot) PER RANGE --
whole-reply ranking would let a narrow higher-ballot accept mask a sibling
range's accepted deps (VERDICT r4 item 6)."""
import pytest

from accord_tpu.coordinate.recover import Recover
from accord_tpu.local.status import Status
from accord_tpu.messages import (
    Accept, AcceptOk, BeginRecovery, PreAccept, RecoverOk,
)
from accord_tpu.messages.base import Callback
from accord_tpu.messages.recover import DepsEntry, DepsTier
from accord_tpu.primitives.deps import Deps, KeyDeps
from accord_tpu.primitives.keyspace import Keys, Range, Ranges
from accord_tpu.primitives.timestamp import Ballot, TxnKind
from accord_tpu.primitives.txn import Txn
from accord_tpu.sim.cluster import Cluster, ClusterConfig
from accord_tpu.sim.list_store import ListQuery, ListRead, ListUpdate


class _Sink(Callback):
    def __init__(self):
        self.replies = []

    def on_success(self, from_node, reply):
        self.replies.append((from_node, reply))

    def on_failure(self, from_node, failure):
        pass


def _write_txn(keys, value):
    return Txn(TxnKind.WRITE, keys, read=ListRead(keys),
               update=ListUpdate(keys, value), query=ListQuery())


K1, K2 = 100, 40000  # shard0 and shard2 of the 4-shard default topology


def _cluster():
    return Cluster(13, ClusterConfig(num_nodes=3, rf=3, stores_per_node=1,
                                     progress=False))


def _completed_txn_id(cluster, node, keys, value):
    res = node.coordinate(_write_txn(keys, value))
    cluster.drain()
    assert res.done and res.failure is None
    return res.value().txn_id


def test_mixed_ballot_recovery_merges_deps_per_range(monkeypatch):
    """Range K1 accepted at ballot b1 (deps: t_a) on nodes {1,2}; range K2
    accepted at a HIGHER ballot b2 (deps: t_b) on nodes {2,3} -- node 2's
    record was overwritten by the later, narrower proposal. Recovery's merged
    proposal must keep BOTH ranges' accepted deps; ranking whole replies by
    ballot (or letting the b2 entry claim whole-store coverage) drops t_a.
    The merged proposal is captured at the resume boundary because the
    subsequent Propose round recalculates deps and would mask the loss."""
    captured = {}
    orig_resume = Recover._resume

    def capture(self, phase, execute_at, deps):
        captured["deps"] = deps
        return orig_resume(self, phase, execute_at, deps)

    monkeypatch.setattr(Recover, "_resume", capture)
    cl = _cluster()
    n1 = cl.node(1)
    t_a = _completed_txn_id(cl, n1, Keys([K1]), 1)
    t_b = _completed_txn_id(cl, n1, Keys([K2]), 2)

    keys = Keys([K1, K2])
    txn = _write_txn(keys, 9)
    txn_id = n1.next_txn_id(txn.kind, txn.domain)
    route = n1.compute_route(txn)
    sink = _Sink()
    for to in (1, 2, 3):
        n1.send(to, PreAccept(txn_id, txn, route), sink)
    cl.drain()
    assert len(sink.replies) == 3

    exec_at = max(r.witnessed_at for _, r in sink.replies)
    b1 = Ballot.from_timestamp(n1.unique_now())
    b2 = Ballot.from_timestamp(n1.unique_now())
    assert b2 > b1
    d1 = Deps(KeyDeps.of({K1: [t_a]}))
    d2 = Deps(KeyDeps.of({K2: [t_b]}))
    acc = _Sink()
    for to in (1, 2):
        n1.send(to, Accept(txn_id, b1, route, Keys([K1]), exec_at, d1), acc)
    cl.drain()
    for to in (2, 3):
        n1.send(to, Accept(txn_id, b2, route, Keys([K2]), exec_at, d2), acc)
    cl.drain()
    assert all(isinstance(r, AcceptOk) for _, r in acc.replies)
    # node 2's record now holds only the b2 proposal (scope K2)
    cmd2 = cl.node(2).command_stores.all()[0].command_if_present(txn_id)
    assert cmd2.accepted_ballot == b2
    assert cmd2.accepted_scope == Keys([K2]).to_ranges()

    # white-box: the merged proposal itself (the LatestDeps analog)
    rec = Recover(cl.node(3), txn_id, txn, route,
                  Ballot.from_timestamp(n1.unique_now()))
    for to in (1, 2, 3):
        cl.node(3).send(to, BeginRecovery(txn_id, txn, route, rec.ballot), rec)
    cl.drain()
    assert rec.result.done
    if rec.result.failure is not None:
        raise rec.result.failure

    # the LatestDeps-grade merged proposal keeps both ranges' accepted deps
    merged = set(captured["deps"].all_txn_ids())
    assert t_a in merged, f"k1's b1-accepted dep lost in merge: {merged}"
    assert t_b in merged, f"k2's b2-accepted dep lost in merge: {merged}"

    # the recovered txn must carry BOTH accepted deps in its stable record
    for nid in (1, 2, 3):
        cmd = cl.node(nid).command_stores.all()[0].command_if_present(txn_id)
        assert cmd is not None and cmd.has_been(Status.STABLE)
        ids = set(cmd.deps.all_txn_ids())
        assert t_a in ids, f"node {nid}: k1's accepted dep lost: {ids}"
        assert t_b in ids, f"node {nid}: k2's accepted dep lost: {ids}"


def _entry(tier, ballot, deps, covering):
    return DepsEntry(tier, ballot, deps, covering)


def test_merge_latest_oracle():
    """Unit oracle for the per-fragment merge: mixed tiers/ballots/coverings
    resolve to the highest (tier, ballot) per atomic fragment, with ties
    unioned."""
    cl = _cluster()
    n3 = cl.node(3)
    keys = Keys([K1, K2])
    txn = _write_txn(keys, 0)
    txn_id = n3.next_txn_id(txn.kind, txn.domain)
    rec = Recover(n3, txn_id, txn, n3.compute_route(txn),
                  Ballot.from_timestamp(n3.unique_now()))

    def tid(hlc):
        from accord_tpu.primitives.timestamp import TxnId, Domain
        return TxnId.create(1, hlc, 1, TxnKind.WRITE, Domain.KEY)

    ta, tb, tc, td = tid(10), tid(11), tid(12), tid(13)
    b_lo = Ballot.from_timestamp(n3.unique_now())
    b_hi = Ballot.from_timestamp(n3.unique_now())
    cover1 = Keys([K1]).to_ranges()
    cover2 = Keys([K2]).to_ranges()
    window = Ranges([Range(0, 65536)])
    entries = [
        # K1: lower-ballot proposal (must win over LOCAL, survive b_hi@K2)
        _entry(DepsTier.PROPOSAL, b_lo, Deps(KeyDeps.of({K1: [ta]})), cover1),
        # K2: higher-ballot proposal
        _entry(DepsTier.PROPOSAL, b_hi, Deps(KeyDeps.of({K2: [tb]})), cover2),
        # K2: a STALE lower-ballot proposal naming a different dep: must lose
        _entry(DepsTier.PROPOSAL, b_lo, Deps(KeyDeps.of({K2: [tc]})), cover2),
        # LOCAL tier everywhere: only fills fragments with no proposal
        _entry(DepsTier.LOCAL, Ballot.ZERO,
               Deps(KeyDeps.of({K1: [td], K2: [td]})), window),
    ]
    deps, missing = rec._merge_latest(entries, window)
    ids_k1 = set(deps.slice(cover1).all_txn_ids())
    ids_k2 = set(deps.slice(cover2).all_txn_ids())
    assert ids_k1 == {ta}, ids_k1            # b_lo wins at K1 (only proposal)
    assert ids_k2 == {tb}, ids_k2            # b_hi beats b_lo and LOCAL at K2
    assert not any(cover1.intersects(m) or cover2.intersects(m)
                   for m in missing)

    # committed floor: only COMMITTED-tier entries qualify; fragments without
    # committed coverage surface as missing (-> CollectDeps top-up)
    entries.append(_entry(DepsTier.COMMITTED, Ballot.ZERO,
                          Deps(KeyDeps.of({K1: [tc]})), cover1))
    deps, missing = rec._merge_latest(entries, window,
                                      tier_floor=DepsTier.COMMITTED)
    assert set(deps.all_txn_ids()) == {tc}
    assert any(m.intersects(cover2) for m in missing)
