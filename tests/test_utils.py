import random

import pytest

from accord_tpu.utils import sorted_arrays as sa
from accord_tpu.utils.async_ import AsyncResult, all_of, failure, settable, success
from accord_tpu.utils.range_map import ReducingRangeMap, merge
from accord_tpu.utils.rng import RandomSource


def test_sorted_arrays():
    assert sa.linear_union((1, 3), (2, 3, 4)) == (1, 2, 3, 4)
    assert sa.linear_intersection((1, 3, 5), (3, 4, 5)) == (3, 5)
    assert sa.linear_difference((1, 2, 3), (2,)) == (1, 3)
    assert sa.contains((1, 3), 3) and not sa.contains((1, 3), 2)
    assert sa.index_of((1, 3, 5), 3) == 1
    assert sa.index_of((1, 3, 5), 4) == -3
    assert sa.insert((1, 3), 2) == (1, 2, 3)
    assert sa.insert((1, 3), 3) == (1, 3)
    assert sa.remove((1, 2, 3), 2) == (1, 3)
    assert sa.next_intersection((1, 5, 9), 0, (2, 5, 9), 0) == (1, 1)
    assert sa.next_intersection((1, 5, 9), 2, (2, 5), 0) is None
    # union fast-path identity
    a = (1, 2, 3)
    assert sa.linear_union(a, (2,)) == a


def test_async_basics():
    r = settable()
    seen = []
    r.map(lambda v: v + 1).on_success(seen.append)
    r.set_success(1)
    assert seen == [2]
    assert r.done and r.success and r.value() == 1

    f = failure(ValueError("x"))
    got = []
    f.on_failure(lambda e: got.append(type(e)))
    assert got == [ValueError]
    with pytest.raises(ValueError):
        f.value()


def test_async_flatmap_and_all():
    a, b = settable(), settable()
    combined = all_of([a, b])
    a.set_success(1)
    assert not combined.done
    b.set_success(2)
    assert combined.value() == [1, 2]

    chained = success(5).flat_map(lambda v: success(v * 2))
    assert chained.value() == 10

    # failure fast-path in all_of
    c, d = settable(), settable()
    comb2 = all_of([c, d])
    c.set_failure(RuntimeError("boom"))
    assert comb2.done and not comb2.success


def test_rng_determinism():
    a, b = RandomSource(123), RandomSource(123)
    assert [a.next_int(100) for _ in range(20)] == [b.next_int(100) for _ in range(20)]
    fa, fb = a.fork(), b.fork()
    assert [fa.next_long() for _ in range(5)] == [fb.next_long() for _ in range(5)]
    z = RandomSource(1)
    vals = [z.zipf(10) for _ in range(200)]
    assert all(0 <= v < 10 for v in vals)
    # hot head: rank 0 should dominate
    assert vals.count(0) > vals.count(9)


def test_range_map_basic():
    m = ReducingRangeMap.EMPTY.with_range(0, 10, 5, max)
    assert m.get(0) == 5 and m.get(9) == 5 and m.get(10) is None and m.get(-1) is None
    m2 = m.with_range(5, 15, 7, max)
    assert m2.get(3) == 5 and m2.get(6) == 7 and m2.get(12) == 7 and m2.get(15) is None
    m3 = m2.with_range(0, 20, 6, max)
    assert m3.get(3) == 6 and m3.get(6) == 7 and m3.get(16) == 6


def test_range_map_randomized_vs_naive():
    rng = random.Random(9)
    for _ in range(40):
        m = ReducingRangeMap.EMPTY
        naive = {}
        for _ in range(rng.randrange(1, 10)):
            s = rng.randrange(0, 40)
            e = s + rng.randrange(1, 12)
            v = rng.randrange(100)
            m = m.with_range(s, e, v, max)
            for x in range(s, e):
                naive[x] = max(naive.get(x, v), v)
        for x in range(-2, 60):
            assert m.get(x) == naive.get(x), f"key {x}: {m} vs {naive.get(x)}"


def test_range_map_fold():
    m = ReducingRangeMap.EMPTY.with_range(0, 10, 1, max).with_range(20, 30, 2, max)
    total = m.fold_over_range(5, 25, lambda acc, v: acc + v, 0)
    assert total == 3
    assert m.fold_over_range(12, 18, lambda acc, v: acc + v, 0) == 0
    assert m.fold_values(lambda acc, v: acc + v, 0) == 3
