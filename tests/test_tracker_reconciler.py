"""Exhaustive tracker state-space reconciliation vs brute-force oracles.

Mirrors the reference's TrackerReconcilerTest (test coordinate/tracking/
TrackerReconcilerTest.java): for every assignment of per-node outcomes and
every delivery order over small topologies, the tracker's first decision --
its type AND the event on which it fires -- must match an oracle computed
directly from the quorum arithmetic, and the decision must be stable
afterwards (every later event reports NO_CHANGE)."""
from __future__ import annotations

from itertools import permutations, product

from accord_tpu.coordinate.tracking import (
    AppliedTracker, FastPathTracker, InvalidationTracker, QuorumTracker,
    RecoveryTracker, RequestStatus,
)
from accord_tpu.primitives.keyspace import Keys, Range
from accord_tpu.topology.shard import Shard
from accord_tpu.topology.topologies import Topologies
from accord_tpu.topology.topology import Topology

FAST, SLOW, FAIL = "fast", "slow", "fail"

TOPOLOGIES = {
    "rf3": Topologies.single(Topology(1, [Shard(Range(0, 100), [1, 2, 3])])),
    "rf5": Topologies.single(Topology(1, [Shard(Range(0, 100),
                                               [1, 2, 3, 4, 5])])),
    "2shard": Topologies.single(Topology(1, [
        Shard(Range(0, 50), [1, 2, 3]),
        Shard(Range(50, 100), [3, 4, 5]),
    ])),
}


def _enumerate(nodes, outcomes):
    """Every outcome assignment x every delivery order. rf5 keeps full
    assignment coverage but caps orders (5! x 3^5 is fine; keep all)."""
    for assignment in product(outcomes, repeat=len(nodes)):
        by_node = dict(zip(nodes, assignment))
        for order in permutations(nodes):
            yield by_node, order


class _Oracle:
    """Brute-force per-shard accounting mirroring the documented criteria."""

    def __init__(self, topologies):
        self.shards = [s for t in topologies for s in t.shards]
        self.success = {id(s): set() for s in self.shards}
        self.failure = {id(s): set() for s in self.shards}
        self.fast = {id(s): set() for s in self.shards}
        self.slow = {id(s): set() for s in self.shards}  # replied, no fast vote

    def feed(self, node, outcome):
        for s in self.shards:
            if node not in s.nodes:
                continue
            if outcome == FAIL:
                self.failure[id(s)].add(node)
            else:
                self.success[id(s)].add(node)
                if outcome == FAST:
                    self.fast[id(s)].add(node)
                else:
                    self.slow[id(s)].add(node)

    def failed(self):
        return any(len(self.failure[id(s)]) > s.max_failures
                   for s in self.shards)

    def quorum(self):
        return all(len(self.success[id(s)]) >= s.slow_path_quorum_size
                   for s in self.shards)

    def fast_resolved(self, s):
        e = s.fast_path_electorate
        votes = len(self.fast[id(s)] & e)
        rejected = len((self.slow[id(s)] | self.failure[id(s)]) & e)
        pending = len(e) - votes - rejected
        achieved = votes >= s.fast_path_quorum_size
        impossible = votes + pending < s.fast_path_quorum_size
        return achieved or impossible

    def fast_all_resolved(self):
        return all(self.fast_resolved(s) for s in self.shards)


def _reconcile(name, topologies, make_tracker, feed, is_success, outcomes):
    nodes = tuple(sorted({n for t in topologies for s in t.shards
                          for n in s.nodes}))
    checked = 0
    for by_node, order in _enumerate(nodes, outcomes):
        tracker = make_tracker(topologies)
        oracle = _Oracle(topologies)
        decided = None
        for step, node in enumerate(order):
            outcome = by_node[node]
            got = feed(tracker, node, outcome)
            oracle.feed(node, outcome)
            if decided is None:
                expect = (RequestStatus.FAILED if oracle.failed()
                          else RequestStatus.SUCCESS
                          if is_success(oracle) else None)
                if expect is not None:
                    assert got == expect, (
                        f"{name} {by_node} order={order} step {step}: "
                        f"got {got}, oracle says {expect}")
                    decided = expect
                else:
                    assert got == RequestStatus.NO_CHANGE, (
                        f"{name} {by_node} order={order} step {step}: "
                        f"premature {got}")
            else:
                # decision is sticky: no event may flip or re-fire it
                assert got == RequestStatus.NO_CHANGE, (
                    f"{name} {by_node} order={order} step {step}: "
                    f"{got} after {decided}")
            assert tracker.decided == decided
        checked += 1
    assert checked > 0


def _feed_plain(tracker, node, outcome):
    if outcome == FAIL:
        return tracker.on_failure(node)
    return tracker.on_success(node)


def _feed_voting(tracker, node, outcome):
    if outcome == FAIL:
        return tracker.on_failure(node)
    return tracker.on_success(node, outcome == FAST)


def test_quorum_tracker_reconciles():
    for tname, topo in TOPOLOGIES.items():
        for cls in (QuorumTracker, AppliedTracker):
            _reconcile(f"{cls.__name__}/{tname}", topo, lambda t: cls(t),
                       _feed_plain, _Oracle.quorum, (SLOW, FAIL))


def test_fast_path_tracker_reconciles():
    """Success needs quorum AND the fast path resolved (achieved or dead) in
    every shard -- the tracker must never conclude while fast is undecided."""
    for tname, topo in TOPOLOGIES.items():
        _reconcile(
            f"FastPath/{tname}", topo, lambda t: FastPathTracker(t),
            _feed_voting,
            lambda o: o.quorum() and o.fast_all_resolved(),
            (FAST, SLOW, FAIL))


def test_recovery_tracker_reconciles():
    """Success is plain quorum; rejects_fast_path must equal the positive-
    reject arithmetic at every step."""
    for tname, topo in TOPOLOGIES.items():
        nodes = tuple(sorted({n for t in topo for s in t.shards
                              for n in s.nodes}))
        for by_node, order in _enumerate(nodes, (FAST, SLOW, FAIL)):
            tracker = RecoveryTracker(topo)
            oracle = _Oracle(topo)
            for node in order:
                _feed_voting(tracker, node, by_node[node])
                oracle.feed(node, by_node[node])
                expect = any(
                    s.rejects_fast_path(
                        len(oracle.slow[id(s)] & s.fast_path_electorate))
                    for s in oracle.shards)
                assert tracker.rejects_fast_path() == expect, \
                    f"{tname} {by_node} order={order}"


def test_invalidation_tracker_reconciles():
    """Success is the promise quorum; is_fast_path_rejected must equal the
    positive-reject arithmetic (failures excluded) at every step."""
    for tname, topo in TOPOLOGIES.items():
        key = Keys([60]) if tname == "2shard" else Keys([10])
        nodes = tuple(sorted({n for t in topo for s in t.shards
                              for n in s.nodes}))
        for by_node, order in _enumerate(nodes, (FAST, SLOW, FAIL)):
            tracker = InvalidationTracker(topo, key, fast_path_epoch=1)
            fast_shards = [s for t in topo for s in t.shards_for(key)]
            oracle = _Oracle(topo)
            for node in order:
                _feed_voting(tracker, node, by_node[node])
                oracle.feed(node, by_node[node])
                expect = bool(fast_shards) and all(
                    s.rejects_fast_path(
                        len(oracle.slow[id(s)] & s.fast_path_electorate))
                    for s in fast_shards)
                assert tracker.is_fast_path_rejected() == expect, \
                    f"{tname} {by_node} order={order}"
