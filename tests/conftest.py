"""Test configuration: force JAX onto a virtual 8-device CPU mesh so sharding
tests run hermetically without TPU hardware (the driver separately validates
the multi-chip path via __graft_entry__.dryrun_multichip).

Note: this machine's site customization registers an 'axon' TPU backend and
hard-sets jax.config.jax_platforms, so the env var alone is not enough -- we
must update the config after importing jax (before any backend init).
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("ACCORD_TPU_PARANOIA", "superlinear")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
