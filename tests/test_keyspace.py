import random

from accord_tpu.primitives import Keys, Range, Ranges


def test_keys_basic():
    k = Keys.of(3, 1, 2, 2)
    assert list(k) == [1, 2, 3]
    assert 2 in k and 5 not in k
    assert k.union(Keys.of(4)).as_tuple() == (1, 2, 3, 4)
    assert k.intersection(Keys.of(2, 3, 9)).as_tuple() == (2, 3)
    assert k.difference(Keys.of(2)).as_tuple() == (1, 3)


def test_keys_slice():
    k = Keys.of(*range(10))
    r = Ranges.of(Range(2, 5), Range(8, 100))
    assert k.slice(r).as_tuple() == (2, 3, 4, 8, 9)
    assert k.intersects(r)
    assert not Keys.of(6, 7).intersects(Ranges.of(Range(0, 6), Range(8, 9)))


def test_ranges_normalize():
    r = Ranges.of(Range(5, 8), Range(0, 3), Range(2, 6))
    assert list(r) == [Range(0, 8)]
    r2 = Ranges.of(Range(0, 2), Range(4, 6))
    assert len(r2) == 2


def test_ranges_ops():
    a = Ranges.of(Range(0, 10), Range(20, 30))
    b = Ranges.of(Range(5, 25))
    assert a.intersects(b)
    assert list(a.intersection(b)) == [Range(5, 10), Range(20, 25)]
    assert list(a.difference(b)) == [Range(0, 5), Range(25, 30)]
    assert a.contains_key(9) and not a.contains_key(15)
    assert a.contains_ranges(Ranges.of(Range(1, 3), Range(21, 22)))
    assert not a.contains_ranges(Ranges.of(Range(9, 11)))


def test_point_ranges():
    k = Keys.of(1, 5)
    pr = k.to_ranges()
    assert pr.contains_key(1) and pr.contains_key(5)
    assert not pr.contains_key(2)
    # successor bound: point range of k contains exactly k
    assert Range.point(1).contains(1)
    assert not Range.point(1).contains(2)
    assert not Range.point(1).contains(0)


def test_randomized_ranges_vs_naive():
    rng = random.Random(42)
    for _ in range(50):
        def mk():
            out = []
            for _ in range(rng.randrange(1, 6)):
                s = rng.randrange(0, 50)
                out.append(Range(s, s + rng.randrange(1, 10)))
            return Ranges.of(*out)

        a, b = mk(), mk()
        domain = range(0, 70)
        na = {x for x in domain if a.contains_key(x)}
        nb = {x for x in domain if b.contains_key(x)}
        un, it, df = a.union(b), a.intersection(b), a.difference(b)
        assert {x for x in domain if un.contains_key(x)} == na | nb
        assert {x for x in domain if it.contains_key(x)} == na & nb
        assert {x for x in domain if df.contains_key(x)} == na - nb
        assert a.intersects(b) == bool(na & nb)
