import random

from accord_tpu.primitives import Deps, KeyDeps, RangeDeps, Range, Ranges, TxnId, TxnKind
from accord_tpu.primitives.deps import KeyDepsBuilder, RangeDepsBuilder


def tid(hlc, node=1, kind=TxnKind.WRITE):
    return TxnId.create(1, hlc, node, kind)


def test_keydeps_builder_csr():
    kd = KeyDeps.of({1: [tid(3), tid(1)], 5: [tid(1)]})
    assert kd.for_key(1) == (tid(1), tid(3))
    assert kd.for_key(5) == (tid(1),)
    assert kd.for_key(9) == ()
    assert kd.all_txn_ids() == (tid(1), tid(3))
    assert kd.contains(tid(3)) and not kd.contains(tid(9))
    assert kd.max_txn_id() == tid(3)
    assert kd.participating_keys(tid(1)).as_tuple() == (1, 5)
    assert kd.participating_keys(tid(3)).as_tuple() == (1,)


def test_keydeps_union_slice_without():
    a = KeyDeps.of({1: [tid(1)], 2: [tid(2)]})
    b = KeyDeps.of({2: [tid(3)], 4: [tid(4)]})
    u = a.union(b)
    assert u.for_key(2) == (tid(2), tid(3))
    s = u.slice(Ranges.of(Range(2, 5)))
    assert s.for_key(1) == () and s.for_key(2) == (tid(2), tid(3))
    w = u.without(lambda t: t.hlc <= 2)
    assert w.for_key(1) == () and w.for_key(2) == (tid(3),)


def test_keydeps_randomized_vs_naive():
    rng = random.Random(7)
    for _ in range(30):
        naive = {}
        b = KeyDepsBuilder()
        for _ in range(rng.randrange(0, 60)):
            k = rng.randrange(8)
            t = tid(rng.randrange(20), rng.randrange(3))
            naive.setdefault(k, set()).add(t)
            b.add(k, t)
        kd = b.build()
        for k in range(8):
            assert kd.for_key(k) == tuple(sorted(naive.get(k, set())))
        assert kd.all_txn_ids() == tuple(sorted(set().union(*naive.values()) if naive else set()))


def test_rangedeps():
    rd = RangeDeps.of({Range(0, 10): [tid(1)], Range(5, 15): [tid(2)]})
    assert rd.for_key(7) == (tid(1), tid(2))
    assert rd.for_key(12) == (tid(2),)
    assert rd.intersecting(Range(14, 20)) == (tid(2),)
    assert rd.intersecting(Range(20, 30)) == ()
    s = rd.slice(Ranges.of(Range(0, 6)))
    assert s.for_key(12) == ()
    assert s.for_key(3) == (tid(1),)


def test_deps_merge():
    d1 = Deps(KeyDeps.of({1: [tid(1)]}), RangeDeps.of({Range(0, 5): [tid(2)]}))
    d2 = Deps(KeyDeps.of({1: [tid(3)]}))
    m = Deps.merge([d1, d2])
    assert m.for_key(1) == (tid(1), tid(2), tid(3))
    assert m.contains(tid(2))
    assert m.max_txn_id() == tid(3)
    assert not m.is_empty()
    assert Deps.NONE.is_empty()
