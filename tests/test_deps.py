import random

from accord_tpu.primitives import Deps, KeyDeps, RangeDeps, Range, Ranges, TxnId, TxnKind
from accord_tpu.primitives.deps import KeyDepsBuilder, RangeDepsBuilder


def tid(hlc, node=1, kind=TxnKind.WRITE):
    return TxnId.create(1, hlc, node, kind)


def test_keydeps_builder_csr():
    kd = KeyDeps.of({1: [tid(3), tid(1)], 5: [tid(1)]})
    assert kd.for_key(1) == (tid(1), tid(3))
    assert kd.for_key(5) == (tid(1),)
    assert kd.for_key(9) == ()
    assert kd.all_txn_ids() == (tid(1), tid(3))
    assert kd.contains(tid(3)) and not kd.contains(tid(9))
    assert kd.max_txn_id() == tid(3)
    assert kd.participating_keys(tid(1)).as_tuple() == (1, 5)
    assert kd.participating_keys(tid(3)).as_tuple() == (1,)


def test_keydeps_union_slice_without():
    a = KeyDeps.of({1: [tid(1)], 2: [tid(2)]})
    b = KeyDeps.of({2: [tid(3)], 4: [tid(4)]})
    u = a.union(b)
    assert u.for_key(2) == (tid(2), tid(3))
    s = u.slice(Ranges.of(Range(2, 5)))
    assert s.for_key(1) == () and s.for_key(2) == (tid(2), tid(3))
    w = u.without(lambda t: t.hlc <= 2)
    assert w.for_key(1) == () and w.for_key(2) == (tid(3),)


def test_keydeps_randomized_vs_naive():
    rng = random.Random(7)
    for _ in range(30):
        naive = {}
        b = KeyDepsBuilder()
        for _ in range(rng.randrange(0, 60)):
            k = rng.randrange(8)
            t = tid(rng.randrange(20), rng.randrange(3))
            naive.setdefault(k, set()).add(t)
            b.add(k, t)
        kd = b.build()
        for k in range(8):
            assert kd.for_key(k) == tuple(sorted(naive.get(k, set())))
        assert kd.all_txn_ids() == tuple(sorted(set().union(*naive.values()) if naive else set()))


def test_rangedeps():
    rd = RangeDeps.of({Range(0, 10): [tid(1)], Range(5, 15): [tid(2)]})
    assert rd.for_key(7) == (tid(1), tid(2))
    assert rd.for_key(12) == (tid(2),)
    assert rd.intersecting(Range(14, 20)) == (tid(2),)
    assert rd.intersecting(Range(20, 30)) == ()
    s = rd.slice(Ranges.of(Range(0, 6)))
    assert s.for_key(12) == ()
    assert s.for_key(3) == (tid(1),)


def test_deps_merge():
    d1 = Deps(KeyDeps.of({1: [tid(1)]}), RangeDeps.of({Range(0, 5): [tid(2)]}))
    d2 = Deps(KeyDeps.of({1: [tid(3)]}))
    m = Deps.merge([d1, d2])
    assert m.for_key(1) == (tid(1), tid(2), tid(3))
    assert m.contains(tid(2))
    assert m.max_txn_id() == tid(3)
    assert not m.is_empty()
    assert Deps.NONE.is_empty()


def test_rangedeps_randomized_vs_naive():
    """Randomized union/slice/without/merge/point+overlap queries against a
    naive interval-list model (reference: DepsTest.java's random-vs-model
    strategy)."""
    rng = random.Random(11)
    for trial in range(30):
        naive = []  # list of (Range, txn_id)
        b = RangeDepsBuilder()
        for _ in range(rng.randrange(0, 50)):
            s = rng.randrange(0, 90)
            r = Range(s, s + 1 + rng.randrange(12))
            t = tid(rng.randrange(25), rng.randrange(3))
            naive.append((r, t))
            b.add(r, t)
        rd = b.build()

        def naive_for_key(k):
            return tuple(sorted({t for r, t in naive
                                 if r.start <= k < r.end}))

        def naive_intersecting(q):
            return tuple(sorted({t for r, t in naive
                                 if r.start < q.end and q.start < r.end}))

        for k in rng.sample(range(0, 105), 12):
            assert rd.for_key(k) == naive_for_key(k), f"trial {trial} key {k}"
        for _ in range(6):
            s = rng.randrange(0, 100)
            q = Range(s, s + 1 + rng.randrange(15))
            assert rd.intersecting(q) == naive_intersecting(q), \
                f"trial {trial} query {q}"
        assert rd.all_txn_ids() == tuple(sorted({t for _, t in naive}))

        # slice: only intersections with the window survive
        s = rng.randrange(0, 80)
        window = Ranges.of(Range(s, s + 20))
        sliced = rd.slice(window)
        for k in range(max(0, s - 3), s + 23):
            inside = s <= k < s + 20
            expect = naive_for_key(k) if inside else ()
            assert sliced.for_key(k) == expect, \
                f"trial {trial} slice key {k}"

        # without: predicate drops ids everywhere
        cut = rng.randrange(25)
        wo = rd.without(lambda t: t.hlc < cut)
        for k in rng.sample(range(0, 105), 8):
            assert wo.for_key(k) == tuple(
                t for t in naive_for_key(k) if not t.hlc < cut)

        # union == merge of the same content split in two
        split = rng.randrange(0, len(naive) + 1)
        b1, b2 = RangeDepsBuilder(), RangeDepsBuilder()
        for i, (r, t) in enumerate(naive):
            (b1 if i < split else b2).add(r, t)
        u = b1.build().union(b2.build())
        m = RangeDeps.merge([b1.build(), b2.build()])
        for k in rng.sample(range(0, 105), 8):
            assert u.for_key(k) == naive_for_key(k)
            assert m.for_key(k) == naive_for_key(k)


def test_deps_randomized_vs_naive():
    """Combined key+range Deps: union/slice/without/participants_of against
    naive models."""
    rng = random.Random(13)
    for trial in range(20):
        key_naive = {}
        range_naive = []
        kb, rb = KeyDepsBuilder(), RangeDepsBuilder()
        for _ in range(rng.randrange(0, 40)):
            t = tid(rng.randrange(20), rng.randrange(3))
            if rng.random() < 0.6:
                k = rng.randrange(12)
                key_naive.setdefault(k, set()).add(t)
                kb.add(k, t)
            else:
                s = rng.randrange(0, 30)
                r = Range(s, s + 1 + rng.randrange(8))
                range_naive.append((r, t))
                rb.add(r, t)
        d = Deps(kb.build(), rb.build())

        def naive_for_key(k):
            out = set(key_naive.get(k, set()))
            out |= {t for r, t in range_naive if r.start <= k < r.end}
            return tuple(sorted(out))

        for k in range(0, 34):
            assert d.for_key(k) == naive_for_key(k), f"trial {trial} key {k}"

        all_ids = {t for ts in key_naive.values() for t in ts} \
            | {t for _, t in range_naive}
        assert d.all_txn_ids() == tuple(sorted(all_ids))
        for t in sorted(all_ids)[:6]:
            assert d.contains(t)
            parts = d.participants_of(t)
            # every key the id was attached to must be covered
            for k, ts in key_naive.items():
                if t in ts:
                    assert parts is not None and k in tuple(parts), \
                        f"trial {trial}: {t} lost key {k}"

        cut = rng.randrange(20)
        wo = d.without(lambda t: t.hlc < cut)
        for k in range(0, 34):
            assert wo.for_key(k) == tuple(
                t for t in naive_for_key(k) if not t.hlc < cut)

        s = rng.randrange(0, 25)
        window = Ranges.of(Range(s, s + 10))
        sliced = d.slice(window)
        for k in range(0, 40):
            expect = naive_for_key(k) if s <= k < s + 10 else ()
            assert sliced.for_key(k) == expect, f"trial {trial} slice {k}"
