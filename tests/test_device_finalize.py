"""Device-side dep finalization: the finalized-CSR harvest (exact key
filtering + segment compaction ON device) must answer bit-identically to
the legacy unpackbits decode -- which is itself tested bit-identical to
the host scans -- across randomized mixed key/range workloads, truncation
and prune churn, compaction landing between dispatch and harvest, and
fused multi-store dispatches. The finalized counters prove the fast path
actually ran: any nonzero legacy_decodes on a healthy run means the
kernels silently handed decode back to the host."""
from __future__ import annotations

import numpy as np

from accord_tpu.local.cfk import CfkStatus
from accord_tpu.ops.resolver import BatchDepsResolver
from accord_tpu.primitives.keyspace import Keys, Range, Ranges
from accord_tpu.primitives.timestamp import Domain, Timestamp, TxnId, TxnKind
from tests.test_fused_dispatch import (_attach, _far, _mixed_subjects,
                                       _register_keys,
                                       _register_mixed_per_store, _run_async,
                                       _store_lo, _two_store_node)
from tests.test_local_engine import setup_store
from tests.test_range_device_deps import _register_mixed, _subjects


def _assert_clean(resolver):
    assert resolver.host_fallbacks == 0
    assert resolver.range_fallbacks == 0
    assert resolver.finalize_fallbacks == 0


def test_finalized_vs_legacy_randomized_differential():
    """The load-bearing differential: same store state, same subjects,
    finalize_on_device=True vs =False must produce identical Deps (and both
    must equal the host scan). The counters prove which decode ran."""
    rng = np.random.default_rng(1234)
    _, node, store = setup_store()
    fin = BatchDepsResolver(num_buckets=128, initial_cap=128)
    assert fin.finalize_on_device  # the default IS the finalized path
    store.deps_resolver = fin
    _, tss = _register_mixed(store, node, rng)

    subs = _subjects(store, node, rng, tss, n=40)
    fin_res = [fin.resolve_one(store, tid, owned, before)
               for tid, owned, before in subs]
    assert fin.finalized_decodes > 0, "finalized path never engaged"
    assert fin.legacy_decodes == 0, "finalized run leaked into legacy decode"
    _assert_clean(fin)

    # a fresh resolver adopts the same store state; finalize off = the
    # legacy unpackbits decode, bit-identical by construction
    leg = BatchDepsResolver(num_buckets=128, initial_cap=128,
                            finalize_on_device=False)
    store.deps_resolver = leg
    leg_res = [leg.resolve_one(store, tid, owned, before)
               for tid, owned, before in subs]
    assert leg.finalized_decodes == 0
    assert leg.legacy_decodes > 0
    _assert_clean(leg)

    key_seen = range_seen = 0
    for (tid, owned, before), fd, ld in zip(subs, fin_res, leg_res):
        assert fd == ld, f"finalized vs legacy diverge on {tid}"
        host = store.host_calculate_deps(tid, owned, before)
        assert fd == host, f"finalized vs host diverge on {tid}"
        key_seen += bool(host.key_deps.all_txn_ids())
        range_seen += bool(host.range_deps.all_txn_ids())
    assert key_seen > 0 and range_seen > 0, "differential vacuous"


def test_finalized_truncation_and_prune():
    """Truncate half the range txns and prune keys off some key txns; the
    finalized path must keep answering exactly (the kid table and interval
    arena shrink with the churn) with no truncated id surviving in any
    answer and no fallback to legacy decode."""
    rng = np.random.default_rng(77)
    _, node, store = setup_store()
    resolver = BatchDepsResolver(num_buckets=128, initial_cap=128)
    store.deps_resolver = resolver
    rids, tss = _register_mixed(store, node, rng, n_key=40, n_range=30)

    arena = resolver._arenas[id(store)]
    for tid in rids[::2]:
        store.range_txns.pop(tid, None)
        store.range_index.remove(tid)
        resolver.on_truncate(store, tid)
    # prune one entry off several keys' cfks, mirrored into the arena the
    # way store._deregister does, so kid-table row masks and kseq move
    # mid-differential
    pruned = 0
    for key in sorted(store.cfks)[:8]:
        cfk = store.cfks[key]
        for t in sorted(cfk._infos)[:1]:
            cfk.remove(t)
            resolver.on_prune(store, t, (key,))
            pruned += 1
    assert pruned > 0

    nonempty = 0
    truncated = set(rids[::2])
    for tid, owned, before in _subjects(store, node, rng, tss, n=24):
        host = store.host_calculate_deps(tid, owned, before)
        dev = resolver.resolve_one(store, tid, owned, before)
        assert dev == host, f"subject {tid} after truncation/prune"
        assert not (set(dev.range_deps.all_txn_ids()) & truncated)
        nonempty += bool(host.key_deps.all_txn_ids()
                         or host.range_deps.all_txn_ids())
    assert nonempty > 0, "differential vacuous"
    assert resolver.finalized_decodes > 0
    assert resolver.legacy_decodes == 0
    _assert_clean(resolver)


def test_compaction_between_dispatch_and_harvest_falls_back_exactly():
    """Compact the key arena while a finalized call is in flight: the
    kseq/gen guard must reject the device CSR (its row ids predate the
    compaction) and the harvest must fall back to the legacy decode over
    the PINNED id snapshot -- still exact, still no host fallback."""
    rng = np.random.default_rng(55)
    cluster, node, store = setup_store()
    resolver = BatchDepsResolver(num_buckets=128, initial_cap=128)
    store.deps_resolver = resolver
    store.batch_window_ms = 0.5
    node.device_latency_ms = 50.0
    node.device_poll_ms = 1.0
    lo = 0

    # prunable chaff (disjoint keys) so compaction can reclaim rows, plus
    # live rows the in-flight subjects actually depend on
    chaff_keys = [sorted({lo + int(k) for k in rng.integers(100, 140, 2)})
                  for _ in range(50)]
    chaff = _register_keys(store, node, chaff_keys)
    live = [sorted({lo + int(k) for k in rng.integers(0, 12, 2)})
            for _ in range(30)]
    _register_keys(store, node, live)
    for t, ks in zip(chaff, chaff_keys):
        resolver.on_prune(store, t, ks)

    arena = resolver._arenas[id(store)]
    far = _far(node)
    subs = []
    for i in range(6):
        tid = node.next_txn_id(TxnKind.WRITE, Domain.KEY)
        keys = Keys(live[10 + i])
        subs.append((tid, keys, far,
                     resolver.enqueue_deps(store, tid, keys, far)))

    while resolver.dispatches < 1:
        assert cluster.queue.process_one(), "tick never fired"
    assert all(not out.done for *_, out in subs)

    gen0 = arena.gen
    assert arena.compact(), "compaction should reclaim the pruned chaff"
    assert arena.gen == gen0 + 1
    assert gen0 in arena.retired_ids  # in-flight pin forced a snapshot

    while not all(out.done for *_, out in subs):
        assert cluster.queue.process_one(), "harvest never fired"
    assert resolver.stale_harvests >= 1
    # the guard tripped: the finalized CSR was discarded for the stale
    # group and the legacy decode ran over the pinned snapshot instead
    assert resolver.finalize_fallbacks >= 1
    assert resolver.host_fallbacks == 0
    cluster.queue.drain(max_events=10_000)
    assert gen0 not in arena.retired_ids  # pin released on harvest

    nonempty = 0
    for tid, keys, before, out in subs:
        host = store.host_calculate_deps(tid, keys, before)
        assert out.value() == host, f"subject {tid} across compaction"
        nonempty += bool(host.key_deps.all_txn_ids())
    assert nonempty > 0, "differential vacuous"

    # and a healthy resolve afterwards goes straight back to finalized
    f0 = resolver.finalized_decodes
    tid = node.next_txn_id(TxnKind.WRITE, Domain.KEY)
    dev = resolver.resolve_one(store, tid, Keys(live[0]), _far(node))
    assert dev == store.host_calculate_deps(tid, Keys(live[0]), _far(node))
    assert resolver.finalized_decodes == f0 + 1


def test_range_compaction_in_flight_finalized_range_guard():
    """The range twin: truncating + compacting the INTERVAL arena while a
    finalized call is in flight must trip the rseq/rgen guard for key
    subjects' range deps and still answer exactly via the translated
    candidate decode."""
    rng = np.random.default_rng(29)
    cluster, node, store = setup_store()
    resolver = BatchDepsResolver(num_buckets=128, initial_cap=128)
    store.deps_resolver = resolver
    store.batch_window_ms = 0.5
    node.device_latency_ms = 50.0
    node.device_poll_ms = 1.0
    rids, _ = _register_mixed(store, node, rng, n_key=30, n_range=40)

    arena = resolver._arenas[id(store)]
    far = Timestamp(node.epoch, node.time_service.now_micros() + 50_000,
                    0, node.id)
    subs = []
    for i in range(8):
        owned = store.owned(Keys(sorted(
            {int(k) for k in rng.integers(0, 1 << 16, 8)})))
        tid = node.next_txn_id(TxnKind.WRITE, Domain.KEY)
        subs.append((tid, owned, far,
                     resolver.enqueue_deps(store, tid, owned, far)))

    while resolver.dispatches < 1:
        assert cluster.queue.process_one(), "tick never fired"

    for tid in rids[:20]:
        store.range_txns.pop(tid, None)
        store.range_index.remove(tid)
        resolver.on_truncate(store, tid)
    rgen0 = arena.ranges.gen
    assert arena.ranges.compact(), "compaction should reclaim rows"

    while not all(out.done for *_, out in subs):
        assert cluster.queue.process_one(), "harvest never fired"
    assert resolver.stale_harvests >= 1
    assert resolver.host_fallbacks == 0
    cluster.queue.drain(max_events=10_000)

    nonempty = 0
    truncated = set(rids[:20])
    for tid, owned, before, out in subs:
        host = store.host_calculate_deps(tid, owned, before)
        assert out.value() == host, f"subject {tid} across range compaction"
        got = set(out.value().key_deps.all_txn_ids())
        assert not (got & truncated)
        # range txns hit by a KEY subject land in key_deps (per-key
        # attribution); count them to prove the stab was exercised
        nonempty += any(t.domain == Domain.RANGE for t in got)
    assert nonempty > 0, "differential vacuous"


def test_fused_multi_store_finalized_differential():
    """Fused cross-store dispatches ride the finalized path end to end:
    each participating store's group materializes from its own device CSR
    slice, answers match both the legacy-decode resolver and the host
    scans, and no group leaks into legacy decode."""
    rng = np.random.default_rng(63)
    cluster, node, stores = _two_store_node()
    fin = BatchDepsResolver(num_buckets=128, initial_cap=128)
    _attach(stores, node, fin, latency=5.0)
    for s in stores:
        _register_mixed_per_store(s, node, rng)

    subs = []
    for wave_rng in (np.random.default_rng(3), np.random.default_rng(4)):
        wave = []
        for s in stores:
            wave.extend(_mixed_subjects(s, node, wave_rng, 9))
        subs.append(wave)

    fin_res = []
    for wave in subs:
        fin_res.extend(_run_async(cluster, fin, wave))
    assert fin.dispatches < 2 * fin.ticks, "fused path disengaged"
    assert fin.finalized_decodes >= 2, "both stores' groups should finalize"
    assert fin.legacy_decodes == 0
    _assert_clean(fin)

    leg = BatchDepsResolver(num_buckets=128, initial_cap=128,
                            finalize_on_device=False)
    leg_res = []
    for wave in subs:
        leg_res.extend(_run_async(cluster, leg, wave))
    assert leg.finalized_decodes == 0 and leg.legacy_decodes > 0

    key_seen = range_seen = 0
    for (store, tid, owned, before), fd, ld in zip(
            [x for wave in subs for x in wave], fin_res, leg_res):
        assert fd == ld, f"finalized vs legacy diverge on {tid}"
        host = store.host_calculate_deps(tid, owned, before)
        assert fd == host, f"finalized vs host diverge on {tid}"
        key_seen += bool(host.key_deps.all_txn_ids())
        range_seen += bool(host.range_deps.all_txn_ids())
    assert key_seen > 0 and range_seen > 0, "differential vacuous"


def test_packed_segment_compact_overflow_signal():
    """A nonzero-word count whose BIT total exceeds out_cap must surface as
    indptr[-1] > out_cap -- the exact total, computed from popcounts before
    any scatter can drop -- never as a silently truncated CSR that decodes
    as a plausible-but-short dep list."""
    import jax.numpy as jnp

    from accord_tpu.ops.kernels import _packed_segment_compact

    rng = np.random.default_rng(13)
    m = rng.integers(0, 1 << 32, (4, 8), dtype=np.uint64).astype(np.uint32)
    total = int(np.unpackbits(m.view(np.uint8)).sum())
    out_cap = 32
    assert total > out_cap  # dense random words: ~512 bits
    indptr, dep_rows = _packed_segment_compact(jnp.asarray(m), out_cap)
    indptr = np.asarray(indptr)
    assert indptr[-1] == total > out_cap, "overflow signal lost"
    # per-segment counts stay exact too (they come from the popcount pass)
    pops = [int(np.unpackbits(row.view(np.uint8)).sum()) for row in m]
    assert np.array_equal(np.diff(indptr), pops)

    # and under the cap the compaction is the ground-truth bit walk
    m2 = np.zeros((3, 2), np.uint32)
    m2[0, 0] = 0b1010001
    m2[1, 1] = 1 << 31
    indptr2, rows2 = _packed_segment_compact(jnp.asarray(m2), 32)
    indptr2, rows2 = np.asarray(indptr2), np.asarray(rows2)
    assert indptr2.tolist() == [0, 3, 4, 4]
    assert rows2[:4].tolist() == [0, 4, 6, 63]


def test_out_cap_overflow_bumps_tier_and_falls_back_exactly():
    """Force the hysteresis picker to pin an undersized out_cap (seed the
    lane with a tiny observed bound), then resolve a subject with more deps
    than the tier holds: the overflow must bump the ladder, the ONE
    overflowing group must decode bit-identically through the legacy
    fallback, and the next dispatch must finalize cleanly on the bumped
    tier."""
    rng = np.random.default_rng(17)
    _, node, store = setup_store()
    resolver = BatchDepsResolver(num_buckets=128, initial_cap=1024)
    store.deps_resolver = resolver

    hot = 7
    for i in range(300):
        ts = node.unique_now()
        tid = TxnId.create(ts.epoch, ts.hlc, ts.node, TxnKind.WRITE,
                           Domain.KEY)
        ks = {hot} | {int(k) for k in rng.integers(0, 1 << 16, 2)}
        store.register(tid, Keys(sorted(ks)), CfkStatus.WITNESSED, ts)

    arena = resolver._arenas[id(store)]
    pol = resolver._outcap(arena, "key")
    pol.observe(8, 8)  # fake a quiet dispatch: estimate pins the 256 tier
    assert not pol.cold

    far = Timestamp(node.epoch, node.time_service.now_micros() + 50_000,
                    0, node.id)
    tid = node.next_txn_id(TxnKind.WRITE, Domain.KEY)
    owned = store.owned(Keys([hot]))
    host = store.host_calculate_deps(tid, owned, far)
    assert len(host.key_deps.all_txn_ids()) >= 300  # > the 256 rung
    dev = resolver.resolve_one(store, tid, owned, far)
    assert dev == host, "overflow fallback diverged from the host scan"
    assert resolver.finalize_fallbacks == 1
    assert resolver.legacy_decodes == 1
    assert pol.current >= 2048, "overflow did not bump the pinned tier"
    assert resolver.outcap_tier_switches >= 1

    # steady state after the bump: straight back to the finalized path
    f0, ff0 = resolver.finalized_decodes, resolver.finalize_fallbacks
    tid2 = node.next_txn_id(TxnKind.WRITE, Domain.KEY)
    dev2 = resolver.resolve_one(store, tid2, owned, far)
    assert dev2 == store.host_calculate_deps(tid2, owned, far)
    assert resolver.finalized_decodes == f0 + 1
    assert resolver.finalize_fallbacks == ff0
    assert resolver.host_fallbacks == 0


def test_device_bound_and_range_stab_randomized_differential():
    """The retired host residuals, differentially: the default resolver
    (device-computed out-cap bound + on-device range-subject stabbing) vs
    the flagged host-bound baseline (device_out_bound=False) vs the legacy
    unpackbits decode (finalize_on_device=False) -- all bit-identical to
    the host scans over a randomized mixed workload with multi-piece range
    subjects, before AND after truncation/prune churn."""
    rng = np.random.default_rng(2718)
    _, node, store = setup_store()
    dev = BatchDepsResolver(num_buckets=128, initial_cap=128)
    hostb = BatchDepsResolver(num_buckets=128, initial_cap=128,
                              device_out_bound=False)
    leg = BatchDepsResolver(num_buckets=128, initial_cap=128,
                            finalize_on_device=False)
    assert dev.device_out_bound
    store.deps_resolver = dev
    rids, tss = _register_mixed(store, node, rng)

    def sweep(subs):
        key_seen = range_seen = 0
        for tid, owned, before in subs:
            host = store.host_calculate_deps(tid, owned, before)
            for r in (dev, hostb, leg):
                store.deps_resolver = r
                got = r.resolve_one(store, tid, owned, before)
                assert got == host, f"{tid} diverged (bound/stab config)"
            key_seen += bool(host.key_deps.all_txn_ids())
            range_seen += bool(host.range_deps.all_txn_ids())
        assert key_seen > 0 and range_seen > 0, "differential vacuous"

    subs = _subjects(store, node, rng, tss, n=36)
    # the population includes multi-piece range subjects (the per-piece
    # segment lanes under test)
    assert any(not isinstance(o, Keys) and len(list(o)) > 1
               for _, o, _ in subs)
    sweep(subs)
    # the device path really decoded range subjects from the stab, with no
    # legacy decode and no guard trips; the host-bound baseline rides the
    # same finalized path (only the out_cap sizing differs)
    assert dev.range_subject_device_decodes > 0
    assert dev.legacy_decodes == 0 and dev.finalize_fallbacks == 0
    # the range lane's out_cap is now fed by the DEVICE stab-count bound
    # riding back with each range_finalize_csr result: after the first
    # dispatch the policy is warm, so steady-state range sizing pays no
    # host entries*nvalid pass (and the differential above proves the
    # device-bound-sized caps never undersize the compaction)
    rpol = dev._outcap(dev._arenas[id(store)], "range")
    assert not rpol.cold, "range lane never observed a device stab bound"
    assert hostb.range_subject_device_decodes > 0
    assert hostb.legacy_decodes == 0 and hostb.finalize_fallbacks == 0
    assert leg.legacy_decodes > 0 and leg.finalized_decodes == 0

    # truncate half the range txns + prune a few key entries, mirrored into
    # every resolver (store._deregister fans out the same way), then the
    # whole differential must keep holding on the shrunk arenas
    for tid in rids[::2]:
        store.range_txns.pop(tid, None)
        store.range_index.remove(tid)
        for r in (dev, hostb, leg):
            r.on_truncate(store, tid)
    pruned = 0
    for key in sorted(store.cfks)[:6]:
        cfk = store.cfks[key]
        for t in sorted(cfk._infos)[:1]:
            cfk.remove(t)
            for r in (dev, hostb, leg):
                r.on_prune(store, t, (key,))
            pruned += 1
    assert pruned > 0
    sweep(_subjects(store, node, rng, tss, n=24))
    for r in (dev, hostb, leg):
        assert r.host_fallbacks == 0
        assert r.range_fallbacks == 0


def test_finalized_truncation_output_cap_growth():
    """Dep lists wider than the first OUT_TIER must grow the output
    capacity tier, not truncate: one hot key touched by hundreds of txns
    answers exactly (indptr overflow would silently drop deps if out_cap
    were pinned to the smallest tier)."""
    rng = np.random.default_rng(91)
    _, node, store = setup_store()
    resolver = BatchDepsResolver(num_buckets=128, initial_cap=1024)
    store.deps_resolver = resolver

    hot = 7
    for i in range(300):
        ts = node.unique_now()
        tid = TxnId.create(ts.epoch, ts.hlc, ts.node, TxnKind.WRITE,
                           Domain.KEY)
        ks = {hot} | {int(k) for k in rng.integers(0, 1 << 16, 2)}
        store.register(tid, Keys(sorted(ks)), CfkStatus.WITNESSED, ts)

    far = Timestamp(node.epoch, node.time_service.now_micros() + 50_000,
                    0, node.id)
    tid = node.next_txn_id(TxnKind.WRITE, Domain.KEY)
    owned = store.owned(Keys([hot]))
    host = store.host_calculate_deps(tid, owned, far)
    assert len(host.key_deps.all_txn_ids()) >= 300
    dev = resolver.resolve_one(store, tid, owned, far)
    assert dev == host
    assert resolver.finalized_decodes == 1
    assert resolver.legacy_decodes == 0
    _assert_clean(resolver)
