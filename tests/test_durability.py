"""Durability rounds, truncation and the pruning floor.

Mirrors the reference's durability machinery (impl/
CoordinateDurabilityScheduling.java:53-77, local/DurableBefore.java:39,
local/Cleanup.java, cfk/Pruning.java:41): background ExclusiveSyncPoint
rounds advance a majority-durable floor, below which (when also locally
redundant) per-txn state is truncated; probes for truncated ids answer
TRUNCATED; state growth plateaus instead of growing with workload size.
"""
from __future__ import annotations

import pytest

from accord_tpu.impl.durability import (
    CoordinateGloballyDurable, CoordinateShardDurable,
)
from accord_tpu.local.status import Status
from accord_tpu.primitives.keyspace import Keys, Ranges
from accord_tpu.primitives.timestamp import TxnKind
from accord_tpu.primitives.txn import Txn
from accord_tpu.sim.burn import run_burn
from accord_tpu.sim.cluster import Cluster, ClusterConfig
from accord_tpu.sim.list_store import ListQuery, ListRead, ListUpdate


def write_txn(keys: Keys, value: int) -> Txn:
    return Txn(TxnKind.WRITE, keys, read=ListRead(keys),
               update=ListUpdate(keys, value), query=ListQuery())


def _run_shard_durable(cluster, node, ranges):
    r = CoordinateShardDurable.run(node, ranges)
    cluster.drain()
    cluster.check_no_failures()
    assert r.done and r.failure is None, f"shard-durable failed: {r.failure!r}"
    return r.value()


def test_shard_durable_round_advances_floor_and_truncates():
    """Two truncation tiers (reference: Cleanup.TRUNCATE_WITH_OUTCOME vs
    ERASE): a shard-durable round SHRINKS records (conflict-registry footprint
    dropped, outcome retained for straggler repair); only a global round
    (universal durability) ERASES them."""
    cluster = Cluster(71, ClusterConfig())
    keys = Keys([100, 200])
    ids = []
    for v in (1, 2, 3):
        res = cluster.nodes[1].coordinate(write_txn(keys, v))
        cluster.drain()
        ids.append(res.value().txn_id)
    cluster.check_no_failures()

    shard0 = cluster.current_topology().shards[0]
    sync_id = _run_shard_durable(cluster, cluster.nodes[1],
                                 Ranges.of(shard0.range))

    for nid in shard0.nodes:
        node = cluster.nodes[nid]
        for s in node.command_stores.all():
            if not s.ranges.contains_key(100):
                continue
            # majority floor advanced to the sync point
            assert s.durable_majority.get(100) == sync_id.as_timestamp()
            # tier A: applied writes below the floor were shrunk -- cfk rows
            # gone, outcome retained (a straggler may still need it)
            for t in ids:
                cmd = s.command_if_present(t)
                assert cmd is not None and cmd.cleaned, \
                    f"{t} not shrunk on node {nid}"
                assert cmd.writes is not None
                c = s.cfks.get(100)
                assert c is None or c.get(t) is None
        assert cluster.stores[nid].snapshot(100) == (1, 2, 3)

    # tier B: a global round erases the records everywhere
    g = CoordinateGloballyDurable.run(cluster.nodes[1])
    cluster.drain()
    cluster.check_no_failures()
    assert g.done and g.failure is None
    for nid in shard0.nodes:
        for s in cluster.nodes[nid].command_stores.all():
            if not s.ranges.contains_key(100):
                continue
            for t in ids:
                assert s.command_if_present(t) is None, \
                    f"{t} not erased on node {nid}"
                assert s.is_truncated(t, keys)
        assert cluster.stores[nid].snapshot(100) == (1, 2, 3)


def test_recovery_of_truncated_txn_returns_truncated():
    from accord_tpu.coordinate.recover import Outcome, Recover
    cluster = Cluster(72, ClusterConfig())
    keys = Keys([500])
    res = cluster.nodes[1].coordinate(write_txn(keys, 9))
    cluster.drain()
    txn_id = res.value().txn_id
    shard0 = cluster.current_topology().shards[0]
    _run_shard_durable(cluster, cluster.nodes[1], Ranges.of(shard0.range))
    # erasure requires universal durability (a global round)
    CoordinateGloballyDurable.run(cluster.nodes[1])
    cluster.drain()
    cluster.check_no_failures()

    # every replica truncated it; a full recovery must conclude TRUNCATED,
    # not invalidate or re-propose (ADVICE round-1 low item)
    r = Recover.recover(cluster.nodes[2], txn_id, write_txn(keys, 9),
                        cluster.nodes[2].compute_route(write_txn(keys, 9)))
    cluster.drain()
    cluster.check_no_failures()
    assert r.done and r.failure is None, f"recover failed: {r.failure!r}"
    assert r.value() == Outcome.TRUNCATED


def test_globally_durable_aggregation():
    cluster = Cluster(73, ClusterConfig())
    keys = Keys([100])
    cluster.nodes[1].coordinate(write_txn(keys, 5))
    cluster.drain()
    shard0 = cluster.current_topology().shards[0]
    sync_id = _run_shard_durable(cluster, cluster.nodes[1],
                                 Ranges.of(shard0.range))
    g = CoordinateGloballyDurable.run(cluster.nodes[1])
    cluster.drain()
    cluster.check_no_failures()
    assert g.done and g.failure is None
    for nid in shard0.nodes:
        for s in cluster.nodes[nid].command_stores.all():
            if s.ranges.contains_key(100):
                assert s.durable_universal.get(100) == sync_id.as_timestamp()


def test_burn_state_plateaus_with_durability():
    """VERDICT round-1 done-criterion: per-store command counts plateau
    instead of growing linearly with ops."""
    import accord_tpu.sim.burn as burn_mod
    from accord_tpu.sim.cluster import Cluster as RealCluster
    captured = []

    class SpyCluster(RealCluster):
        def __init__(self, *a, **k):
            super().__init__(*a, **k)
            captured.append(self)

    totals = {}
    orig = burn_mod.Cluster
    burn_mod.Cluster = SpyCluster
    try:
        for ops in (300, 600):
            captured.clear()
            r = run_burn(74, ops=ops,
                         config=ClusterConfig(durability=True,
                                              durability_interval_ms=250.0))
            assert r.acked == ops and r.lost == 0
            c = captured[0]
            totals[ops] = sum(len(s.commands) for n in c.nodes.values()
                              for s in n.command_stores.all())
    finally:
        burn_mod.Cluster = orig
    # without truncation the residual grows linearly with ops (2x here);
    # with it, the steady-state level is set by the round interval, not ops
    assert totals[600] < totals[300] * 1.5, f"no plateau: {totals}"
    assert totals[600] < 600 * 3, "residual exceeds untruncated floor"


def test_durability_burn_liveness_seed74():
    """Round-2 regression: seed 74, ops=100 ground to 'no quiescence after
    2000000 events'. Root cause: records were ERASED at majority durability,
    so a straggler replica that missed an Apply could never repair its copy
    (probes found only outcome-less TRUNCATED answers) and every later txn +
    durability sync point chained behind it forever. Erasure now requires
    universal durability; outcome-retaining shrink covers the majority tier."""
    for ops in (100, 150):
        r = run_burn(74, ops=ops,
                     config=ClusterConfig(durability=True,
                                          durability_interval_ms=250.0))
        assert r.acked == ops and r.lost == 0


def test_deps_stay_bounded_with_durability():
    """The dep-floor injection (reference: RedundantBefore.collectDeps):
    deps sets must be bounded by the inter-durability-round arrival rate,
    not the total number of live txns."""
    import accord_tpu.sim.burn as burn_mod
    from accord_tpu.sim.cluster import Cluster as RealCluster
    captured = []

    class SpyCluster(RealCluster):
        def __init__(self, *a, **k):
            super().__init__(*a, **k)
            captured.append(self)

    orig = burn_mod.Cluster
    burn_mod.Cluster = SpyCluster
    try:
        r = run_burn(74, ops=600,
                     config=ClusterConfig(durability=True,
                                          durability_interval_ms=250.0))
        assert r.acked == 600
        worst = 0
        for n in captured[0].nodes.values():
            for s in n.command_stores.all():
                for cmd in s.commands.values():
                    if cmd.deps is not None:
                        worst = max(worst, len(cmd.deps.all_txn_ids()))
        # without floor injection the worst sync-point deps enumerate every
        # live txn (hundreds); with it they track the per-round arrival rate
        assert worst < 120, f"deps not bounded: worst={worst}"
    finally:
        burn_mod.Cluster = orig


def test_burn_deterministic_with_durability():
    cfg = dict(ops=120, config=ClusterConfig(durability=True,
                                             durability_interval_ms=250.0))
    a = run_burn(75, collect_log=True, **cfg)
    b = run_burn(75, collect_log=True, **cfg)
    assert a.log == b.log
