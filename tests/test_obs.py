"""Observability stack: MetricsRegistry, FlightRecorder, Perfetto export.

Load-bearing properties:
  1. determinism -- two same-seed device burns with the recorder on emit
     byte-identical event streams (sim-time timestamps, wall durs off);
  2. histogram fidelity -- log2-bucket percentile estimates land within a
     factor of two of exact numpy percentiles by construction;
  3. export schema -- the emitted document is well-formed Chrome
     trace_event JSON (metadata rows, int tids, monotone per-track ts,
     async spans carrying cat + id/id2) and the CLI summarizer reads it;
  4. registry-backed attributes -- the legacy counter reads on the
     resolver are views over registry cells (one source of truth);
  5. the jit guard -- a recorder call reached under jax tracing fails
     loudly; a DISABLED recorder stays inert everywhere, including jit.
"""
from __future__ import annotations

import json

import numpy as np
import pytest

from accord_tpu.obs import export
from accord_tpu.obs.metrics import (GLOSSARY, CounterDict, Histogram,
                                    MetricsRegistry)
from accord_tpu.obs.trace import REC, FlightRecorder


@pytest.fixture(autouse=True)
def _recorder_reset():
    """Every test leaves the process-global recorder disabled and empty."""
    yield
    REC.enabled = False
    REC.wall = False
    REC.clear()


# -- recorded burn fixture ----------------------------------------------------

def _record_burn(seed: int = 7, ops: int = 40):
    from accord_tpu.ops.resolver import BatchDepsResolver
    from accord_tpu.sim.burn import run_burn
    from accord_tpu.sim.cluster import ClusterConfig

    resolvers = []

    def factory():
        r = BatchDepsResolver(num_buckets=128, initial_cap=128,
                              max_dispatch=64)
        resolvers.append(r)
        return r

    cfg = ClusterConfig(num_nodes=3, rf=3, deps_resolver_factory=factory,
                        deps_batch_window_ms=4.0, device_latency_ms=10.0)
    REC.clear()
    REC.configure(capacity=1 << 16, wall=False)
    REC.enabled = True
    try:
        report = run_burn(seed, ops=ops, key_count=8, zipf_theta=0.99,
                          max_keys_per_txn=3, concurrency=8,
                          write_ratio=0.7, config=cfg)
    finally:
        REC.enabled = False
    events = REC.events()
    dropped = REC.dropped
    REC.clear()
    assert report.acked + report.failed == ops
    return report, events, dropped, resolvers


@pytest.fixture(scope="module")
def recorded():
    return _record_burn(), _record_burn()


def test_same_seed_traces_byte_identical(recorded):
    (_, e1, d1, _), (_, e2, d2, _) = recorded
    assert d1 == 0 and d2 == 0, "ring overflowed; capacity too small"
    assert len(e1) > 500, "trace suspiciously small for a 40-op burn"
    assert json.dumps(e1, sort_keys=True) == json.dumps(e2, sort_keys=True)


def test_trace_vocabulary_present(recorded):
    _, events, _, _ = recorded[0]
    names = {ev["name"] for ev in events}
    # txn lifecycle, device pipeline, sim network: all tracks populated
    for expect in ("coordinate", "preaccepted", "accepted", "stable",
                   "applied", "dispatch", "window", "stage_host",
                   "preaccept", "encode", "decode", "send", "deliver"):
        assert expect in names, f"no {expect!r} events recorded"


def test_txn_spans_balance(recorded):
    report, events, _, _ = recorded[0]
    begins = sum(1 for e in events
                 if e.get("ph") == "b" and e.get("cat") == "txn")
    ends = sum(1 for e in events
               if e.get("ph") == "e" and e.get("cat") == "txn")
    assert begins == report.acked + report.failed
    assert ends == begins, "coordinations left open at burn end"


def test_registry_latency_histograms(recorded):
    report, _, _, _ = recorded[0]
    snap = report.registry.snapshot()
    for name in ("txn.commit_latency_us", "txn.apply_latency_us"):
        h = snap[name]
        assert h["count"] == report.acked
        assert 0 < h["p50"] <= h["p95"] <= h["p99"] <= h["max"]
    assert snap["txn.started"] >= report.acked


def test_resolver_snapshot_is_registry_backed(recorded):
    _, _, _, resolvers = recorded[0]
    assert resolvers, "device factory never ran"
    for r in resolvers:
        snap = r.snapshot()
        # the legacy attribute reads are descriptor views over the same
        # registry cells the snapshot serializes
        assert snap["resolver.dispatches"] == r.dispatches
        assert snap["resolver.subjects"] == r.subjects
        assert snap["resolver.host_hidden_s"] == r.host_hidden_s
        assert snap["resolver.upload_bytes"] == r.upload_bytes
        # and nothing escapes the documented vocabulary
        unknown = set(snap) - set(GLOSSARY)
        assert not unknown, f"undocumented metrics: {sorted(unknown)}"


# -- export schema ------------------------------------------------------------

def test_export_schema(recorded):
    _, events, _, _ = recorded[0]
    doc = export.to_chrome_trace(events)
    assert doc["displayTimeUnit"] == "ms"
    evs = doc["traceEvents"]
    pids = {e["pid"] for e in evs}
    named = {e["pid"] for e in evs if e.get("ph") == "M"
             and e["name"] == "process_name"}
    assert named == pids, "every node process must be named"
    last_ts: dict = {}
    for e in evs:
        assert isinstance(e["tid"], int), "string tids must be numbered"
        if e.get("ph") == "M":
            continue
        key = (e["pid"], e["tid"])
        assert e["ts"] >= last_ts.get(key, 0), "per-track ts not monotone"
        last_ts[key] = e["ts"]
        if e["ph"] == "X":
            assert "dur" in e
        elif e["ph"] in ("b", "e"):
            assert "cat" in e and ("id" in e or "id2" in e)
        elif e["ph"] == "i":
            assert e["s"] == "t"
        elif e["ph"] == "f":
            assert e["bp"] == "e"


def test_export_summarize_and_cli(tmp_path, capsys, recorded):
    _, events, _, _ = recorded[0]
    path = tmp_path / "trace.json"
    doc = export.write_trace(str(path), events)
    summary = export.summarize(doc)
    # every device window closes (harvest fired for every dispatch) and
    # every coordination closes (applied-quorum or failure)
    assert summary["unclosed_async"] == 0
    assert summary["spans"]["window"]["count"] > 0
    assert summary["instants"]["send"] == summary["instants"]["deliver"]
    assert export.main(["--summarize", str(path)]) == 0
    out = capsys.readouterr().out
    assert "window" in out and "coordinate" in out


# -- histogram fidelity -------------------------------------------------------

def test_histogram_percentiles_vs_numpy():
    rng = np.random.default_rng(0)
    samples = rng.lognormal(mean=3.0, sigma=1.5, size=5000)
    h = Histogram("t")
    for v in samples:
        h.observe(float(v))
    assert h.count == 5000
    assert h.mean == pytest.approx(float(np.mean(samples)))
    for p in (50, 95, 99):
        exact = float(np.percentile(samples, p))
        est = h.percentile(p)
        assert exact / 2 <= est <= exact * 2, \
            f"p{p}: est {est} vs exact {exact}"
    assert h.percentile(100) == h.max


def test_histogram_zeros_and_merge():
    a = Histogram("a")
    for v in (0.0, 0.0, 5.0, 9.0):
        a.observe(v)
    assert a.percentile(25) == 0.0
    b = Histogram("b")
    for v in (100.0, 200.0):
        b.observe(v)
    a.merge_from(b)
    assert a.count == 6
    assert a.max == 200.0
    assert a.percentile(99) <= 200.0


# -- registry -----------------------------------------------------------------

def test_registry_kind_mismatch_raises():
    reg = MetricsRegistry()
    reg.counter("x").inc()
    with pytest.raises(TypeError):
        reg.timer("x")


def test_registry_merge():
    a, b = MetricsRegistry(), MetricsRegistry()
    a.counter("c").inc(2)
    b.counter("c").inc(3)
    a.timer("t").add(0.5)
    b.timer("t").add(0.25)
    b.gauge("g").set(7.0)
    a.merge_from(b)
    assert a.counter("c").value == 5
    assert a.timer("t").total == pytest.approx(0.75)
    assert a.gauge("g").value == 7.0


def test_counterdict_view():
    reg = MetricsRegistry()
    d = CounterDict(reg, "up", ("full", "ts"))
    d["full"] += 10
    d["ts"] = 3
    assert d == {"full": 10, "ts": 3}
    assert reg.counter("up.full").value == 10
    assert sorted(d) == ["full", "ts"]
    assert d.get("missing", 42) == 42


def test_descriptors_write_through():
    from accord_tpu.ops.resolver import BatchDepsResolver
    r = BatchDepsResolver(num_buckets=128, initial_cap=128)
    r.dispatches += 3
    r.preaccept_s += 0.5
    assert r.metrics.counter("resolver.dispatches").value == 3
    assert r.dispatches == 3
    assert r.metrics.timer("resolver.preaccept_s").total == \
        pytest.approx(0.5)


# -- recorder mechanics -------------------------------------------------------

def test_ring_bounded_and_disabled_noop():
    rec = FlightRecorder(capacity=16)
    for i in range(100):
        rec.instant(0, "t", "x", i)
    assert len(rec) == 0, "disabled recorder must not record"
    rec.enabled = True
    for i in range(100):
        rec.instant(0, "t", "x", i)
    assert len(rec) == 16
    assert rec.dropped == 84
    assert rec.events()[0]["ts"] == 84  # oldest dropped
    rec.clear()
    assert len(rec) == 0 and rec.dropped == 0


def test_wall_flag_gates_durations():
    rec = FlightRecorder()
    rec.enabled = True
    rec.complete(0, "t", "x", 10, dur=5.5)
    assert rec.events()[0]["dur"] == 0, "wall off: dur must stay 0"
    rec.configure(wall=True)
    rec.complete(0, "t", "x", 20, dur=5.5)
    assert rec.events()[1]["dur"] == 5.5


def test_recorder_rejects_jit_traced_calls():
    import jax
    import jax.numpy as jnp

    REC.configure(capacity=256)
    REC.enabled = True

    @jax.jit
    def bad(x):
        REC.instant(0, "t", "inside-jit", 0)
        return x + 1

    with pytest.raises(RuntimeError, match="jax tracing"):
        bad(jnp.int32(1))

    # disabled, the same call is a no-op even under tracing
    REC.enabled = False
    REC.clear()

    @jax.jit
    def fine(x):
        REC.instant(0, "t", "inside-jit", 0)
        return x * 2

    assert int(fine(jnp.int32(2))) == 4
    assert len(REC) == 0


# -- node / maelstrom integration ---------------------------------------------

def test_node_shutdown_emits_snapshot():
    from accord_tpu.maelstrom.runner import Runner

    r = Runner(seed=3)
    stats = r.run_random_workload(ops=12)
    assert stats["txn_ok"] > 0 and stats["errors"] == 0
    assert stats["txn_ok"] == r.metrics.counter("maelstrom.txn_ok").value
    r.shutdown()
    lines = [ln for ln in getattr(r, "log_lines", [])
             if ln.startswith("metrics shutdown ")]
    assert len(lines) == len(r.nodes), "every node emits a final snapshot"
    started = 0
    for ln in lines:
        snap = json.loads(ln.split(" ", 3)[3])
        assert snap, "empty metrics snapshot"
        started += snap.get("txn.started", 0)
    assert started >= stats["txn_ok"], \
        "coordinations started across nodes must cover every acked txn"


def test_readme_documents_every_metric():
    with open("README.md") as f:
        readme = f.read()
    missing = [name for name in GLOSSARY if name not in readme]
    assert not missing, f"README glossary missing: {missing}"
