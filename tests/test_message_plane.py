"""Device message plane tests: the ticketed batched drain + device mailbox
routing (sim/network.DeviceMessageNetwork + ops/mailbox.py) against the
per-message host event baseline.

The contract under test is EXACT equivalence, not statistical agreement:
both modes consume the same rng draws and queue sequence numbers at the
same call sites, so a burn's committed event log must be bit-identical
with `device_messages=True` -- including under chaos (drops, partitions,
crash/restart) and range traffic. The fast subset here rides tier 1.
"""
from __future__ import annotations

import numpy as np
import pytest

from accord_tpu.ops.mailbox import MailboxPlane, pack_words, unpack_words
from accord_tpu.sim.burn import run_burn
from accord_tpu.sim.mesh_burn import run_mesh_burn
from accord_tpu.sim.network import (DeviceMessageNetwork, LinkConfig,
                                    LinkMatrix, SimNetwork)
from accord_tpu.sim.queue import PendingQueue
from accord_tpu.utils.rng import RandomSource

pytestmark = pytest.mark.message_plane


# -- queue ticket primitives --------------------------------------------------

def test_queue_tickets_share_event_sequence():
    """ticket() consumes the same counter add() stamps onto events, so a
    ticketed message occupies exactly the heap position the baseline's
    deliver event would have."""
    q = PendingQueue()
    fired = []
    q.add(10, lambda: fired.append("a"))    # seq 0
    t = q.ticket()                          # seq 1 -- the parked message
    q.add(10, lambda: fired.append("c"))    # seq 2
    q.add_ticketed_at(q.now_micros + 10, t, lambda: fired.append("b"))
    q.drain()
    assert fired == ["a", "b", "c"]


def test_queue_peek_skips_cancelled_heads():
    q = PendingQueue()
    t0 = q.now_micros
    h = q.add(5, lambda: None)
    q.add(9, lambda: None)
    assert q.peek() == (t0 + 5, 0)
    h.cancel()
    assert q.peek() == (t0 + 9, 1)
    q.drain()
    assert q.peek() is None


# -- payload packing ----------------------------------------------------------

def test_pack_unpack_roundtrip():
    for n in (0, 1, 3, 4, 5, 63, 64, 251, 252):
        payload = bytes(range(256))[:n] * 1
        w = pack_words(payload, 64)
        assert w is not None and w.shape == (64,)
        assert unpack_words(w) == payload
    # exactly full: 4*(width-1) bytes fit, one more spills
    assert pack_words(b"x" * 252, 64) is not None
    assert pack_words(b"x" * 253, 64) is None


# -- link matrix --------------------------------------------------------------

def test_link_matrix_regional_asymmetry():
    """Eastward cross-region links are scaled slower than their westward
    twins; intra-region links are symmetric."""
    m = LinkMatrix.regional(12, regions=3, asymmetry=0.5)
    east = m.config(1, 12)   # region 0 -> region 2
    west = m.config(12, 1)   # region 2 -> region 0
    assert east.min_latency_us > west.min_latency_us
    assert east.max_latency_us > west.max_latency_us
    a, b = m.config(1, 2), m.config(2, 1)  # same region
    assert (a.min_latency_us, a.max_latency_us) == \
        (b.min_latency_us, b.max_latency_us)


def test_link_matrix_latency_draws_within_bounds():
    """A network seeded from a LinkMatrix draws every latency inside that
    directed link's [min, max] band -- the same dict feeds both modes."""
    m = LinkMatrix(4)
    m.set(1, 2, LinkConfig(100, 200))
    m.set(2, 1, LinkConfig(5_000, 9_000))
    net = SimNetwork(PendingQueue(), RandomSource(3), link_matrix=m)
    for _ in range(50):
        assert 100 <= net._latency(1, 2) <= 200
        assert 5_000 <= net._latency(2, 1) <= 9_000


# -- unit-level network behaviour --------------------------------------------

class _StubNode:
    def __init__(self, nid):
        self.id = nid
        self.got = []

    def receive(self, msg, src, ctx):
        self.got.append((msg, src))


def _pair(net_cls, seed=7, **kw):
    q = PendingQueue()
    net = net_cls(q, RandomSource(seed), serialize=False, **kw)
    a, b = _StubNode(1), _StubNode(2)
    net.register_node(a)
    net.register_node(b)
    return q, net, a, b


def test_device_network_delivery_order_matches_host():
    """Same seed, same sends: the batched ticketed drain delivers in the
    baseline's exact order and the stats dicts agree."""
    results = []
    for cls in (SimNetwork, DeviceMessageNetwork):
        q, net, a, b = _pair(cls)
        for i in range(40):
            net.send_request(1 if i % 3 else 2, 2 if i % 3 else 1, i, None)
        q.drain()
        results.append((list(b.got), list(a.got), dict(net.stats)))
    assert results[0] == results[1]


def test_drop_accounting_matches_host():
    for cls in (SimNetwork, DeviceMessageNetwork):
        q, net, a, b = _pair(cls)
        net.set_link(1, 2, LinkConfig(100, 200, drop_probability=1.0))
        for i in range(10):
            net.send_request(1, 2, i, None)
            net.send_request(2, 1, i, None)
        q.drain()
        assert net.stats["dropped"] == 10
        assert net.stats["delivered"] == 10
        assert b.got == []
        assert len(a.got) == 10


def test_partition_symmetry():
    """set_partitioned cuts BOTH directions of the pair, and healing
    restores them; the device twin behaves identically."""
    for cls in (SimNetwork, DeviceMessageNetwork):
        q, net, a, b = _pair(cls)
        net.set_partitioned(1, 2, True)
        net.send_request(1, 2, "x", None)
        net.send_request(2, 1, "y", None)
        q.drain()
        assert a.got == [] and b.got == []
        net.set_partitioned(1, 2, False)
        net.send_request(1, 2, "x", None)
        net.send_request(2, 1, "y", None)
        q.drain()
        assert len(a.got) == 1 and len(b.got) == 1


def test_mailbox_partition_mask_symmetric():
    plane = MailboxPlane(4, depth=4, words=8)
    plane.set_partitions({frozenset((1, 3))}, version=1)
    part = np.asarray(plane.part)
    assert bool(part[1, 3]) and bool(part[3, 1])
    assert not part[1, 2] and not part[2, 4]
    assert plane.counters()["mailbox_partition_epochs"] == 1


# -- burn differentials (engine-less batched drain) ---------------------------

def test_burn_differential_batched_drain():
    """Host vs device-messages burn (no tick engine attached): identical
    committed logs, and the drain collapsed many deliveries per host
    callback."""
    kw = dict(ops=60, nodes=3, concurrency=4, collect_log=True)
    host = run_burn(7, **kw)
    dev = run_burn(7, device_messages=True, **kw)
    assert host.log == dev.log
    assert dev.counters["message_plane_batches"] > 0
    assert dev.counters["messages_per_host_callback"] > 2.0
    assert dev.counters["mailbox_verify_fallbacks"] == 0
    assert "message_plane_batches" not in host.counters


def test_burn_differential_range_traffic_and_chaos():
    """Range reads/writes + drop chaos + partitions: the rng streams stay
    aligned, so the histories match bit for bit."""
    kw = dict(ops=50, nodes=3, concurrency=4, collect_log=True,
              chaos_drop=0.08, chaos_partitions=True,
              range_read_ratio=0.2, range_write_ratio=0.1)
    host = run_burn(13, **kw)
    dev = run_burn(13, device_messages=True, **kw)
    assert host.log == dev.log


def test_burn_device_messages_reconcile():
    """Device-messages mode reconciles with itself: same seed twice gives
    the same log (the --reconcile CLI contract)."""
    kw = dict(ops=50, nodes=3, concurrency=4, collect_log=True,
              device_messages=True)
    assert run_burn(19, **kw).log == run_burn(19, **kw).log


# -- fused mailbox routing (tick engine attached) -----------------------------

def test_megakernel_device_messages_differential():
    """The full tentpole path: payload bytes ride the mailbox arena inside
    the single fused protocol_tick launch, every delivery verifies against
    the staged host bytes, and the committed history is bit-identical to
    the host-message megakernel run."""
    kw = dict(ops=40, nodes=3, megakernel=True, collect_log=True)
    host, _ = run_mesh_burn(5, **kw)
    dev, eng = run_mesh_burn(5, device_messages=True, **kw)
    assert host.log == dev.log
    c = dev.counters
    assert c["device_messages_delivered"] > 0
    assert c["mailbox_verify_fallbacks"] == 0
    assert c["mailbox_overflow_spills"] == 0
    assert c["launches_per_tick"] == 1.0
    assert c["messages_per_host_callback"] > 2.0


@pytest.mark.slow
def test_megakernel_device_messages_chaos_seeds():
    """Chaos legs at 4 nodes: drops + partitions + crash/restart, two
    seeds. Device mailbox routing must not disturb any rng stream."""
    kw = dict(ops=70, nodes=4, megakernel=True, collect_log=True,
              chaos_drop=0.05, chaos_partitions=True, crash_restart=True)
    for seed in (23, 31):
        host, _ = run_mesh_burn(seed, **kw)
        dev, _ = run_mesh_burn(seed, device_messages=True, **kw)
        assert host.log == dev.log, f"chaos diverged at seed {seed}"
        assert dev.counters["mailbox_verify_fallbacks"] == 0


@pytest.mark.slow
def test_cmd_defer_retired_rides_fused_program():
    """Satellite 1: with the command plane on, host-twinned PreAccept
    deferrals are folded back through the fused program's repair stage and
    counted retired -- without disturbing the committed history."""
    kw = dict(ops=60, nodes=3, megakernel=True, cmd_plane=True,
              collect_log=True)
    host, _ = run_mesh_burn(9, **kw)
    dev, _ = run_mesh_burn(9, device_messages=True, **kw)
    assert host.log == dev.log
    assert dev.counters.get("cmd_defer_retired", 0) > 0


@pytest.mark.slow
def test_seeded_mailbox_corruption_caught_by_verify():
    """Seeded device-routing bit flips (fault_plane mailbox_rate): every
    injection is caught by the verify-against-staged-bytes contract and
    falls back to the host copy, so the chaos history still matches the
    fault-free host run bit for bit."""
    kw = dict(ops=40, nodes=3, megakernel=True, collect_log=True)
    host, _ = run_mesh_burn(5, **kw)
    dev, _ = run_mesh_burn(5, device_messages=True, device_chaos=True,
                           device_fault_rates={"mailbox_rate": 0.25}, **kw)
    assert host.log == dev.log
    injected = dev.device_faults["mailbox"]
    assert injected > 0, "mailbox fault rate 0.25 never drew"
    assert dev.counters["mailbox_verify_fallbacks"] == injected
    assert dev.counters["device_messages_delivered"] > 0


@pytest.mark.slow
def test_tiny_mailbox_overflow_degrades_gracefully():
    """Satellite 6: a mailbox far too small for the traffic spills to the
    host path (counted), and the history still matches the host run."""
    kw = dict(ops=40, nodes=3, megakernel=True, collect_log=True)
    host, _ = run_mesh_burn(5, **kw)
    dev, _ = run_mesh_burn(5, device_messages=True,
                           mailbox_depth=2, mailbox_words=16, **kw)
    assert host.log == dev.log
    assert dev.counters["mailbox_overflow_spills"] > 0


@pytest.mark.slow
def test_regional_link_matrix_both_paths():
    """The 3-region asymmetric matrix runs bit-identically through the
    host event queue and the device plane (the bench parity leg)."""
    m = LinkMatrix.regional(6, regions=3)
    kw = dict(ops=50, nodes=6, megakernel=True, collect_log=True,
              link_matrix=m)
    host, _ = run_mesh_burn(17, **kw)
    dev, _ = run_mesh_burn(17, device_messages=True, **kw)
    assert host.log == dev.log
