"""Home-shard progress ownership + Inform gossip.

Mirrors the reference's ProgressShard Home/NonHome split and the
InformOfTxnId / InformDurable / InformHomeDurable messages
(api/ProgressLog.java:59, messages/InformOfTxnId.java:29,
coordinate/Persist.java:88): the home shard owns each txn's liveness, a
non-home witness of an orphaned (undecided) txn informs the home shard
instead of racing its own recovery, and the persist path broadcasts
majority-durability.
"""
import pytest

from accord_tpu.local.status import Durability, Status
from accord_tpu.messages import PreAccept
from accord_tpu.messages.base import Callback
from accord_tpu.primitives.keyspace import Keys
from accord_tpu.primitives.timestamp import TxnKind
from accord_tpu.primitives.txn import Txn
from accord_tpu.sim.burn import run_burn
from accord_tpu.sim.cluster import Cluster, ClusterConfig
from accord_tpu.sim.list_store import ListQuery, ListRead, ListUpdate


class _Sink(Callback):
    def __init__(self):
        self.replies = []

    def on_success(self, from_node, reply):
        self.replies.append((from_node, reply))

    def on_failure(self, from_node, failure):
        pass


def _write_txn(keys, value):
    return Txn(TxnKind.WRITE, keys, read=ListRead(keys),
               update=ListUpdate(keys, value), query=ListQuery())


def _find_cmd(cluster, node_id, txn_id):
    for store in cluster.node(node_id).command_stores.all():
        cmd = store.command_if_present(txn_id)
        if cmd is not None:
            return cmd
    return None


def _orphan_preaccept(cluster):
    """Witness a txn ONLY at node 4 (a non-home participant replica) -- the
    coordinator 'dies' after one PreAccept. Topology (5 nodes, rf 3,
    4 shards, round-robin): key 100 -> shard0 {1,2,3} (home), key 50000 ->
    shard3 {4,5,1}. Node 4 replicates only the non-home shard."""
    n1 = cluster.node(1)
    keys = Keys([100, 50000])
    txn = _write_txn(keys, 77)
    txn_id = n1.next_txn_id(txn.kind, txn.domain)
    route = n1.compute_route(txn)
    assert route.home_key == 100
    home_nodes = set(
        cluster.current_topology().shard_for_key(100).nodes)
    assert home_nodes == {1, 2, 3}
    assert set(cluster.current_topology().shard_for_key(50000).nodes) \
        == {4, 5, 1}
    sink = _Sink()
    n1.send(4, PreAccept(txn_id, txn, route), sink)
    return txn_id


def _gossip_config(**kw):
    return ClusterConfig(num_nodes=5, rf=3, **kw)


def test_orphaned_preaccept_rescued_via_inform_of_txn():
    """The orphaned-preaccept net: node 4 (non-home) defers, informs the home
    shard, and the HOME shard drives the txn to a terminal state; node 4
    itself never has to probe."""
    cl = Cluster(11, _gossip_config())
    txn_id = _orphan_preaccept(cl)
    cl.drain()
    cl.check_no_failures()

    # the txn was driven to a terminal decision (recovered or invalidated --
    # with only 1 of 5 witnesses and no definition on the home shard, it
    # must be invalidated)
    cmd = _find_cmd(cl, 4, txn_id)
    assert cmd is not None and cmd.status.is_terminal, \
        f"orphaned txn not resolved: {cmd and cmd.status}"

    # gossip happened: node 4 informed; a home replica drove the decision
    assert cl.node(4).counters["informs_of_txn_sent"] >= 1
    assert cl.node(4).counters["progress_probes"] == 0, \
        "non-home replica probed despite home ownership"
    home_probes = sum(cl.node(n).counters["progress_probes"] for n in (1, 2, 3))
    assert home_probes >= 1


def test_orphaned_preaccept_resolves_without_gossip_too():
    """Liveness does not DEPEND on the gossip: with inform disabled the
    non-home replica escalates to its own probe (more probes, same
    outcome)."""
    cl = Cluster(11, _gossip_config(progress_home_defer=1.0,
                                    progress_inform_home=False))
    txn_id = _orphan_preaccept(cl)
    cl.drain()
    cl.check_no_failures()
    cmd = _find_cmd(cl, 4, txn_id)
    assert cmd is not None and cmd.status.is_terminal
    assert cl.node(4).counters["progress_probes"] >= 1
    assert cl.node(4).counters["informs_of_txn_sent"] == 0


def test_persist_broadcasts_inform_durable():
    """A normally-coordinated txn ends with every replica knowing the outcome
    is majority-durable (reference: Persist.java:88)."""
    cl = Cluster(5, ClusterConfig(num_nodes=3, rf=3))
    n1 = cl.node(1)
    keys = Keys([300, 20000])
    res = n1.coordinate(_write_txn(keys, 5))
    cl.drain()
    assert res.done and res.failure is None

    assert n1.counters["informs_durable_sent"] >= 3
    txn_id = None
    for store in n1.command_stores.all():
        for tid, cmd in store.commands.items():
            if cmd.status == Status.APPLIED:
                txn_id = tid
    assert txn_id is not None
    for nid in (1, 2, 3):
        cmd = _find_cmd(cl, nid, txn_id)
        assert cmd is not None
        assert cmd.durability >= Durability.MAJORITY, \
            f"node {nid} never learned durability: {cmd.durability.name}"


def _strand_multi_witness_orphans(cluster, count):
    """Strand `count` txns witnessed at ALL FIVE non-home participant
    replicas (8 nodes, rf 3, 6 shards: key 100 -> home shard {1,2,3}; keys
    35000/45000/60000 -> shards {4,5,6}/{5,6,7}/{6,7,8}): the coordinator
    dies after PreAccept reached every non-home shard but no home replica."""
    n1 = cluster.node(1)
    ids = []
    for i in range(count):
        keys = Keys([100 + i, 35000 + i, 45000 + i, 60000 + i])
        txn = _write_txn(keys, 1000 + i)
        txn_id = n1.next_txn_id(txn.kind, txn.domain)
        route = n1.compute_route(txn)
        sink = _Sink()
        for to in (4, 5, 6, 7, 8):
            n1.send(to, PreAccept(txn_id, txn, route), sink)
        ids.append(txn_id)
    return ids


def _run_orphan_probe_count(config):
    cl = Cluster(21, config)
    topo = cl.current_topology()
    assert set(topo.shard_for_key(100).nodes) == {1, 2, 3}
    witnesses = set()
    for k in (35000, 45000, 60000):
        witnesses |= set(topo.shard_for_key(k).nodes)
    assert witnesses == {4, 5, 6, 7, 8}
    ids = _strand_multi_witness_orphans(cl, 6)
    cl.drain()
    cl.check_no_failures()
    for txn_id in ids:
        cmd = _find_cmd(cl, 4, txn_id)
        assert cmd is not None and cmd.status.is_terminal, \
            f"orphan {txn_id} unresolved: {cmd and cmd.status}"
    return cl.total_counters()


def test_multi_witness_orphans_gossip_dedupes_probes():
    """When every non-home participant shard witnessed a stranded undecided
    txn, naive per-replica liveness has all 5 witnesses race their own
    recovery probes; with home ownership + InformOfTxnId the 3-replica home
    shard dedupes the recovery (VERDICT r4 'done' criterion: probe count
    measurably drops, by event counters)."""
    cfg = ClusterConfig(num_nodes=8, rf=3, num_shards=6)
    with_gossip = _run_orphan_probe_count(cfg)
    without = _run_orphan_probe_count(ClusterConfig(
        num_nodes=8, rf=3, num_shards=6, progress_home_defer=1.0,
        progress_inform_home=False))
    assert with_gossip.get("informs_of_txn_sent", 0) >= 6
    probes_with = with_gossip.get("progress_probes", 0)
    probes_without = without.get("progress_probes", 0)
    assert probes_with < probes_without, (
        f"gossip did not reduce probes: {probes_with} vs {probes_without}")


def test_partition_crash_burn_green_with_gossip():
    """The full partition + coordinator-crash burn stays green with the
    home-shard gossip machinery on (its default)."""
    report = run_burn(3, ops=120, nodes=5, rf=3, key_count=24, concurrency=6,
                      chaos_partitions=True, chaos_drop=0.05,
                      crash_restart=True, config=_gossip_config())
    assert report.acked + report.failed == 120 and report.lost == 0
