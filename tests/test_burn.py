"""End-to-end simulated-cluster tests (the reference's burn-test strategy,
SURVEY.md section 4.1, scaled down for CI)."""
import pytest

from accord_tpu.api import EventsListener
from accord_tpu.sim.burn import run_burn
from accord_tpu.sim.cluster import Cluster, ClusterConfig
from accord_tpu.primitives.keyspace import Keys
from accord_tpu.primitives.timestamp import TxnKind
from accord_tpu.primitives.txn import Txn
from accord_tpu.sim.list_store import ListQuery, ListRead, ListUpdate


def test_burn_small():
    r = run_burn(seed=42, ops=100)
    assert r.acked == 100 and r.failed == 0 and r.lost == 0


def test_burn_five_nodes():
    r = run_burn(seed=7, ops=100, nodes=5, rf=3)
    assert r.acked == 100 and r.failed == 0 and r.lost == 0


def test_burn_single_hot_key_contention():
    # maximum contention: all txns hit one key -> exercises the slow path
    r = run_burn(seed=3, ops=80, key_count=1, concurrency=12)
    assert r.acked == 80 and r.failed == 0 and r.lost == 0


def test_burn_determinism():
    a = run_burn(seed=99, ops=60, collect_log=True)
    b = run_burn(seed=99, ops=60, collect_log=True)
    assert a.log == b.log and len(a.log) == 60


class _PathCounter(EventsListener):
    def __init__(self):
        self.fast = 0
        self.slow = 0

    def on_fast_path_taken(self, txn_id):
        self.fast += 1

    def on_slow_path_taken(self, txn_id):
        self.slow += 1


def _run_counted(seed, n_txns, same_key: bool):
    cluster = Cluster(seed, ClusterConfig())
    counter = _PathCounter()
    for node in cluster.nodes.values():
        node.events = counter
    results = []
    for i in range(n_txns):
        key = 5 if same_key else 100 + i * 50
        txn = Txn(TxnKind.WRITE, Keys.of(key), read=ListRead(Keys.of(key)),
                  update=ListUpdate(Keys.of(key), i + 1), query=ListQuery())
        node = cluster.nodes[1 + i % len(cluster.nodes)]
        cluster.queue.add(i * 100, lambda n=node, t=txn: results.append(n.coordinate(t)))
    cluster.drain()
    cluster.check_no_failures()
    assert all(r.success for r in results)
    return counter


def test_uncontended_takes_fast_path():
    c = _run_counted(1, 10, same_key=False)
    assert c.fast == 10 and c.slow == 0


def test_contended_exercises_slow_path():
    # near-simultaneous same-key txns from different coordinators cannot all
    # witness themselves first -> some must take the slow path
    c = _run_counted(2, 10, same_key=True)
    assert c.fast + c.slow == 10
    assert c.slow > 0
