"""Device-resident range deps: the interval arena + CSR subject encoding
must answer EXACTLY what the host scans answer, for every mix of key/range
subjects against key/range conflict state -- including subject rows wider
than the retired MAXK=16 scatter, truncation/prune of range txns, and the
range arena compacting while calls are in flight. The fallback counters
(host_fallbacks, range_fallbacks) must stay zero throughout: any nonzero
means the device path silently left the kernel."""
from __future__ import annotations

import numpy as np

from accord_tpu.local.cfk import CfkStatus
from accord_tpu.ops.resolver import BatchDepsResolver
from accord_tpu.primitives.keyspace import Keys, Range, Ranges
from accord_tpu.primitives.timestamp import Domain, Timestamp, TxnId, TxnKind
from tests.test_local_engine import setup_store

DOMAIN = 1 << 16


def _register_mixed(store, node, rng, n_key=60, n_range=40):
    """Key txns (some wider than the old MAXK=16) + range txns, registered
    through the store funnel so the attached resolver mirrors them."""
    tss = []
    rids = []
    for i in range(n_key):
        ts = node.unique_now()
        kind = TxnKind.WRITE if i % 3 else TxnKind.READ
        tid = TxnId.create(ts.epoch, ts.hlc, ts.node, kind, Domain.KEY)
        width = 40 if i % 11 == 0 else 1 + int(rng.integers(0, 4))
        keys = Keys(sorted({int(k) for k in rng.integers(0, DOMAIN, width)}))
        store.register(tid, keys, CfkStatus.WITNESSED, ts)
        tss.append(ts)
    for i in range(n_range):
        ts = node.unique_now()
        kind = TxnKind.WRITE if i % 2 else TxnKind.READ
        tid = TxnId.create(ts.epoch, ts.hlc, ts.node, kind, Domain.RANGE)
        pieces = []
        for _ in range(1 + int(rng.integers(0, 2))):
            s = int(rng.integers(0, DOMAIN - 64))
            pieces.append(Range(s, s + 1 + int(rng.integers(0, 2048))))
        store.register(tid, Ranges(pieces), CfkStatus.WITNESSED, ts)
        rids.append(tid)
        tss.append(ts)
    return rids, tss


def _subjects(store, node, rng, tss, n=40):
    far = Timestamp(node.epoch, node.time_service.now_micros() + 50_000,
                    0, node.id)
    subs = []
    for i in range(n):
        kind = TxnKind.WRITE if i % 2 else TxnKind.READ
        if i % 3 == 0:
            pieces = [Range(s, s + 1 + int(rng.integers(0, 4096)))
                      for s in (int(rng.integers(0, DOMAIN - 64)),)]
            if i % 6 == 0:
                s2 = int(rng.integers(0, DOMAIN - 64))
                pieces.append(Range(s2, s2 + 1 + int(rng.integers(0, 512))))
            owned = store.owned(Ranges(pieces))
            tid = node.next_txn_id(kind, Domain.RANGE)
        else:
            width = 24 if i % 9 == 0 else 1 + int(rng.integers(0, 4))
            owned = store.owned(Keys(sorted(
                {int(k) for k in rng.integers(0, DOMAIN, width)})))
            tid = node.next_txn_id(kind, Domain.KEY)
        # mixed bounds: mostly future (sees everything), sometimes a
        # registered txn's timestamp (exercises the lex-before mask)
        before = far if i % 4 else tss[int(rng.integers(0, len(tss)))]
        subs.append((tid, owned, before))
    return subs


def _assert_counters_zero(resolver):
    assert resolver.host_fallbacks == 0
    assert resolver.range_fallbacks == 0


def test_randomized_mixed_differential():
    rng = np.random.default_rng(42)
    _, node, store = setup_store()
    resolver = BatchDepsResolver(num_buckets=128, initial_cap=128)
    store.deps_resolver = resolver   # registrations funnel via on_register
    _, tss = _register_mixed(store, node, rng)

    arena = resolver._arenas[id(store)]
    # the population really exercised the retired limits: a row wider than
    # the old MAXK scatter, and a grown interval arena
    assert max(len(m) for m in arena.row_mods if m is not None) > 16
    assert arena.ranges.count > 0

    key_deps_seen = range_deps_seen = 0
    for tid, owned, before in _subjects(store, node, rng, tss):
        host = store.host_calculate_deps(tid, owned, before)
        dev = resolver.resolve_one(store, tid, owned, before)
        assert dev == host, f"subject {tid} ({type(owned).__name__})"
        key_deps_seen += bool(host.key_deps.all_txn_ids())
        range_deps_seen += bool(host.range_deps.all_txn_ids())
    assert key_deps_seen > 0 and range_deps_seen > 0, "differential vacuous"
    _assert_counters_zero(resolver)


def test_range_truncation_and_prune():
    """Mirror store._deregister for half the range txns (range_txns/
    range_index popped, then the resolver's on_truncate hook); the arena
    must drop their rows and the differential must keep holding."""
    rng = np.random.default_rng(7)
    _, node, store = setup_store()
    resolver = BatchDepsResolver(num_buckets=128, initial_cap=128)
    store.deps_resolver = resolver
    rids, tss = _register_mixed(store, node, rng, n_key=30, n_range=30)

    arena = resolver._arenas[id(store)]
    for tid in rids[::2]:
        store.range_txns.pop(tid, None)
        store.range_index.remove(tid)
        resolver.on_truncate(store, tid)
        assert tid not in arena.ranges.rows_of
    for tid in rids[1::2]:
        assert tid in arena.ranges.rows_of

    nonempty = 0
    for tid, owned, before in _subjects(store, node, rng, tss, n=24):
        host = store.host_calculate_deps(tid, owned, before)
        dev = resolver.resolve_one(store, tid, owned, before)
        assert dev == host, f"subject {tid} after truncation"
        nonempty += bool(host.range_deps.all_txn_ids())
    assert nonempty > 0
    # no surviving truncated id in any answer (paranoia: the re-filter at
    # decode is what makes freed-row reuse exact)
    truncated = set(rids[::2])
    for tid, owned, before in _subjects(store, node, rng, tss, n=8):
        dev = resolver.resolve_one(store, tid, owned, before)
        assert not (set(dev.range_deps.all_txn_ids()) & truncated)
        assert not (set(dev.key_deps.all_txn_ids()) & truncated)
    _assert_counters_zero(resolver)


def test_compaction_with_range_calls_in_flight():
    """Truncate + compact the INTERVAL arena while mixed-domain calls are in
    flight: the pinned id snapshot must translate the stale candidates (no
    host fallback) and every answer must equal the post-truncation host
    scan."""
    rng = np.random.default_rng(11)
    cluster, node, store = setup_store()
    resolver = BatchDepsResolver(num_buckets=128, initial_cap=128)
    store.deps_resolver = resolver
    store.batch_window_ms = 0.5
    node.device_latency_ms = 50.0
    node.device_poll_ms = 1.0
    rids, _ = _register_mixed(store, node, rng, n_key=30, n_range=40)

    arena = resolver._arenas[id(store)]
    far = Timestamp(node.epoch, node.time_service.now_micros() + 50_000,
                    0, node.id)
    subs = []
    for i in range(6):
        if i % 2 == 0:
            s = int(rng.integers(0, DOMAIN - 4096))
            owned = store.owned(Ranges([Range(s, s + 4096)]))
            tid = node.next_txn_id(TxnKind.WRITE, Domain.RANGE)
        else:
            owned = store.owned(Keys(sorted(
                {int(k) for k in rng.integers(0, DOMAIN, 3)})))
            tid = node.next_txn_id(TxnKind.WRITE, Domain.KEY)
        subs.append((tid, owned, far,
                     resolver.enqueue_deps(store, tid, owned, far)))

    while resolver.dispatches < 1:
        assert cluster.queue.process_one(), "tick never fired"
    assert all(not out.done for *_, out in subs)

    # truncate most range txns mid-flight, then compact the interval arena
    for tid in rids[:30]:
        store.range_txns.pop(tid, None)
        store.range_index.remove(tid)
        resolver.on_truncate(store, tid)
    rgen0 = arena.ranges.gen
    assert arena.ranges.compact(), "compaction should reclaim truncated rows"
    assert arena.ranges.gen == rgen0 + 1
    # the in-flight pin forced a row->txn snapshot of the retired mapping
    assert rgen0 in arena.ranges.retired_ids

    while not all(out.done for *_, out in subs):
        assert cluster.queue.process_one(), "harvest never fired"
    assert resolver.stale_harvests >= 1
    _assert_counters_zero(resolver)
    cluster.queue.drain(max_events=10_000)
    assert rgen0 not in arena.ranges.retired_ids  # pin released on harvest

    nonempty = 0
    for tid, owned, before, out in subs:
        host = store.host_calculate_deps(tid, owned, before)
        assert out.value() == host, f"subject {tid} across compaction"
        nonempty += bool(host.range_deps.all_txn_ids()
                         or host.key_deps.all_txn_ids())
    assert nonempty > 0, "differential vacuous"


def test_covered_bucket_contraction_vs_hull():
    """The covered-bucket contraction (which retired the [kmin, kmax]
    modular hull) must mark EXACTLY the buckets some interval key hashes
    into -- randomized CSR lists with padding rows and >=K-wide intervals
    -- and on sparse rows it must be strictly tighter than the hull span
    the old encoding would have marked."""
    import jax.numpy as jnp
    from accord_tpu.ops.kernels import covered_buckets

    K = 128
    rng = np.random.default_rng(97)
    partial_rows = 0
    for _ in range(10):
        b = 1 + int(rng.integers(0, 5))
        iv_of, iv_s, iv_e = [], [], []
        for subj in range(b):
            for _ in range(1 + int(rng.integers(0, 3))):
                s = int(rng.integers(0, 1 << 16))
                w = int(rng.integers(1, 2 * K + 8)) if rng.integers(0, 2) \
                    else int(rng.integers(1, 8))
                iv_of.append(subj)
                iv_s.append(s)
                iv_e.append(s + w)
        # CSR padding rows (iv_of == b): one degenerate, one nonempty --
        # both must be dropped, not smeared into row b-1 or wrapped
        iv_of += [b, b]
        iv_s += [0, 0]
        iv_e += [0, 5]
        got = np.asarray(covered_buckets(
            jnp.asarray(iv_of, jnp.int32), jnp.asarray(iv_s, jnp.int32),
            jnp.asarray(iv_e, jnp.int32), b, K, 0, K)
            .astype(jnp.float32)) > 0.5
        truth = np.zeros((b, K), bool)
        for o, s, e in zip(iv_of, iv_s, iv_e):
            if o >= b:
                continue
            if e - s >= K:
                truth[o, :] = True
            else:
                truth[o, np.arange(s, e) % K] = True
        assert (got == truth).all(), "contraction != hashed-bucket truth"
        partial_rows += int(((truth.sum(axis=1) > 0)
                             & (truth.sum(axis=1) < K)).sum())
    assert partial_rows > 0, "differential vacuous: every row was all-wide"

    # the case the hull could never win: two narrow intervals far apart in
    # ONE row. The retired hull marked every bucket between them; the
    # contraction marks exactly the four hashed buckets.
    got = np.asarray(covered_buckets(
        jnp.asarray([0, 0], jnp.int32), jnp.asarray([10, 2000], jnp.int32),
        jnp.asarray([12, 2002], jnp.int32), 1, K, 0, K)
        .astype(jnp.float32))[0] > 0.5
    marked = np.nonzero(got)[0]
    assert set(marked.tolist()) == {10, 11, 2000 % K, 2001 % K}
    hull_span = int(marked.max() - marked.min() + 1)
    assert len(marked) < hull_span, "contraction no tighter than the hull"

    # modular straddle: an interval crossing a multiple of K wraps its
    # covered buckets around the ring exactly
    got = np.asarray(covered_buckets(
        jnp.asarray([0], jnp.int32), jnp.asarray([K * 5 - 2], jnp.int32),
        jnp.asarray([K * 5 + 2], jnp.int32), 1, K, 0, K)
        .astype(jnp.float32))[0] > 0.5
    assert set(np.nonzero(got)[0].tolist()) == {K - 2, K - 1, 0, 1}


def test_sharded_resolver_mixed_differential():
    """The mesh-sharded twin answers the same mixed key/range differential
    (rows over 'data'; the range kernel shards both arenas' rows)."""
    from accord_tpu.ops.resolver import ShardedBatchDepsResolver
    from accord_tpu.parallel.mesh import make_mesh

    rng = np.random.default_rng(19)
    _, node, store = setup_store()
    resolver = ShardedBatchDepsResolver(mesh=make_mesh(),
                                        num_buckets=256, initial_cap=512)
    store.deps_resolver = resolver
    _, tss = _register_mixed(store, node, rng, n_key=30, n_range=25)

    for tid, owned, before in _subjects(store, node, rng, tss, n=18):
        host = store.host_calculate_deps(tid, owned, before)
        dev = resolver.resolve_one(store, tid, owned, before)
        assert dev == host, f"sharded subject {tid}"
    _assert_counters_zero(resolver)
