"""Topology churn under load: the randomizer's full mutation set (move,
split, merge, electorate/joining reconfiguration, node bounce) running
concurrently with the workload, alone and combined with chaos and durability.

Mirrors the reference burn's TopologyRandomizer integration (test
topology/TopologyRandomizer.java:60,430 + Cluster.java:458-462): every shape
of epoch handover -- bootstrap + fetch, handover sync, electorate churn,
node replacement -- must preserve strict serializability and converge.
"""
from __future__ import annotations

import pytest

from accord_tpu.sim.burn import run_burn
from accord_tpu.sim.cluster import Cluster, ClusterConfig

SEEDS = (7, 9, 12)


def churn_config(**kw):
    # 4 nodes so bounce/move mutations always have a spare replica; client
    # patience sized to ride out bootstrap storms (multi-second handover)
    return ClusterConfig(num_nodes=4, rf=3, timeout_ms=4000.0,
                         preaccept_timeout_ms=4000.0, **kw)


@pytest.mark.parametrize("seed", SEEDS)
def test_churn_burn(seed):
    r = run_burn(seed, ops=300, topology_churn=True, churn_interval_ms=1000.0,
                 config=churn_config())
    assert r.lost == 0
    assert r.failed <= 30, f"excessive client loss: {r.failed}/300"


# The residual churn+chaos liveness holes (old-epoch stragglers wedging
# quiescence -- seeds 1 and 4 were the named reproducers) were fixed by the
# partial-read / gap-healing / lost-range-elision batch; the seed surface here
# includes the former reproducers plus a spread of previously-unrun seeds
# (1-15, 31 all verified green in the round-4 sweep).
@pytest.mark.parametrize("seed", (1, 4, 7, 13, 31))
def test_churn_with_chaos(seed):
    r = run_burn(seed, ops=300, topology_churn=True, churn_interval_ms=1000.0,
                 chaos_drop=0.05, chaos_partitions=True,
                 config=churn_config())
    assert r.lost == 0
    assert r.failed <= 60, f"excessive client loss: {r.failed}/300"


@pytest.mark.parametrize("seed", SEEDS)
def test_churn_with_durability(seed):
    r = run_burn(seed, ops=300, topology_churn=True, churn_interval_ms=1000.0,
                 config=churn_config(durability=True,
                                     durability_interval_ms=500.0))
    assert r.lost == 0
    assert r.failed <= 30, f"excessive client loss: {r.failed}/300"


def test_churn_deterministic():
    kw = dict(ops=200, topology_churn=True, churn_interval_ms=1000.0)
    a = run_burn(9, collect_log=True, config=churn_config(), **kw)
    b = run_burn(9, collect_log=True, config=churn_config(), **kw)
    assert a.log == b.log


def test_churn_exercises_every_mutation_and_bootstraps():
    """Every mutation kind fires under load (round-robin instead of random
    picks, so coverage is guaranteed), and a node that gained ranges (via
    move/bounce/merge) completes a bootstrap (its store acquires data it can
    then serve)."""
    import accord_tpu.sim.burn as burn_mod
    from accord_tpu.sim import topology_randomizer as TRmod
    from accord_tpu.topology.topology import Topology

    class CyclingRandomizer(TRmod.TopologyRandomizer):
        def _mutate(self, t):
            order = [self._move, self._split, self._merge, self._electorate,
                     self._bounce_node]
            for off in range(len(order)):
                mutation = order[(self.issued + off) % len(order)]
                shards = mutation(list(t.shards))
                if shards is not None:
                    name = mutation.__name__.lstrip("_")
                    self.mutation_counts[name] = \
                        self.mutation_counts.get(name, 0) + 1
                    return Topology(t.epoch + 1, shards)
            return None

    randomizers = []
    orig_start = TRmod.TopologyRandomizer.start

    def spy_start(self):
        randomizers.append(self)
        return orig_start(self)

    captured = []

    class SpyCluster(Cluster):
        def __init__(self, *a, **k):
            super().__init__(*a, **k)
            captured.append(self)

    orig_tr = TRmod.TopologyRandomizer
    TRmod.TopologyRandomizer = CyclingRandomizer
    CyclingRandomizer.start = spy_start
    orig_cluster = burn_mod.Cluster
    burn_mod.Cluster = SpyCluster
    try:
        counts: dict = {}
        bootstrapped = False
        for seed in (7, 9, 12):
            captured.clear()
            # 400ms interval: transitive-dependency elision shortened the
            # burn's sim time enough that 700ms ticks no longer reach all
            # five mutation kinds within one run
            r = run_burn(seed, ops=250, topology_churn=True,
                         churn_interval_ms=400.0, config=churn_config())
            assert r.lost == 0
            for k, v in randomizers[-1].mutation_counts.items():
                counts[k] = counts.get(k, 0) + v
            for node in captured[0].nodes.values():
                for s in node.command_stores.all():
                    if not s.safe_to_read.is_empty():
                        bootstrapped = True
    finally:
        TRmod.TopologyRandomizer = orig_tr
        burn_mod.Cluster = orig_cluster
    for kind in ("move", "split", "merge", "electorate", "bounce_node"):
        assert counts.get(kind, 0) > 0, f"mutation {kind} never applied: {counts}"
    assert bootstrapped, "no store ever completed a range acquisition"
