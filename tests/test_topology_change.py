"""Topology change: epochs, handover sync, bootstrap + fetch.

Mirrors the reference's elasticity machinery (topology/TopologyManager.java:71,
local/CommandStores.java:646 updateTopology, local/Bootstrap.java:81,
impl/AbstractFetchCoordinator.java:60): a new epoch that moves a range to a
new replica must (a) keep coordinations contacting the old replica set until
the epoch syncs, (b) have the new replica acquire the range's history before
serving reads, and (c) converge.
"""
from __future__ import annotations

import pytest

from accord_tpu.local.status import Status
from accord_tpu.primitives.keyspace import Keys, Range, Ranges
from accord_tpu.primitives.timestamp import TxnKind
from accord_tpu.primitives.txn import Txn
from accord_tpu.sim.cluster import Cluster, ClusterConfig, build_topology
from accord_tpu.sim.list_store import ListQuery, ListRead, ListUpdate
from accord_tpu.topology.shard import Shard
from accord_tpu.topology.topology import Topology


def write_txn(keys: Keys, value: int) -> Txn:
    return Txn(TxnKind.WRITE, keys, read=ListRead(keys),
               update=ListUpdate(keys, value), query=ListQuery())


def read_txn(keys: Keys) -> Txn:
    return Txn(TxnKind.READ, keys, read=ListRead(keys), query=ListQuery())


def four_node_cluster(seed: int) -> Cluster:
    return Cluster(seed, ClusterConfig(num_nodes=4, rf=3))


def move_shard(topology: Topology, shard_index: int, new_nodes) -> Topology:
    """Next epoch with one shard's replica set replaced."""
    shards = list(topology.shards)
    old = shards[shard_index]
    shards[shard_index] = Shard(old.range, list(new_nodes))
    return Topology(topology.epoch + 1, shards)


def test_epoch_sync_acks_retire_old_epoch():
    cluster = four_node_cluster(seed=101)
    t1 = cluster.current_topology()
    # shard 0 is [0, 16384) on nodes (1, 2, 3); hand it to (2, 3, 4)
    t2 = move_shard(t1, 0, (2, 3, 4))
    cluster.issue_topology(t2)
    cluster.drain()
    cluster.check_no_failures()
    for node in cluster.nodes.values():
        assert node.topology_manager.has_epoch(2)
        assert node.topology_manager.is_synced(2), \
            f"node {node.id} never saw epoch 2 sync"


def test_bootstrap_fetches_history_for_added_range():
    cluster = four_node_cluster(seed=102)
    node1 = cluster.nodes[1]
    keys = Keys([10, 500, 12000])  # all in shard 0 = [0, 16384)
    for v in (1, 2, 3):
        node1.coordinate(write_txn(keys, v))
    cluster.drain()
    cluster.check_no_failures()

    t2 = move_shard(cluster.current_topology(), 0, (2, 3, 4))
    cluster.issue_topology(t2)
    cluster.drain()
    cluster.check_no_failures()

    # node 4 (the new replica) must hold the full history
    store4 = cluster.stores[4]
    for k in keys:
        assert store4.snapshot(k) == (1, 2, 3), \
            f"node 4 missing history for {k}: {store4.snapshot(k)}"
    # and its command stores must be safe to read the whole added range
    for s in cluster.nodes[4].command_stores.all():
        owned = s.current_owned()
        assert s.safe_to_read.contains_ranges(owned)


def test_new_replica_serves_consistent_reads():
    cluster = four_node_cluster(seed=103)
    keys = Keys([42])
    for v in (1, 2):
        cluster.nodes[1].coordinate(write_txn(keys, v))
        cluster.drain()  # sequential: fixes the serialization order
    cluster.check_no_failures()

    t2 = move_shard(cluster.current_topology(), 0, (2, 3, 4))
    cluster.issue_topology(t2)
    cluster.drain()
    cluster.check_no_failures()

    # read coordinated AND served at the new replica
    r = cluster.nodes[4].coordinate(read_txn(keys))
    cluster.drain()
    cluster.check_no_failures()
    assert r.done and r.failure is None, f"read failed: {r.failure!r}"
    assert r.value().reads[42] == (1, 2)


def test_writes_across_handover_converge():
    """Writes racing the topology change land on both replica sets and the
    final owners converge on one history."""
    cluster = four_node_cluster(seed=104)
    keys = Keys([7, 9000])
    results = []
    for v in (1, 2):
        results.append(cluster.nodes[1].coordinate(write_txn(keys, v)))
    # issue the epoch while those writes are (possibly) in flight
    t2 = move_shard(cluster.current_topology(), 0, (2, 3, 4))
    cluster.issue_topology(t2)
    for v in (3, 4):
        results.append(cluster.nodes[2].coordinate(write_txn(keys, v)))
    cluster.drain()
    cluster.check_no_failures()
    done = [r for r in results if r.done and r.failure is None]
    assert len(done) >= 3  # racing the floor may invalidate a straggler
    cluster.converged_key_lists()


def test_rf_expansion_bootstraps_added_replica():
    """Growing a shard from rf=2 to rf=3 bootstraps the new member."""
    cluster = Cluster(105, ClusterConfig(num_nodes=3, rf=2))
    keys = Keys([100])
    cluster.nodes[1].coordinate(write_txn(keys, 9))
    cluster.drain()
    cluster.check_no_failures()
    t1 = cluster.current_topology()
    shard0 = t1.shards[0]
    assert 100 in range(shard0.range.start, shard0.range.end) or \
        shard0.range.contains(100)
    new_nodes = sorted(set(shard0.nodes) | {3})
    shards = list(t1.shards)
    shards[0] = Shard(shard0.range, new_nodes)
    cluster.issue_topology(Topology(2, shards))
    cluster.drain()
    cluster.check_no_failures()
    assert cluster.stores[3].snapshot(100) == (9,)
    cluster.converged_key_lists()


def test_epoch_retirement_plateaus():
    """Long churn + durability rounds: retained epoch state must plateau
    (reference: TopologyManager closed/complete retirement) instead of
    growing with every issued epoch."""
    from accord_tpu.sim.burn import run_burn
    from accord_tpu.sim.cluster import Cluster, ClusterConfig
    _last = {}
    orig = Cluster.__init__

    def spy(self, *a, **k):
        orig(self, *a, **k)
        _last["c"] = self

    Cluster.__init__ = spy
    try:
        r = run_burn(9, ops=400, topology_churn=True, churn_interval_ms=400.0,
                     config=ClusterConfig(num_nodes=4, rf=3,
                                          timeout_ms=4000.0,
                                          preaccept_timeout_ms=4000.0,
                                          durability=True,
                                          durability_interval_ms=300.0))
    finally:
        Cluster.__init__ = orig
    assert r.lost == 0
    c = _last["c"]
    issued = max(c.topology_service.epochs)
    retained = min(len(n.topology_manager._epochs) for n in c.nodes.values())
    assert issued >= 6, f"churn too tame to test retirement ({issued} epochs)"
    # global durability rounds are best-effort broadcasts, so assert the
    # mechanism fired (nodes that missed the last round retire on the next)
    assert retained < issued, \
        f"no epoch ever retired: {retained} retained of {issued} issued"
