"""Device-plane chaos: seeded fault injection against the resolver's
dispatch/harvest pipeline (ops/fault_plane.py).

The hardening claims under test, end to end:

  * every corrupted readback is caught by the finalize checksum lane
    BEFORE decode (checksum_mismatches == corrupt injections, and the
    strict-serializability verifier sees no wrong deps);
  * stuck calls either complete late inside the watchdog's probe budget
    or trip it and answer host-side -- never wedge the pipeline;
  * the per-node health ladder quarantines a faulting node's device path,
    serves the countdown through the host differential path, and walks
    back to HEALTHY through probation canaries;
  * all handling is sim-timing-neutral: two chaos runs reconcile
    bit-identically, and the fault-free run of the same seed commits the
    SAME history (the injected-fault rng is forked unconditionally, so the
    streams align).

Fast subset runs in tier 1; the per-kind x protocol-flag matrix is
slow-marked (the `chaos` marker selects the whole family).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest

from accord_tpu.ops.fault_plane import (DEGRADED, FAULT_KINDS, HEALTHY,
                                        PROBATION, QUARANTINED,
                                        DeviceFaultPlane, DeviceHealth)
from accord_tpu.ops.kernels import csr_checksum, csr_checksum_host
from accord_tpu.ops.resolver import BatchDepsResolver
from accord_tpu.sim.burn import run_burn
from accord_tpu.sim.cluster import ClusterConfig
from accord_tpu.utils import faults
from accord_tpu.utils.rng import RandomSource

pytestmark = pytest.mark.chaos


# -- units: health ladder ----------------------------------------------------

def test_health_ladder_full_round_trip():
    """HEALTHY -> DEGRADED -> QUARANTINED -> (host countdown) -> PROBATION
    -> (canaries) -> HEALTHY, with every transition observed."""
    seen = []
    h = DeviceHealth(quarantine_after=2, recover_after=4,
                     quarantine_dispatches=3, probation_canaries=2,
                     on_transition=lambda old, new: seen.append((old, new)))
    h.on_fault("stuck")
    assert h.state == DEGRADED and not h.route_host
    h.on_fault("corrupt")
    assert h.state == QUARANTINED and h.route_host
    for _ in range(3):
        assert h.route_host
        h.on_host_dispatch()
    assert h.state == PROBATION and h.wants_canary
    h.canary_ok()
    assert h.state == PROBATION  # needs probation_canaries consecutive
    h.canary_ok()
    assert h.state == HEALTHY
    assert seen == [(HEALTHY, DEGRADED), (DEGRADED, QUARANTINED),
                    (QUARANTINED, PROBATION), (PROBATION, HEALTHY)]
    assert h.transitions == 4


def test_health_ladder_degraded_recovers_without_quarantine():
    """A single fault followed by enough clean harvests walks DEGRADED back
    to HEALTHY; consecutive-fault counting resets on a clean dispatch."""
    h = DeviceHealth(quarantine_after=2, recover_after=3)
    h.on_fault("dispatch_exc")
    assert h.state == DEGRADED
    h.on_clean_dispatch()          # resets the consecutive-fault count
    h.on_fault("dispatch_exc")     # so this is 1 again, not 2
    assert h.state == DEGRADED
    for _ in range(3):
        h.on_clean_dispatch()
    assert h.state == HEALTHY


def test_health_ladder_probation_fault_requarantines():
    h = DeviceHealth(quarantine_after=1, quarantine_dispatches=1,
                     probation_canaries=2)
    h.on_fault("stuck")
    assert h.state == QUARANTINED
    h.on_host_dispatch()
    assert h.state == PROBATION
    h.canary_ok()
    h.on_fault("corrupt")          # mid-probation fault: straight back
    assert h.state == QUARANTINED
    h.on_host_dispatch()
    assert h.state == PROBATION    # a full fresh countdown was served


# -- units: checksum lane ----------------------------------------------------

def test_checksum_device_host_agree_and_catch_bit_flips():
    """The jitted fold and its host twin agree exactly on the finalize
    kernels' result shapes (indptr i32[S+1], dep_rows i32[N], dep_ts
    i32[N, 3]), and ANY single-bit flip in any covered array changes the
    sum."""
    rng = np.random.default_rng(5)
    indptr = np.cumsum(rng.integers(0, 5, 33)).astype(np.int32)
    rows = rng.integers(0, 1 << 20, int(indptr[-1])).astype(np.int32)
    ts = rng.integers(0, 1 << 31, (int(indptr[-1]), 3)).astype(np.int32)
    dev = int(csr_checksum(jnp.asarray(indptr), jnp.asarray(rows),
                           jnp.asarray(ts)))
    host = csr_checksum_host(indptr, rows, ts)
    assert dev == host
    for arr in (indptr, rows, ts):
        for _ in range(8):
            clone = [np.array(a) for a in (indptr, rows, ts)]
            tgt = clone[[id(indptr), id(rows), id(ts)].index(id(arr))]
            flat = tgt.reshape(-1).view(np.uint32)
            pos = int(rng.integers(flat.shape[0]))
            bit = int(rng.integers(32))
            flat[pos] ^= np.uint32(1) << np.uint32(bit)
            assert csr_checksum_host(*clone) != host, \
                f"flip at word {pos} bit {bit} not detected"


def test_fault_plane_deterministic_and_exact_ledger():
    """Two planes over identically-seeded rngs draw the same schedule and
    flip the same bits; the injected ledger counts only APPLIED faults."""
    rates = dict(dispatch_exc_rate=0.2, stuck_rate=0.2, corrupt_rate=0.2,
                 overflow_rate=0.1)
    a = DeviceFaultPlane(RandomSource(99).fork(), **rates)
    b = DeviceFaultPlane(RandomSource(99).fork(), **rates)
    assert [a.draw() for _ in range(300)] == [b.draw() for _ in range(300)]
    bufs_a = [np.arange(16, dtype=np.int32), np.arange(8, dtype=np.int32)]
    bufs_b = [np.arange(16, dtype=np.int32), np.arange(8, dtype=np.int32)]
    assert a.corrupt_arrays(bufs_a) and b.corrupt_arrays(bufs_b)
    assert all(np.array_equal(x, y) for x, y in zip(bufs_a, bufs_b))
    assert a.injected["corrupt"] == 1
    assert not a.corrupt_arrays([np.empty(0, np.int32)])  # nothing to hit
    assert a.injected["corrupt"] == 1  # dropped draws are not counted


# -- burns: the fast tier-1 chaos leg ----------------------------------------

CHAOS_RATES = {"dispatch_exc_rate": 0.08, "stuck_rate": 0.08,
               "corrupt_rate": 0.08, "overflow_rate": 0.03}


def _chaos_leg(seed, ops, chaos, rates=None, **burn_kwargs):
    resolvers = []

    def factory():
        r = BatchDepsResolver(num_buckets=128)
        resolvers.append(r)
        return r

    cfg = ClusterConfig(deps_resolver_factory=factory,
                        deps_batch_window_ms=2.0, device_latency_ms=8.0)
    rep = run_burn(seed, ops=ops, key_count=8, concurrency=8,
                   write_ratio=0.7, device_chaos=chaos,
                   device_fault_rates=rates, collect_log=True, config=cfg,
                   **burn_kwargs)
    return rep, resolvers


def _agg(resolvers, name):
    return sum(getattr(r, name) for r in resolvers)


def test_chaos_burn_all_kinds_reconciles_and_matches_fault_free():
    """The tier-1 chaos gate: one contended burn with every fault kind
    armed. All four kinds fire and are handled (exact per-kind ledgers),
    the health ladder round-trips quarantine, two chaos runs are
    bit-identical, and the fault-free run of the same seed commits the
    same history -- injected faults are invisible to simulated state."""
    rep_a, res_a = _chaos_leg(31, 120, True, CHAOS_RATES)
    rep_b, _ = _chaos_leg(31, 120, True, CHAOS_RATES)
    rep_c, _ = _chaos_leg(31, 120, False)

    assert rep_a.lost == 0 and rep_a.failed == 0
    assert rep_a.log == rep_b.log, "chaos burn is not reconcile-identical"
    assert rep_a.log == rep_c.log, \
        "chaos history diverged from the fault-free run of the same seed"
    inj = rep_a.device_faults
    assert all(inj[k] > 0 for k in FAULT_KINDS), inj
    assert rep_c.device_faults is None
    # exact ledgers: every injection was consumed and counted once
    assert _agg(res_a, "device_faults_injected") == sum(inj.values())
    assert _agg(res_a, "checksum_mismatches") == inj["corrupt"]
    assert _agg(res_a, "device_watchdog_trips") > 0
    assert _agg(res_a, "device_retries") > 0
    # the ladder round-tripped: nodes were quarantined AND recovered
    assert _agg(res_a, "quarantine_entries") > 0
    assert _agg(res_a, "quarantine_exits") > 0
    assert _agg(res_a, "device_canaries") > 0
    assert _agg(res_a, "degraded_dispatches") > 0
    # finalize fallbacks under chaos are EXACTLY the handled injections
    # that abandon the compacted CSR -- caught corruptions plus consumed
    # overflow storms (each falls back to the legacy decode of the
    # uncorrupted raw candidate buffers); nothing else trips the guards
    assert _agg(res_a, "finalize_fallbacks") == inj["corrupt"] + inj["overflow"]


# -- slow matrix: isolated fault kinds x protocol fault flags -----------------

_KIND_RATE = {"dispatch_exc": "dispatch_exc_rate", "stuck": "stuck_rate",
              "corrupt": "corrupt_rate", "overflow": "overflow_rate"}


@pytest.mark.slow
@pytest.mark.parametrize("fast_path_disabled", [False, True])
@pytest.mark.parametrize("kind", FAULT_KINDS)
def test_fault_matrix_each_kind_isolated(kind, fast_path_disabled):
    """One fault kind at a time, with and without the protocol-level
    FAST_PATH_DISABLED flag: the kind fires, its specific handling ledger
    moves, no other kind's does, and the history still matches the
    fault-free leg under the same flag."""
    rates = {_KIND_RATE[kind]: 0.12}
    with faults.scoped(FAST_PATH_DISABLED=fast_path_disabled):
        rep, res = _chaos_leg(47, 100, True, rates)
        rep_clean, _ = _chaos_leg(47, 100, False)
    assert rep.lost == 0
    assert rep.log == rep_clean.log
    inj = rep.device_faults
    assert inj[kind] > 0, inj
    assert all(v == 0 for k, v in inj.items() if k != kind), inj
    assert _agg(res, "device_faults_injected") == inj[kind]
    assert _agg(res, "checksum_mismatches") == \
        (inj["corrupt"] if kind == "corrupt" else 0)
    if kind == "stuck":
        assert _agg(res, "device_retries") > 0
    if kind == "dispatch_exc":
        assert _agg(res, "device_retries") > 0
