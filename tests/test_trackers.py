from accord_tpu.coordinate.tracking import (
    FastPathTracker, QuorumTracker, ReadTracker, RequestStatus,
)
from accord_tpu.primitives.keyspace import Range
from accord_tpu.topology.shard import Shard
from accord_tpu.topology.topologies import Topologies
from accord_tpu.topology.topology import Topology


def topo3():
    return Topologies.single(Topology(1, [Shard(Range(0, 100), [1, 2, 3])]))


def topo5_2shards():
    return Topologies.single(Topology(1, [
        Shard(Range(0, 50), [1, 2, 3]),
        Shard(Range(50, 100), [3, 4, 5]),
    ]))


def test_shard_quorum_math():
    s = Shard(Range(0, 1), [1, 2, 3])
    assert s.max_failures == 1
    assert s.slow_path_quorum_size == 2
    assert s.fast_path_quorum_size == 3  # (1 + 3)//2 + 1
    s5 = Shard(Range(0, 1), [1, 2, 3, 4, 5])
    assert s5.max_failures == 2
    assert s5.slow_path_quorum_size == 3
    assert s5.fast_path_quorum_size == 4  # (2 + 5)//2 + 1
    assert not s5.rejects_fast_path(1)
    assert s5.rejects_fast_path(2)


def test_quorum_tracker():
    t = QuorumTracker(topo3())
    assert t.nodes() == (1, 2, 3)
    assert t.on_success(1) == RequestStatus.NO_CHANGE
    assert t.on_success(2) == RequestStatus.SUCCESS
    assert t.on_success(3) == RequestStatus.NO_CHANGE  # already decided


def test_quorum_tracker_failure():
    t = QuorumTracker(topo3())
    assert t.on_failure(1) == RequestStatus.NO_CHANGE
    assert t.on_failure(2) == RequestStatus.FAILED


def test_quorum_tracker_multi_shard():
    t = QuorumTracker(topo5_2shards())
    # quorum in shard 1 only
    t.on_success(1)
    assert t.on_success(2) == RequestStatus.NO_CHANGE
    # node 3 counts for both shards; shard 2 needs one more
    t.on_success(3)
    assert t.on_success(4) == RequestStatus.SUCCESS


def test_fast_path_tracker_fast():
    t = FastPathTracker(topo3())
    assert t.on_success(1, True) == RequestStatus.NO_CHANGE
    assert t.on_success(2, True) == RequestStatus.NO_CHANGE
    # rf=3: fast quorum is all 3 electorate members
    assert t.on_success(3, True) == RequestStatus.SUCCESS
    assert t.has_fast_path_accepted()


def test_fast_path_tracker_slow_resolution():
    t = FastPathTracker(topo3())
    t.on_success(1, True)
    t.on_success(2, True)
    # a single non-fast vote makes fq=3 impossible -> resolve slow
    assert t.on_success(3, False) == RequestStatus.SUCCESS
    assert not t.has_fast_path_accepted()


def test_fast_path_tracker_waits_for_resolution():
    t = FastPathTracker(topo3())
    # quorum reached but fast path still possible: must NOT decide yet
    assert t.on_success(1, True) == RequestStatus.NO_CHANGE
    assert t.on_success(2, True) == RequestStatus.NO_CHANGE
    assert t.decided is None


def test_fast_path_tracker_electorate_failure():
    t = FastPathTracker(topo3())
    t.on_success(1, True)
    t.on_success(2, True)
    # failure of the third electorate member rules out fq=3
    assert t.on_failure(3) == RequestStatus.SUCCESS
    assert not t.has_fast_path_accepted()


def test_read_tracker():
    t = ReadTracker(topo5_2shards())
    contacts = t.initial_contacts()
    # node 3 replicates both shards -> a single contact may cover both
    assert len(contacts) in (1, 2)
    for c in contacts:
        st = t.on_data_success(c)
    assert t.decided == RequestStatus.SUCCESS


def test_read_tracker_escalation():
    t = ReadTracker(topo3())
    (c,) = t.initial_contacts()
    status, more = t.on_read_failure(c)
    assert status == RequestStatus.NO_CHANGE and len(more) == 1
    assert t.on_data_success(more[0]) == RequestStatus.SUCCESS


def test_read_tracker_exhaustion():
    t = ReadTracker(topo3())
    (c1,) = t.initial_contacts()
    _, (c2,) = t.on_read_failure(c1)
    _, (c3,) = t.on_read_failure(c2)
    status, more = t.on_read_failure(c3)
    assert status == RequestStatus.FAILED


def test_invalidation_tracker():
    """(reference: InvalidationTracker.java:28) promise quorum + the
    fast-path-rejection arithmetic scoped to the txn's original epoch."""
    from accord_tpu.coordinate.tracking import InvalidationTracker
    from accord_tpu.primitives.keyspace import Keys

    def make():
        return InvalidationTracker(
            Topologies.single(Topology(1, [Shard(Range(0, 100), [1, 2, 3, 4, 5])])),
            Keys([10]), fast_path_epoch=1)

    # rf=5: fast quorum 4, electorate 5, spare = 1 -> rejected needs >1 rejects
    t = make()
    assert t.on_success(1, False) == RequestStatus.NO_CHANGE
    assert not t.is_fast_path_rejected()       # 1 reject <= spare
    assert t.on_success(2, False) == RequestStatus.NO_CHANGE
    assert t.is_fast_path_rejected()           # 2 rejects > spare: fp dead
    assert t.on_success(3, True) == RequestStatus.SUCCESS
    assert t.is_fast_path_rejected()

    # a fast VOTE never contributes to rejection
    t = make()
    t.on_success(1, True)
    t.on_success(2, True)
    t.on_success(3, False)
    assert not t.is_fast_path_rejected()

    # failures prove nothing about the original fast path
    t = make()
    t.on_success(1, False)
    t.on_failure(2)
    t.on_failure(3)
    assert not t.is_fast_path_rejected()

    # no shard state at the fast-path epoch (retired): never "safe"
    t = InvalidationTracker(
        Topologies.single(Topology(2, [Shard(Range(0, 100), [1, 2, 3])])),
        Keys([10]), fast_path_epoch=1)
    t.on_success(1, False)
    t.on_success(2, False)
    t.on_success(3, False)
    assert not t.is_fast_path_rejected()


def test_progress_token_order_and_merge():
    """(reference: primitives/ProgressToken.java) durability dominates, then
    phase, then ballot; merge is the component-wise max."""
    from accord_tpu.local.status import Durability, ProgressToken, Status
    from accord_tpu.primitives.timestamp import Ballot, Timestamp

    b1 = Ballot.from_timestamp(Timestamp(1, 5, 0, 1))
    b2 = Ballot.from_timestamp(Timestamp(1, 9, 0, 2))
    none = ProgressToken.NONE
    preaccepted = ProgressToken(Durability.NOT_DURABLE, Status.PRE_ACCEPTED,
                                Ballot.ZERO)
    accepted_b1 = ProgressToken(Durability.NOT_DURABLE, Status.ACCEPTED, b1)
    accepted_b2 = ProgressToken(Durability.NOT_DURABLE, Status.ACCEPTED, b2)
    applied = ProgressToken(Durability.NOT_DURABLE, Status.APPLIED, Ballot.ZERO)
    durable = ProgressToken(Durability.MAJORITY, Status.PRE_ACCEPTED, Ballot.ZERO)

    assert none < preaccepted < accepted_b1 < accepted_b2 < applied < durable
    m = accepted_b1.merge(durable)
    assert m.durability == Durability.MAJORITY
    assert m.status == Status.ACCEPTED and m.promised == b1
    assert accepted_b1.merge(accepted_b1) == accepted_b1
