"""Serving surface tests: the transport codec (pure, no sockets), the
admission governor (deterministic injected clock), and -- marked slow --
real 3-process clusters over TCP: commit + strict-serializability verify,
and a crash-one-node leg where the surviving quorum keeps committing.

No sockets are bound at collection time; every bind happens inside a test
body (and only in the slow ones)."""
from __future__ import annotations

import asyncio
import os
import socket
import subprocess
import sys
import time

import pytest

from accord_tpu.serve import transport
from accord_tpu.serve.admission import AdmissionController, TokenBucket

pytestmark = pytest.mark.serve


# -- framing ------------------------------------------------------------------

def test_frame_roundtrip_single():
    payload = b"hello accord"
    frame = transport.encode_frame(payload)
    assert frame[:4] == len(payload).to_bytes(4, "big")
    dec = transport.FrameDecoder()
    assert dec.feed(frame) == [payload]
    assert dec.pending_bytes() == 0


def test_frame_decoder_handles_arbitrary_segmentation():
    payloads = [b"", b"x", b"y" * 300, b"z" * 70000]
    stream = b"".join(transport.encode_frame(p) for p in payloads)
    # worst case: the stream arrives one byte at a time (headers and
    # payloads both split across feeds)
    dec = transport.FrameDecoder()
    out = []
    for i in range(len(stream)):
        out.extend(dec.feed(stream[i:i + 1]))
    assert out == payloads
    assert dec.pending_bytes() == 0
    assert dec.bytes_in == len(stream)


def test_frame_decoder_many_frames_one_chunk():
    payloads = [bytes([i]) * (i * 37 + 1) for i in range(20)]
    stream = b"".join(transport.encode_frame(p) for p in payloads)
    dec = transport.FrameDecoder()
    assert dec.feed(stream) == payloads


def test_frame_large_payload_over_64kib():
    # bigger than any single socket read chunk (the server reads 64 KiB at
    # a time), so the decoder must hold a partial body across feeds
    payload = os.urandom((1 << 20) + 17)
    stream = transport.encode_frame(payload)
    dec = transport.FrameDecoder()
    out = []
    for off in range(0, len(stream), 1 << 16):
        out.extend(dec.feed(stream[off:off + (1 << 16)]))
    assert out == [payload]


def test_frame_ceiling_enforced_both_directions():
    with pytest.raises(transport.FrameError):
        transport.encode_frame(b"x" * (transport.MAX_FRAME_BYTES + 1))
    # a hostile/corrupt header must fail fast, not buffer gigabytes
    bad = (transport.MAX_FRAME_BYTES + 1).to_bytes(4, "big")
    with pytest.raises(transport.FrameError):
        transport.FrameDecoder().feed(bad)


def test_envelope_roundtrips_wire_codec():
    env = {"t": "accord", "mid": 7, "from": 2,
           "payload": {"nested": [1, 2, (3, 4)], "k": "v"}}
    frame = transport.encode_envelope(env)
    (payload,) = transport.FrameDecoder().feed(frame)
    got = transport.decode_message(payload)
    assert got == env
    assert got is not env  # value copy, never a shared live object


def test_line_decoder_partial_lines():
    dec = transport.LineDecoder()
    assert list(dec.feed(b'{"a": 1}\n{"b"')) == [b'{"a": 1}']
    assert list(dec.feed(b": 2}\n\n")) == [b'{"b": 2}']
    assert transport.decode_json_line(b'{"b": 2}') == {"b": 2}


def test_bind_host_parses_and_roundtrips():
    """Multi-host plumbing, no sockets: non-loopback addresses parse, the
    bind/advertise split lands in ServeConfig (bind_host is the bound
    interface; `listen` stays the ADVERTISED address peers dial, and
    None means bind the advertised host -- the loopback CI default), and
    peer maps carrying non-loopback addresses survive the frame codec."""
    from accord_tpu.serve.server import ServeConfig, _parse_addr, _parse_peers

    assert _parse_addr("0.0.0.0:7001") == ("0.0.0.0", 7001)
    assert _parse_addr("10.1.2.3:7102") == ("10.1.2.3", 7102)
    assert _parse_addr("7103") == ("127.0.0.1", 7103)  # bare-port default

    peers = _parse_peers("1=10.1.2.3:7101,2=10.1.2.4:7101,3=127.0.0.1:7103")
    assert peers == {1: ("10.1.2.3", 7101), 2: ("10.1.2.4", 7101),
                     3: ("127.0.0.1", 7103)}

    cfg = ServeConfig(node_id=1, listen=("10.1.2.3", 7101), peers=peers,
                      bind_host="0.0.0.0")
    assert cfg.bind_host == "0.0.0.0"
    assert cfg.listen == ("10.1.2.3", 7101)  # advertised, not the bind
    assert ServeConfig(node_id=1, listen=("127.0.0.1", 7101),
                       peers=peers).bind_host is None

    # a peer-exchange payload with routable addresses round-trips the
    # length-prefixed wire codec byte-exactly
    env = {"t": "peers", "from": 1,
           "payload": {nid: list(addr) for nid, addr in peers.items()}}
    (raw,) = transport.FrameDecoder().feed(transport.encode_envelope(env))
    assert transport.decode_message(raw) == env


# -- admission ----------------------------------------------------------------

def test_token_bucket_rate_and_burst():
    b = TokenBucket(rate_per_s=10.0, burst=5)
    # burst drains immediately...
    assert [b.try_take(0.0) for _ in range(6)] == [True] * 5 + [False]
    # ...then refills at exactly rate_per_s
    assert not b.try_take(0.05)   # half a token earned: still dry
    assert b.try_take(0.1)        # one token earned
    assert not b.try_take(0.1)


def test_admission_overload_sheds_with_explicit_busy():
    """Offered load far beyond capacity: every arrival is either admitted
    or answered BUSY (nothing silently dropped), queue depth stays at the
    bound, and pressure engages once per episode."""
    pressure_calls = []
    adm = AdmissionController(rate_per_s=100.0, burst=10, max_inflight=8,
                              on_pressure=pressure_calls.append)
    admitted = busy = 0
    inflight = []
    # 1000 arrivals in one simulated second = 10x the sustained rate
    for i in range(1000):
        now = i / 1000.0
        if adm.try_admit(now):
            admitted += 1
            inflight.append(now)
            assert adm.inflight <= adm.max_inflight
        else:
            busy += 1
        # complete admitted work slowly: 1 completion per 4 arrivals keeps
        # the queue pinned at its depth bound
        if i % 4 == 0 and inflight:
            inflight.pop()
            adm.on_complete(now)
    assert admitted + busy == 1000  # zero dropped-without-reply
    assert busy > 0 and adm.busy_count == busy
    assert adm.metrics.gauge("serve.queue_depth").value <= adm.max_inflight
    # overload is one episode: pressure engaged once, not per BUSY
    assert pressure_calls == [True]
    assert adm.shed_count == 1
    # drain whatever is still in flight, then a full quiet window later
    # the next admit disengages the governor
    while inflight:
        inflight.pop()
        adm.on_complete(0.999)
    t = 1.0 + AdmissionController.QUIET_WINDOW_S
    assert adm.try_admit(t)
    adm.on_complete(t)
    assert pressure_calls == [True, False]
    # the next overload is a NEW episode
    for i in range(200):
        adm.try_admit(t + 0.001 * i)
    assert adm.shed_count == 2


def test_admission_closed_rejects_everything():
    adm = AdmissionController(rate_per_s=1000.0, burst=100, max_inflight=10)
    assert adm.try_admit(0.0)
    adm.closed = True
    assert not adm.try_admit(0.1)
    adm.on_complete(0.2)
    assert adm.inflight == 0


# -- shutdown semantics -------------------------------------------------------

def test_node_shutdown_idempotent_and_schedulerless():
    """Node.shutdown drains the device pipeline exactly once (a second
    call -- serve-mode Ctrl-C racing a client shutdown -- is a no-op) and
    works on a node whose scheduler is gone (an external event loop owns
    the drain; harvest timers are skipped, the blocking drain still runs
    to completion)."""
    from accord_tpu.maelstrom.runner import Runner

    r = Runner(seed=3, num_nodes=2)
    r.run_random_workload(ops=8, keys=4)
    first, second = (mn.node for mn in r.nodes.values())
    snapshots = []
    first.metrics_sink = snapshots.append
    first.shutdown()
    first.shutdown()
    assert len(snapshots) == 1, "second shutdown re-drained the pipeline"
    second.scheduler = None
    second.shutdown()  # must not touch the missing scheduler


# -- multi-process cluster (slow) ---------------------------------------------

def _free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


class _Cluster:
    """N serve processes on loopback. Started with --no-warmup (tests warm
    in-band instead of paying the full tier pre-compile per process) and a
    long rpc timeout so in-band compilation cannot fail early txns."""

    def __init__(self, n=3, tmpdir="/tmp"):
        self.ports = _free_ports(n)
        peers = ",".join(f"{i + 1}=127.0.0.1:{p}"
                         for i, p in enumerate(self.ports))
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        self.logs = []
        self.procs = []
        for i, port in enumerate(self.ports):
            log = open(os.path.join(tmpdir, f"serve-n{i + 1}.log"), "w")
            self.logs.append(log)
            self.procs.append(subprocess.Popen(
                [sys.executable, "-m", "accord_tpu.serve",
                 "--node-id", str(i + 1),
                 "--listen", f"127.0.0.1:{port}", "--peers", peers,
                 "--no-warmup", "--rpc-timeout-ms", "20000",
                 "--metrics-interval-s", "60"],
                env=env, stdout=log, stderr=log))

    @property
    def addrs(self):
        return {i + 1: ("127.0.0.1", p) for i, p in enumerate(self.ports)}

    async def wait_listening(self, timeout_s=60.0):
        for host, port in self.addrs.values():
            deadline = time.monotonic() + timeout_s
            while True:
                try:
                    _, w = await asyncio.open_connection(host, port)
                    w.close()
                    break
                except OSError:
                    if time.monotonic() > deadline:
                        raise AssertionError(f"node on :{port} never bound")
                    await asyncio.sleep(0.2)

    def kill(self, nid):
        self.procs[nid - 1].kill()

    def teardown(self):
        for p in self.procs:
            if p.poll() is None:
                p.kill()
            p.wait(timeout=10)
        for log in self.logs:
            log.close()


async def _shutdown_all(client, cluster, nids):
    for nid in nids:
        reply = await client.admin(nid, "shutdown", timeout_s=30)
        assert reply is not None and reply["t"] == "shutdown_ok", reply
    for nid in nids:
        assert cluster.procs[nid - 1].wait(timeout=15) == 0


def _merged_keylists(lists_by_node):
    """Per-key longest list across nodes, asserting every node's copy is a
    prefix of the longest (append-only convergence)."""
    merged = {}
    for lists in lists_by_node.values():
        for k, v in lists.items():
            cur = merged.setdefault(k, v)
            short, long_ = (cur, v) if len(cur) <= len(v) else (v, cur)
            assert tuple(long_[:len(short)]) == tuple(short), \
                f"key {k} diverged: {cur} vs {v}"
            merged[k] = long_
    return merged


@pytest.mark.slow
def test_three_process_commit_and_verify(tmp_path):
    from accord_tpu.serve.loadgen import LoadClient, LoadGen, verify_history

    cluster = _Cluster(3, str(tmp_path))

    async def scenario():
        await cluster.wait_listening()
        client = LoadClient(cluster.addrs)
        await client.connect()
        try:
            gen = LoadGen(client, seed=31, txn_timeout_s=60.0)
            # warm leg: drives every node's in-band kernel compiles; its
            # entries stay part of the one verified history
            await gen.run_leg(rate_per_s=3, duration_s=4)
            leg = await gen.run_leg(rate_per_s=25, duration_s=4)
            assert leg["ok"] > 0, leg
            assert leg["lost"] == 0 and leg["errors"] == 0, leg
            assert leg["p99_us"] > 0
            await asyncio.sleep(1.0)
            lists_by_node = {}
            for nid in cluster.addrs:
                reply = await client.admin(nid, "keylists")
                lists_by_node[nid] = reply["lists"]
            verify_history(gen.issues, gen.entries,
                           final_lists=_merged_keylists(lists_by_node))
            await _shutdown_all(client, cluster, list(cluster.addrs))
        finally:
            await client.close()

    try:
        asyncio.run(scenario())
    finally:
        cluster.teardown()


@pytest.mark.slow
def test_crash_one_node_survivors_commit(tmp_path):
    from accord_tpu.serve.loadgen import LoadClient, LoadGen, verify_history

    cluster = _Cluster(3, str(tmp_path))

    async def scenario():
        await cluster.wait_listening()
        client = LoadClient(cluster.addrs)
        await client.connect()
        try:
            gen = LoadGen(client, seed=47, txn_timeout_s=60.0)
            await gen.run_leg(rate_per_s=3, duration_s=4)  # in-band warm
            cluster.kill(3)
            # rf=3 electorate: {1, 2} is still a quorum, so the survivors
            # keep committing (txns sent to the dead node count as lost)
            leg = await gen.run_leg(rate_per_s=15, duration_s=4,
                                    nodes=[1, 2])
            assert leg["ok"] > 0, leg
            assert leg["lost"] == 0, leg
            # the acked history must still linearize; final-state check is
            # skipped (the dead node may hold acked-but-unreplicated reads'
            # context, and survivors converge only after recovery settles)
            verify_history(gen.issues, gen.entries)
            await _shutdown_all(client, cluster, [1, 2])
        finally:
            await client.close()

    try:
        asyncio.run(scenario())
    finally:
        cluster.teardown()
