"""Recovery-path tests: BeginRecovery decisions, invalidation, propagation,
and the chaos burn (drops + partitions) staying strict-serializable.

Mirrors the reference's RecoverTest / burn-with-faults strategy
(SURVEY.md section 4): drive specific partial protocol states through the
simulated network, then let recovery finish or kill the transaction, and
check cluster convergence.
"""
import pytest

from accord_tpu.coordinate.recover import MaybeRecover, Outcome, Recover
from accord_tpu.coordinate.errors import Preempted
from accord_tpu.local.status import Status
from accord_tpu.messages import BeginRecovery, PreAccept, Accept, AcceptOk
from accord_tpu.messages.base import Callback
from accord_tpu.primitives.keyspace import Keys
from accord_tpu.primitives.timestamp import Ballot, TxnKind
from accord_tpu.primitives.txn import Txn
from accord_tpu.sim.burn import run_burn
from accord_tpu.sim.cluster import Cluster, ClusterConfig
from accord_tpu.sim.list_store import ListQuery, ListRead, ListUpdate


class _Sink(Callback):
    def __init__(self):
        self.replies = []
        self.failures = []

    def on_success(self, from_node, reply):
        self.replies.append((from_node, reply))

    def on_failure(self, from_node, failure):
        self.failures.append((from_node, failure))


def _write_txn(keys, value):
    return Txn(TxnKind.WRITE, keys, read=ListRead(keys),
               update=ListUpdate(keys, value), query=ListQuery())


def _cluster(seed=7):
    return Cluster(seed, ClusterConfig(num_nodes=3, rf=3, progress=False))


def _outcome(result):
    assert result.done, "recovery did not complete"
    if result.failure is not None:
        raise result.failure
    return result.value()


def test_recover_preaccepted_completes_fast_path():
    """A txn witnessed everywhere but abandoned pre-Accept: recovery re-proposes
    executeAt=txnId and executes it to completion."""
    cl = _cluster()
    n1 = cl.node(1)
    keys = Keys([100, 40000])
    txn = _write_txn(keys, 1)
    txn_id = n1.next_txn_id(txn.kind, txn.domain)
    route = n1.compute_route(txn)

    sink = _Sink()
    for to in (1, 2, 3):
        n1.send(to, PreAccept(txn_id, txn, route), sink)
    cl.drain()
    assert len(sink.replies) == 3

    result = Recover.recover(cl.node(2), txn_id, txn, route)
    cl.drain()
    assert _outcome(result) == Outcome.APPLIED
    for nid in (1, 2, 3):
        assert cl.stores[nid].data[100] == [*cl.stores[nid].data[100][:0],
                                            cl.stores[nid].data[100][0]]
        assert [v for _, v in cl.stores[nid].data[100]] == [1]
        assert [v for _, v in cl.stores[nid].data[40000]] == [1]


def test_recover_unwitnessed_invalidates():
    """A txn no replica ever saw gets raced to invalidation, and later
    preaccepts for it are refused."""
    cl = _cluster()
    n1 = cl.node(1)
    keys = Keys([123])
    txn = _write_txn(keys, 9)
    txn_id = n1.next_txn_id(txn.kind, txn.domain)
    route = n1.compute_route(txn)

    result = MaybeRecover.probe(cl.node(3), txn_id, keys)
    cl.drain()
    assert _outcome(result) == Outcome.INVALIDATED

    # the original coordinator's late PreAccept must not resurrect it
    sink = _Sink()
    for to in (1, 2, 3):
        n1.send(to, PreAccept(txn_id, txn, route), sink)
    cl.drain()
    assert all([v for _, v in s.data.get(123, [])] == []
               for s in cl.stores.values())


def test_recover_accepted_resumes_proposal():
    """A txn that reached Accept on a quorum resumes from the accepted
    (executeAt, deps) and completes."""
    cl = _cluster()
    n1 = cl.node(1)
    keys = Keys([555])
    txn = _write_txn(keys, 5)
    txn_id = n1.next_txn_id(txn.kind, txn.domain)
    route = n1.compute_route(txn)

    pre = _Sink()
    for to in (1, 2, 3):
        n1.send(to, PreAccept(txn_id, txn, route), pre)
    cl.drain()
    execute_at = max(r.witnessed_at for _, r in pre.replies)
    deps = pre.replies[0][1].deps
    acc = _Sink()
    for to in (1, 2):  # quorum only
        n1.send(to, Accept(txn_id, Ballot.ZERO, route, keys, execute_at, deps), acc)
    cl.drain()
    assert sum(isinstance(r, AcceptOk) for _, r in acc.replies) == 2

    result = Recover.recover(cl.node(3), txn_id, txn, route)
    cl.drain()
    assert _outcome(result) == Outcome.APPLIED
    for nid in (1, 2, 3):
        assert [v for _, v in cl.stores[nid].data[555]] == [5]


def test_recover_applied_txn_propagates():
    """A fully-applied txn being probed just propagates APPLIED."""
    cl = _cluster()
    n1 = cl.node(1)
    keys = Keys([777])
    txn = _write_txn(keys, 3)
    res = n1.coordinate(txn)
    cl.drain()
    assert res.done and res.failure is None

    # any txn id the cluster knows: find it on node 2
    store = next(s for s in cl.node(2).command_stores.all()
                 if s.owns(keys))
    txn_id = next(iter(store.commands))
    probe = MaybeRecover.probe(cl.node(3), txn_id, keys)
    cl.drain()
    assert _outcome(probe) == Outcome.APPLIED


def test_recover_preempted_by_higher_ballot():
    cl = _cluster()
    n1 = cl.node(1)
    keys = Keys([888])
    txn = _write_txn(keys, 8)
    txn_id = n1.next_txn_id(txn.kind, txn.domain)
    route = n1.compute_route(txn)

    high = Ballot(1, 1 << 40, 0, 3)
    sink = _Sink()
    for to in (1, 2, 3):
        n1.send(to, BeginRecovery(txn_id, txn, route, high), sink)
    cl.drain()

    low = Ballot(1, 1, 0, 2)
    result = Recover.recover(cl.node(2), txn_id, txn, route, ballot=low)
    cl.drain()
    assert result.done and isinstance(result.failure, Preempted)


def test_invalidated_stays_dead_under_late_accept():
    """After invalidation commits, a late Accept round must not succeed."""
    cl = _cluster()
    n1 = cl.node(1)
    keys = Keys([999])
    txn = _write_txn(keys, 4)
    txn_id = n1.next_txn_id(txn.kind, txn.domain)
    route = n1.compute_route(txn)

    probe = MaybeRecover.probe(cl.node(2), txn_id, keys)
    cl.drain()
    assert _outcome(probe) == Outcome.INVALIDATED

    acc = _Sink()
    ea = txn_id.as_timestamp()
    for to in (1, 2, 3):
        n1.send(to, Accept(txn_id, Ballot.ZERO, route, keys, ea), acc)
    cl.drain()
    oks = [r for _, r in acc.replies if isinstance(r, AcceptOk)]
    assert len(oks) == 0


def test_recovery_rank_accept_phase_tiebreaks_by_ballot():
    """The Accept phase ranks by ballot first: ACCEPTED_INVALIDATE at a higher
    ballot must outrank ACCEPTED at Ballot.ZERO (ADVICE r1: ranking by raw
    status ordinal resurrects invalidated txns)."""
    from accord_tpu.local.status import recovery_rank
    from accord_tpu.primitives.timestamp import Ballot as B
    hi = B(1, 50, 0, 2)
    assert recovery_rank(Status.ACCEPTED_INVALIDATE, hi) \
        > recovery_rank(Status.ACCEPTED, B.ZERO)
    # same ballot: status ordinal decides within the phase
    assert recovery_rank(Status.ACCEPTED, B.ZERO) \
        > recovery_rank(Status.ACCEPTED_INVALIDATE, B.ZERO)
    # a decided status beats any accept-phase ballot
    assert recovery_rank(Status.COMMITTED, B.ZERO) \
        > recovery_rank(Status.ACCEPTED_INVALIDATE, hi)
    # pre-accept never outranks accept
    assert recovery_rank(Status.PRE_ACCEPTED, hi) \
        < recovery_rank(Status.ACCEPTED_INVALIDATE, B.ZERO)


def test_recover_honours_higher_ballot_accepted_invalidate():
    """Quorum holds ACCEPTED@Ballot.ZERO on one replica and
    ACCEPTED_INVALIDATE@higher on another: recovery must finish the
    invalidation, not re-propose and apply (split decision)."""
    from accord_tpu.messages.recover import AcceptInvalidate
    cl = _cluster()
    n1 = cl.node(1)
    keys = Keys([777])
    txn = _write_txn(keys, 6)
    txn_id = n1.next_txn_id(txn.kind, txn.domain)
    route = n1.compute_route(txn)

    pre = _Sink()
    for to in (1, 2, 3):
        n1.send(to, PreAccept(txn_id, txn, route), pre)
    cl.drain()
    execute_at = max(r.witnessed_at for _, r in pre.replies)
    deps = pre.replies[0][1].deps
    acc = _Sink()
    n1.send(1, Accept(txn_id, Ballot.ZERO, route, keys, execute_at, deps), acc)
    cl.drain()
    assert sum(isinstance(r, AcceptOk) for _, r in acc.replies) == 1

    # a prior recovery proposed invalidation at a higher ballot on 2,3
    b1 = Ballot.from_timestamp(n1.unique_now())
    inv = _Sink()
    for to in (2, 3):
        n1.send(to, AcceptInvalidate(txn_id, b1, route.home_key), inv)
    cl.drain()
    assert len(inv.replies) == 2 and not inv.failures

    result = Recover.recover(cl.node(2), txn_id, txn, route)
    cl.drain()
    assert _outcome(result) == Outcome.INVALIDATED
    for nid in (1, 2, 3):
        assert 777 not in cl.stores[nid].data \
            or [v for _, v in cl.stores[nid].data[777]] == []


def test_multi_store_replica_surfaces_accepted_invalidate():
    """ADVICE r1 #2: a replica whose stores hold ACCEPTED@ZERO (one key's
    store) and ACCEPTED_INVALIDATE@higher (the arbitration key's store) must
    report ACCEPTED_INVALIDATE from BeginRecovery, not mask it."""
    from accord_tpu.messages.recover import AcceptInvalidate, RecoverOk
    cl = _cluster()
    n1 = cl.node(1)
    keys = Keys([100, 40000])  # land in different command stores
    txn = _write_txn(keys, 7)
    txn_id = n1.next_txn_id(txn.kind, txn.domain)
    route = n1.compute_route(txn)

    pre = _Sink()
    for to in (1, 2, 3):
        n1.send(to, PreAccept(txn_id, txn, route), pre)
    cl.drain()
    execute_at = max(r.witnessed_at for _, r in pre.replies)
    deps = pre.replies[0][1].deps
    acc = _Sink()
    n1.send(1, Accept(txn_id, Ballot.ZERO, route, keys, execute_at, deps), acc)
    cl.drain()

    b1 = Ballot.from_timestamp(n1.unique_now())
    inv = _Sink()
    n1.send(1, AcceptInvalidate(txn_id, b1, route.home_key), inv)
    cl.drain()
    assert len(inv.replies) == 1 and not inv.failures

    b2 = Ballot.from_timestamp(n1.unique_now())
    rec = _Sink()
    n1.send(1, BeginRecovery(txn_id, txn, route, b2), rec)
    cl.drain()
    assert len(rec.replies) == 1
    reply = rec.replies[0][1]
    assert isinstance(reply, RecoverOk)
    assert reply.status == Status.ACCEPTED_INVALIDATE
    assert reply.accepted_ballot == b1


def test_blind_invalidate_prepare_leaves_no_stray_accept():
    """The blind-invalidate path must abort on a witness WITHOUT mutating any
    replica's status: a stray ACCEPTED_INVALIDATE left behind by an aborted
    invalidation would outrank the quorum-chosen ACCEPTED@ZERO proposal in a
    later recovery (code-review r2 finding 1)."""
    from accord_tpu.coordinate.recover import propose_invalidate, WitnessedElsewhere
    cl = _cluster()
    n1 = cl.node(1)
    keys = Keys([888])
    txn = _write_txn(keys, 8)
    txn_id = n1.next_txn_id(txn.kind, txn.domain)
    route = n1.compute_route(txn)

    pre = _Sink()
    for to in (1, 2, 3):
        n1.send(to, PreAccept(txn_id, txn, route), pre)
    cl.drain()
    execute_at = max(r.witnessed_at for _, r in pre.replies)
    deps = pre.replies[0][1].deps
    acc = _Sink()
    for to in (1, 2):
        n1.send(to, Accept(txn_id, Ballot.ZERO, route, keys, execute_at, deps), acc)
    cl.drain()
    assert sum(isinstance(r, AcceptOk) for _, r in acc.replies) == 2

    b1 = Ballot.from_timestamp(cl.node(3).unique_now())
    result = propose_invalidate(cl.node(3), txn_id, b1, route.home_key,
                                abort_if_witnessed=True)
    cl.drain()
    assert result.done and isinstance(result.failure, WitnessedElsewhere)
    # no replica's status was demoted by the aborted prepare
    for nid in (1, 2, 3):
        for store in cl.node(nid).command_stores.all():
            cmd = store.command_if_present(txn_id)
            if cmd is not None:
                assert cmd.status != Status.ACCEPTED_INVALIDATE
    # and the txn still recovers to its chosen proposal
    rec = Recover.recover(cl.node(3), txn_id, txn, route)
    cl.drain()
    assert _outcome(rec) == Outcome.APPLIED
    for nid in (1, 2, 3):
        assert [v for _, v in cl.stores[nid].data[888]] == [8]


@pytest.mark.parametrize("seed", [11, 12])
def test_burn_with_drops(seed):
    r = run_burn(seed, ops=200, chaos_drop=0.04)
    assert r.lost == 0


def test_burn_with_partitions():
    r = run_burn(21, ops=200, chaos_drop=0.05, chaos_partitions=True)
    assert r.lost == 0


def test_burn_chaos_deterministic():
    a = run_burn(31, ops=120, chaos_drop=0.05, chaos_partitions=True,
                 collect_log=True)
    b = run_burn(31, ops=120, chaos_drop=0.05, chaos_partitions=True,
                 collect_log=True)
    assert a.log == b.log
