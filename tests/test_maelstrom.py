"""Maelstrom harness tests: the in-process Runner (deterministic random
workload + prefix consistency) and the real stdio executable, single-node
and as a routed 3-process cluster (the shape Maelstrom itself drives)."""
from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from accord_tpu.maelstrom.runner import Runner


def test_runner_random_workload():
    r = Runner(seed=5, num_nodes=3)
    stats = r.run_random_workload(ops=60)
    assert stats["txn_ok"] == 60
    assert stats["errors"] == 0
    assert stats["reads_checked"] > 0
    assert not getattr(r, "log_lines", [])


def test_runner_deterministic():
    a = Runner(seed=11, num_nodes=3).run_random_workload(ops=40)
    b = Runner(seed=11, num_nodes=3).run_random_workload(ops=40)
    assert a == b


_SERVE_SH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "maelstrom", "serve.sh")


class _Proc:
    def __init__(self, node_id: str, router):
        env = dict(os.environ)
        env["PYTHON"] = sys.executable  # pin the test venv's interpreter
        self.node_id = node_id
        # exec the shipped --bin wrapper itself (what `maelstrom test -w
        # txn-list-append --bin maelstrom/serve.sh` would run per node)
        self.proc = subprocess.Popen(
            [_SERVE_SH],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True, env=env)
        self.router = router
        self.thread = threading.Thread(target=self._pump, daemon=True)
        self.thread.start()

    def _pump(self):
        for line in self.proc.stdout:
            line = line.strip()
            if line:
                self.router(json.loads(line))

    def send(self, packet: dict) -> None:
        self.proc.stdin.write(json.dumps(packet) + "\n")
        self.proc.stdin.flush()

    def close(self):
        try:
            self.proc.stdin.close()
            self.proc.wait(timeout=10)
        except Exception:
            self.proc.kill()


@pytest.fixture
def cluster3():
    procs = {}
    replies = []
    lock = threading.Lock()

    def router(packet):
        dest = packet["dest"]
        if dest in procs:
            procs[dest].send(packet)
        else:
            with lock:
                replies.append(packet)

    ids = ["n1", "n2", "n3"]
    for nid in ids:
        procs[nid] = _Proc(nid, router)
    for nid in ids:
        procs[nid].send({"src": "c0", "dest": nid, "body": {
            "type": "init", "msg_id": 0, "node_id": nid, "node_ids": ids}})
    yield procs, replies, lock
    for p in procs.values():
        p.close()


def _await(replies, lock, pred, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        with lock:
            snapshot = list(replies)
        if pred(snapshot):
            return snapshot
        time.sleep(0.05)
    raise AssertionError(f"timeout; got {snapshot}")


def test_stdio_three_node_cluster(cluster3):
    procs, replies, lock = cluster3
    _await(replies, lock,
           lambda rs: sum(1 for r in rs if r["body"]["type"] == "init_ok") == 3)

    # a handful of txns spread across coordinators
    n = 12
    for i in range(n):
        node = f"n{1 + i % 3}"
        ops = [["append", 7, 100 + i], ["r", 7, None]]
        procs[node].send({"src": "c1", "dest": node, "body": {
            "type": "txn", "msg_id": 100 + i, "txn": ops}})
        time.sleep(0.05)

    def all_ok(rs):
        oks = [r for r in rs if r["body"]["type"] == "txn_ok"]
        return len(oks) == n

    rs = _await(replies, lock, all_ok, timeout=60.0)
    # prefix consistency across every observed read of key 7
    observations = []
    for r in rs:
        if r["body"]["type"] != "txn_ok":
            continue
        for op, key, value in r["body"]["txn"]:
            if op == "r":
                observations.append(tuple(value))
    observations.sort(key=len)
    for shorter, longer in zip(observations, observations[1:]):
        assert longer[:len(shorter)] == shorter, (shorter, longer)
    # every append eventually visible: the longest read (which includes the
    # issuing txn's own append) holds a permutation of a subset; the final
    # check is that no value vanished from the longest observation chain
    assert len(observations[-1]) >= n // 2
