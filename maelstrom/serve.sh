#!/usr/bin/env bash
# Maelstrom --bin wrapper for the accord_tpu node (reference:
# accord-maelstrom Main.java:60). Maelstrom execs one copy per node and
# speaks JSON lines over stdin/stdout; logs go to stderr.
#
#   maelstrom test -w txn-list-append --bin "$(pwd)/maelstrom/serve.sh" \
#       --node-count 3 --time-limit 30 --rate 100
#
# The script resolves the repo root from its own location so maelstrom can
# exec it from any working directory.
set -euo pipefail
REPO_ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
export PYTHONPATH="${REPO_ROOT}${PYTHONPATH:+:${PYTHONPATH}}"
# PYTHON override lets test harnesses (and venv users) pin the interpreter
exec "${PYTHON:-python3}" -m accord_tpu.maelstrom "$@"
