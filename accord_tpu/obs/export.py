"""Export FlightRecorder events as Chrome trace_event JSON.

The emitted file loads directly in Perfetto (https://ui.perfetto.dev) or
chrome://tracing: one process per node (pid = node id), one named thread
per track (``txn``, ``net``, ``stage_host``, ``device``, ``deltas``, ...),
txn lifecycle rendered as async spans with flow arrows linking the
coordinator slice to replica status transitions and device dispatches.

Also a tiny CLI::

    python -m accord_tpu.obs.export --summarize trace.json

prints a per-stage time breakdown (span counts, total/mean duration) so a
trace can be read without a UI.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, Iterable, List, Optional, Tuple

# Stable thread ordering inside each node's process row; unknown tracks
# sort after these, alphabetically.
_TRACK_ORDER = ("txn", "net", "stage_host", "device", "exec", "deltas")


def _track_key(tid: str) -> Tuple[int, str]:
    try:
        return (_TRACK_ORDER.index(tid), tid)
    except ValueError:
        return (len(_TRACK_ORDER), tid)


def to_chrome_trace(events: Iterable[dict]) -> dict:
    """Convert recorder events into a ``{"traceEvents": [...]}`` document.

    Recorder events carry string track names in ``tid``; Chrome wants
    integer thread ids, so tracks are numbered per-process (in
    `_TRACK_ORDER`) and named via ``thread_name`` metadata. Events are
    stably sorted by timestamp so per-track ``ts`` is monotone while
    same-ts events keep their recorded order.
    """
    evs = list(events)
    tracks: Dict[Tuple[int, str], int] = {}
    for ev in evs:
        key = (ev["pid"], ev["tid"])
        if key not in tracks:
            tracks[key] = 0  # numbered below, once all tracks are known

    pids = sorted({pid for pid, _ in tracks})
    out: List[dict] = []
    for pid in pids:
        out.append({"ph": "M", "pid": pid, "tid": 0, "name": "process_name",
                    "args": {"name": f"node {pid}"}})
        names = sorted((t for p, t in tracks if p == pid), key=_track_key)
        for i, tname in enumerate(names):
            tracks[(pid, tname)] = i
            out.append({"ph": "M", "pid": pid, "tid": i,
                        "name": "thread_name", "args": {"name": tname}})
            out.append({"ph": "M", "pid": pid, "tid": i,
                        "name": "thread_sort_index",
                        "args": {"sort_index": i}})

    body = []
    for ev in evs:
        ev = dict(ev)
        ev["tid"] = tracks[(ev["pid"], ev.pop("tid"))]
        body.append(ev)
    body.sort(key=lambda e: e["ts"])
    out.extend(body)
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def write_trace(path: str, events: Iterable[dict]) -> dict:
    doc = to_chrome_trace(events)
    with open(path, "w") as f:
        json.dump(doc, f, separators=(",", ":"))
    return doc


# -- summarize ---------------------------------------------------------------

def summarize(doc: dict) -> dict:
    """Per-stage breakdown of a trace document (or raw recorder events).

    Complete (X) spans aggregate by name: count + total/mean wall dur.
    Async (b/e) spans match begin/end the way the trace viewer does --
    global ids by (cat, id), process-local ids (``id2.local``) by
    (pid, cat, id) -- and aggregate the timestamp delta by name.
    Instants aggregate counts only.
    """
    events = doc["traceEvents"] if isinstance(doc, dict) else list(doc)
    spans: Dict[str, Dict[str, float]] = {}
    instants: Dict[str, int] = {}
    open_async: Dict[tuple, float] = {}

    def span(name: str, dur: float) -> None:
        s = spans.setdefault(name, {"count": 0, "total_us": 0.0})
        s["count"] += 1
        s["total_us"] += dur

    def async_key(ev: dict) -> tuple:
        local = ev.get("id2", {}).get("local")
        if local is not None:
            return (ev["pid"], ev.get("cat", ""), str(local), ev["name"])
        return (ev.get("cat", ""), str(ev.get("id")), ev["name"])

    for ev in events:
        ph = ev.get("ph")
        if ph == "X":
            span(ev["name"], float(ev.get("dur", 0)))
        elif ph == "i":
            instants[ev["name"]] = instants.get(ev["name"], 0) + 1
        elif ph == "b":
            open_async[async_key(ev)] = float(ev["ts"])
        elif ph == "e":
            t0 = open_async.pop(async_key(ev), None)
            if t0 is not None:
                span(ev["name"], float(ev["ts"]) - t0)
    for s in spans.values():
        s["mean_us"] = round(s["total_us"] / s["count"], 3) if s["count"] else 0.0
        s["total_us"] = round(s["total_us"], 3)
    return {"spans": spans, "instants": instants,
            "unclosed_async": len(open_async)}


def format_summary(summary: dict) -> str:
    lines = [f"{'span':<24}{'count':>10}{'total_us':>16}{'mean_us':>12}"]
    for name in sorted(summary["spans"],
                       key=lambda n: -summary["spans"][n]["total_us"]):
        s = summary["spans"][name]
        lines.append(f"{name:<24}{s['count']:>10}{s['total_us']:>16.1f}"
                     f"{s['mean_us']:>12.3f}")
    if summary["instants"]:
        lines.append("")
        lines.append(f"{'instant':<24}{'count':>10}")
        for name in sorted(summary["instants"],
                           key=lambda n: -summary["instants"][n]):
            lines.append(f"{name:<24}{summary['instants'][name]:>10}")
    if summary.get("unclosed_async"):
        lines.append("")
        lines.append(f"unclosed async spans: {summary['unclosed_async']}")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m accord_tpu.obs.export",
        description="Summarize a recorded Perfetto trace.")
    ap.add_argument("--summarize", metavar="TRACE_JSON", required=True,
                    help="path to a trace written by bench.py --trace")
    ns = ap.parse_args(argv)
    with open(ns.summarize) as f:
        doc = json.load(f)
    print(format_summary(summarize(doc)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
