"""FlightRecorder: a bounded ring buffer of trace events.

Disabled (the default) every record call is a single attribute check and
an immediate return -- no allocation, no clock read -- so the recorder can
stay compiled into every hot path. Enabled, events append into a deque
ring (oldest dropped beyond `capacity`, counted in `dropped`).

Timestamps are supplied by callers in MICROSECONDS from the owning node's
time service -- deterministic sim time in the simulator, so two same-seed
runs produce byte-identical event streams. Wall-clock durations are
recorded only when `wall=True` (the bench's trace mode); with it off, the
default, spans carry dur=0 and the stream stays replay-identical.

Event vocabulary (Chrome trace_event phases, exported by obs/export.py):
  X  complete span   (host pipeline stages; dur = wall us when enabled)
  i  instant         (messages, status transitions, delta uploads)
  b/e async span     (device in-flight windows keyed by dispatch id;
                      txn lifecycle keyed by TxnId)
  s/t/f flow         (coordinator -> replica -> device dispatch linking)

No recorder call may originate under jax tracing: the append funnel
asserts `jax.core.trace_state_clean()` while recording, so a span
accidentally placed inside a jit-traced function fails loudly at trace
time instead of silently baking one stale event into the compiled
artifact (guard unit-tested in tests/test_obs.py).
"""
from __future__ import annotations

import time
from collections import deque
from typing import Callable, List, Optional

_TXN_CAT = "txn"
_FLOW_CAT = "txnflow"

_jax_clean: Optional[Callable[[], bool]] = None


def _tracing_clean() -> bool:
    """True when NOT under a jax trace (cheap after first call; tolerant
    of jax being absent or the API moving)."""
    global _jax_clean
    if _jax_clean is None:
        try:
            from jax.core import trace_state_clean as fn
        except Exception:  # noqa: BLE001 -- no jax / API drift: no guard
            def fn() -> bool:
                return True
        _jax_clean = fn
    return _jax_clean()


class FlightRecorder:
    __slots__ = ("enabled", "wall", "clock", "dropped", "_buf")

    def __init__(self, capacity: int = 1 << 16):
        self.enabled = False
        # include wall-clock durations/args in events (breaks byte-identical
        # replay of same-seed sim traces; the bench opts in)
        self.wall = False
        # () -> int microseconds, used only by callers with no node in scope
        # (deltas.flush_lane); the sim cluster and maelstrom point it at
        # their deterministic clocks
        self.clock: Optional[Callable[[], int]] = None
        self.dropped = 0
        self._buf: deque = deque(maxlen=capacity)

    # -- lifecycle -----------------------------------------------------------
    def configure(self, capacity: Optional[int] = None,
                  wall: Optional[bool] = None) -> None:
        if capacity is not None and capacity != self._buf.maxlen:
            self._buf = deque(self._buf, maxlen=capacity)
        if wall is not None:
            self.wall = wall

    def clear(self) -> None:
        self._buf.clear()
        self.dropped = 0

    def events(self) -> List[dict]:
        return list(self._buf)

    def __len__(self) -> int:
        return len(self._buf)

    def now_us(self) -> int:
        if self.clock is not None:
            return self.clock()
        return time.monotonic_ns() // 1000

    # -- append funnel -------------------------------------------------------
    def _append(self, ev: dict) -> None:
        if not _tracing_clean():
            raise RuntimeError(
                "FlightRecorder call under jax tracing: recorder calls must "
                f"stay outside jit-traced code (event {ev.get('name')!r})")
        if len(self._buf) == self._buf.maxlen:
            self.dropped += 1
        self._buf.append(ev)

    # -- record API (every method no-ops unless enabled) ---------------------
    def complete(self, pid: int, tid: str, name: str, ts: int,
                 dur: float = 0.0, args: Optional[dict] = None) -> None:
        if not self.enabled:
            return
        ev = {"ph": "X", "pid": pid, "tid": tid, "name": name, "ts": ts,
              "dur": dur if self.wall else 0}
        if args:
            ev["args"] = args
        self._append(ev)

    def instant(self, pid: int, tid: str, name: str, ts: int,
                args: Optional[dict] = None) -> None:
        if not self.enabled:
            return
        ev = {"ph": "i", "pid": pid, "tid": tid, "name": name, "ts": ts,
              "s": "t"}
        if args:
            ev["args"] = args
        self._append(ev)

    def async_begin(self, pid: int, tid: str, name: str, span_id: str,
                    ts: int, cat: str = "device", local: bool = False,
                    args: Optional[dict] = None) -> None:
        """local=True scopes the span id to the process (Chrome id2.local):
        device dispatch ids are per-node counters, so five nodes each
        opening window "d0" must not pair up cross-process. Txn spans stay
        global -- their ids (TxnIds) are cluster-unique and their flows
        deliberately cross processes."""
        if not self.enabled:
            return
        ev = {"ph": "b", "pid": pid, "tid": tid, "name": name, "ts": ts,
              "cat": cat}
        ev.update({"id2": {"local": span_id}} if local else {"id": span_id})
        if args:
            ev["args"] = args
        self._append(ev)

    def async_end(self, pid: int, tid: str, name: str, span_id: str,
                  ts: int, cat: str = "device", local: bool = False,
                  args: Optional[dict] = None) -> None:
        if not self.enabled:
            return
        ev = {"ph": "e", "pid": pid, "tid": tid, "name": name, "ts": ts,
              "cat": cat}
        ev.update({"id2": {"local": span_id}} if local else {"id": span_id})
        if args:
            ev["args"] = args
        self._append(ev)

    def flow(self, pid: int, tid: str, ph: str, flow_id: str,
             ts: int) -> None:
        """One flow step: ph 's' (start), 't' (step), or 'f' (finish),
        binding to the zero-duration slice emitted at the same (track, ts)."""
        if not self.enabled:
            return
        self._append({"ph": ph, "pid": pid, "tid": tid, "name": "txn",
                      "ts": ts, "cat": _FLOW_CAT, "id": flow_id,
                      **({"bp": "e"} if ph == "f" else {})})

    # -- txn lifecycle helpers (coordinator + replica call sites) ------------
    def txn_begin(self, pid: int, txn_id, ts: int,
                  args: Optional[dict] = None) -> None:
        if not self.enabled:
            return
        tid_s = str(txn_id)
        self.async_begin(pid, "txn", "coordinate", tid_s, ts, cat=_TXN_CAT,
                         args=args)
        self.flow(pid, "txn", "s", tid_s, ts)

    def txn_step(self, pid: int, txn_id, name: str, ts: int,
                 args: Optional[dict] = None) -> None:
        """A replica/coordinator status transition: a zero-duration slice
        (so the flow has something to bind to) plus a flow step."""
        if not self.enabled:
            return
        tid_s = str(txn_id)
        ev = {"ph": "X", "pid": pid, "tid": "txn", "name": name, "ts": ts,
              "dur": 0}
        if args:
            ev["args"] = args
        self._append(ev)
        self.flow(pid, "txn", "t", tid_s, ts)

    def txn_end(self, pid: int, txn_id, ts: int,
                args: Optional[dict] = None) -> None:
        if not self.enabled:
            return
        tid_s = str(txn_id)
        self.async_end(pid, "txn", "coordinate", tid_s, ts, cat=_TXN_CAT,
                       args=args)
        self.flow(pid, "txn", "f", tid_s, ts)


# The process-global recorder every instrumentation site checks. Hot paths
# read `REC.enabled` (one attribute load) before doing any work.
REC = FlightRecorder()

# Trace process id for cluster-scoped spans (the ClusterTickEngine's
# per-tick megakernel span): node pids are NodeIds >= 1, so 0 is free.
CLUSTER_PID = 0


def recorder() -> FlightRecorder:
    return REC


def node_pid(node) -> int:
    """Trace process id for a Node: its integer NodeId."""
    return int(getattr(node, "id", 0) or 0)


def node_ts(node) -> int:
    """Deterministic event timestamp for a Node: its time service's
    microsecond clock (sim time under the simulator, so same-seed runs
    emit byte-identical streams)."""
    svc = getattr(node, "time_service", None)
    return svc.now_micros() if svc is not None else REC.now_us()
