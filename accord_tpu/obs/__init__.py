"""Observability: the unified metrics registry and flight recorder.

`obs.metrics` holds the `MetricsRegistry` (named counters / gauges /
timers / log2-bucket histograms) that backs every bench counter in the
resolver, the exec plane, and the maelstrom runner. `obs.trace` is the
ring-buffer `FlightRecorder` threaded through the protocol and device
pipeline; `obs.export` turns its events into Chrome `trace_event` JSON
loadable in Perfetto (`python -m accord_tpu.obs.export --summarize`).
"""
from accord_tpu.obs.metrics import (
    Counter, Gauge, Histogram, MetricsRegistry, RegCounter, RegTimer, Timer,
)
from accord_tpu.obs.trace import REC, FlightRecorder, recorder

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "RegCounter",
    "RegTimer", "Timer", "FlightRecorder", "REC", "recorder",
]
