"""MetricsRegistry: named counters, gauges, timers, log2 histograms.

One registry instance lives wherever counters used to be scattered as
plain attributes (BatchDepsResolver, ExecPlane, Node, the maelstrom
runner). Existing attribute reads and writes (`resolver.dispatches += 1`,
`resolver.host_hidden_s`) keep working through the `RegCounter` /
`RegTimer` descriptors, which proxy class attributes onto the owning
object's `metrics` registry -- so every legacy call site compiles into a
registry update and `registry.snapshot()` is the single source for bench
JSON.

Histograms use log2 buckets: bucket `b` holds values in [2^b, 2^(b+1)).
Percentile estimates take the geometric midpoint of the covering bucket,
clamped to the observed [min, max] -- within a factor of two of the exact
sample percentile by construction (asserted against numpy in
tests/test_obs.py).
"""
from __future__ import annotations

import math
from typing import Dict, Iterator, Optional, Tuple


class Counter:
    """Monotone-in-spirit integer cell (resets to 0 allowed: legacy code
    assigns as well as increments)."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n


class Gauge:
    """Last-value-wins float cell (point-in-time readings)."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = v


class Timer:
    """Accumulated wall seconds (the `*_s` phase counters)."""

    __slots__ = ("name", "total")

    def __init__(self, name: str):
        self.name = name
        self.total = 0.0

    def add(self, dt: float) -> None:
        self.total += dt


class Histogram:
    """Log2-bucket histogram over non-negative samples.

    Bucket index b covers [2^b, 2^(b+1)); zeros land in a dedicated
    bucket. Exact count/sum/min/max ride along, so means are exact and
    percentile estimates are clamped to the observed range."""

    __slots__ = ("name", "buckets", "zeros", "count", "sum", "min", "max")

    def __init__(self, name: str):
        self.name = name
        self.buckets: Dict[int, int] = {}
        self.zeros = 0
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def observe(self, v: float) -> None:
        self.count += 1
        self.sum += v
        if self.min is None or v < self.min:
            self.min = v
        if self.max is None or v > self.max:
            self.max = v
        if v <= 0:
            self.zeros += 1
            return
        b = math.frexp(v)[1] - 1  # v in [2^b, 2^(b+1))
        self.buckets[b] = self.buckets.get(b, 0) + 1

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    def percentile(self, p: float) -> float:
        """Estimated p-th percentile: geometric midpoint of the bucket the
        cumulative count crosses, clamped to [min, max]."""
        if self.count == 0:
            return 0.0
        target = max(1, math.ceil(self.count * p / 100.0))
        cum = self.zeros
        if cum >= target:
            return 0.0
        est = None
        for b in sorted(self.buckets):
            cum += self.buckets[b]
            if cum >= target:
                est = 2.0 ** (b + 0.5)
                break
        if est is None:  # p beyond the last bucket (float dust): take max
            est = self.max
        return min(max(est, self.min), self.max)

    def merge_from(self, other: "Histogram") -> None:
        for b, n in other.buckets.items():
            self.buckets[b] = self.buckets.get(b, 0) + n
        self.zeros += other.zeros
        self.count += other.count
        self.sum += other.sum
        if other.min is not None and (self.min is None or other.min < self.min):
            self.min = other.min
        if other.max is not None and (self.max is None or other.max > self.max):
            self.max = other.max

    def snapshot(self) -> dict:
        return {
            "count": self.count,
            "mean": round(self.mean, 3),
            "p50": round(self.percentile(50), 3),
            "p95": round(self.percentile(95), 3),
            "p99": round(self.percentile(99), 3),
            "p999": round(self.percentile(99.9), 3),
            "max": round(self.max, 3) if self.max is not None else 0.0,
        }


class MetricsRegistry:
    """Named metric cells, created on first touch; kind mismatches raise."""

    __slots__ = ("_metrics",)

    def __init__(self):
        self._metrics: Dict[str, object] = {}

    def _get(self, name: str, kind):
        m = self._metrics.get(name)
        if m is None:
            m = self._metrics[name] = kind(name)
        elif type(m) is not kind:
            raise TypeError(
                f"metric {name!r} is {type(m).__name__}, not {kind.__name__}")
        return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def timer(self, name: str) -> Timer:
        return self._get(name, Timer)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    def names(self) -> Iterator[str]:
        return iter(sorted(self._metrics))

    def merge_from(self, other: "MetricsRegistry") -> None:
        """Fold another registry into this one (cross-node aggregation:
        counters/timers sum, gauges take the other's value, histograms
        merge bucket-wise)."""
        for name in sorted(other._metrics):
            m = other._metrics[name]
            if isinstance(m, Counter):
                self.counter(name).value += m.value
            elif isinstance(m, Timer):
                self.timer(name).total += m.total
            elif isinstance(m, Gauge):
                self.gauge(name).value = m.value
            elif isinstance(m, Histogram):
                self.histogram(name).merge_from(m)

    def snapshot(self) -> dict:
        """Flat name -> value dict (histograms as {count, mean, p50, p95,
        p99, p999, max} sub-dicts) -- the single source for bench JSON."""
        out = {}
        for name in sorted(self._metrics):
            m = self._metrics[name]
            if isinstance(m, Counter):
                out[name] = m.value
            elif isinstance(m, Timer):
                out[name] = m.total
            elif isinstance(m, Gauge):
                out[name] = m.value
            else:
                out[name] = m.snapshot()
        return out

    def snapshot_json(self, extra: Optional[dict] = None) -> str:
        """snapshot() as one sorted JSON line -- the shared export behind
        the serve node's periodic stderr metrics dump and bench_serve's
        per-leg reports (machine-parseable, diff-stable key order)."""
        import json
        snap = self.snapshot()
        if extra:
            snap.update(extra)
        return json.dumps(snap, sort_keys=True)


class RegCounter:
    """Class-level descriptor backing a legacy int attribute with a
    registry Counter on the instance's `metrics` registry: existing
    `self.dispatches += 1` statements and `resolver.dispatches` reads
    compile into registry updates unchanged."""

    __slots__ = ("metric",)

    def __init__(self, metric: str):
        self.metric = metric

    def __get__(self, obj, owner=None):
        if obj is None:
            return self
        return obj.metrics.counter(self.metric).value

    def __set__(self, obj, value) -> None:
        obj.metrics.counter(self.metric).value = value


class RegTimer:
    """RegCounter's float twin, backed by a registry Timer."""

    __slots__ = ("metric",)

    def __init__(self, metric: str):
        self.metric = metric

    def __get__(self, obj, owner=None):
        if obj is None:
            return self
        return obj.metrics.timer(self.metric).total

    def __set__(self, obj, value) -> None:
        obj.metrics.timer(self.metric).total = float(value)


class CounterDict:
    """Dict-like view over a family of registry counters `prefix.key` --
    backs the `upload_bytes_by_field` breakdown dicts so per-field
    accounting lives in the registry while `d[k] += n` / `d.items()` call
    sites keep working."""

    __slots__ = ("registry", "prefix", "_keys")

    def __init__(self, registry: MetricsRegistry, prefix: str,
                 keys: Tuple[str, ...]):
        self.registry = registry
        self.prefix = prefix
        self._keys = tuple(keys)
        for k in self._keys:
            registry.counter(f"{prefix}.{k}")

    def __getitem__(self, k: str) -> int:
        return self.registry.counter(f"{self.prefix}.{k}").value

    def __setitem__(self, k: str, v: int) -> None:
        self.registry.counter(f"{self.prefix}.{k}").value = v

    def get(self, k: str, default=0):
        return self[k] if k in self._keys else default

    def keys(self):
        return list(self._keys)

    def values(self):
        return [self[k] for k in self._keys]

    def items(self):
        return [(k, self[k]) for k in self._keys]

    def __iter__(self):
        return iter(self._keys)

    def __len__(self) -> int:
        return len(self._keys)

    def __contains__(self, k) -> bool:
        return k in self._keys

    def __eq__(self, other) -> bool:
        return dict(self.items()) == other

    def __repr__(self) -> str:
        return repr(dict(self.items()))


# Every metric name the stack registers, with its one-line meaning. The
# README "Observability" glossary documents each of these; a test greps
# README for every name that shows up in a live run's snapshot AND asserts
# each lives here, so the table cannot rot silently.
GLOSSARY: Dict[str, str] = {
    # -- resolver (BatchDepsResolver.metrics) --------------------------------
    "resolver.dispatches": "device deps dispatches launched",
    "resolver.subjects": "deps subjects resolved through the device path",
    "resolver.ticks": "node ticks that produced any items",
    "resolver.preaccept_s": "host preaccept transition wall seconds",
    "resolver.encode_s": "host CSR/upload-array build wall seconds",
    "resolver.dispatch_s": "kernel launch + readback-enqueue wall seconds",
    "resolver.harvest_stall_s": "wall seconds blocked on async transfers",
    "resolver.decode_s": "host-side result materialization wall seconds",
    "resolver.readback_s": "device->host transfer wall seconds",
    "resolver.materialize_s": "decode minus in-decode readback",
    "resolver.host_hidden_s": "host phase seconds run while a call was in flight",
    "resolver.staged_dispatches": "launches taken off the encode-ahead list",
    "resolver.padded_dispatches": "fused calls topped up to pad_store_tiers",
    "resolver.prefetched": "harvests whose transfer the readiness poll drained",
    "resolver.polls_armed": "readiness polls armed (device_poll_ms)",
    "resolver.stale_harvests": "calls translated across a compaction",
    "resolver.host_fallbacks": "stale calls with no pinned snapshot",
    "resolver.range_fallbacks": "subjects demoted host-side (unencodable ranges)",
    "resolver.finalized_decodes": "groups decoded from the device CSR",
    "resolver.legacy_decodes": "groups through the legacy unpackbits decode",
    "resolver.finalize_fallbacks": "finalize guards tripped mid-flight",
    "resolver.outcap_tier_switches": "finalize out-cap tier ladder moves",
    "resolver.bound_readback_s": "device dep-bound scalar readback wall seconds",
    "resolver.range_subject_device_decodes": "range subjects decoded from the device stab",
    "resolver.shard_merge_s": "sharded finalize launch + fragment-merge wall seconds",
    "resolver.window_shrinks": "adaptive window scale-down adjustments",
    "resolver.window_widens": "adaptive window scale-up adjustments",
    # -- resolver device-plane fault handling (ops/fault_plane.py) -----------
    "resolver.device_faults_injected": "injected device faults consumed by the pipeline",
    "resolver.device_retries": "bounded dispatch retries + watchdog probes spent",
    "resolver.device_watchdog_trips": "harvests declared wedged/late by the watchdog",
    "resolver.checksum_mismatches": "corrupted harvests caught by the finalize checksum lane",
    "resolver.degraded_dispatches": "dispatches answered host-side (give-ups + quarantine reroutes)",
    "resolver.quarantine_entries": "node health transitions into QUARANTINED",
    "resolver.quarantine_exits": "probation ladders completed back to HEALTHY",
    "resolver.device_canaries": "probation canary dispatches double-decoded",
    # -- resolver computed gauges (folded into resolver.snapshot()) ----------
    "resolver.host_hidden_pct": "share of host phase time hidden in the device window",
    "resolver.upload_bytes": "bytes shipped host->device by arena scatters",
    "resolver.upload_bytes_full_equiv": "bytes the whole-row scheme would have shipped",
    "resolver.upload_bytes.full": "arena bytes shipped as all-lane rows",
    "resolver.upload_bytes.keys": "arena bytes shipped as key-lane deltas",
    "resolver.upload_bytes.ts": "arena bytes shipped as timestamp-lane deltas",
    "resolver.upload_bytes.valid": "arena bytes shipped as valid-lane deltas",
    "resolver.upload_bytes.kids": "arena bytes shipped to the key-id mask table",
    "resolver.upload_bytes.range_full": "interval-arena bytes shipped as full rows",
    "resolver.upload_bytes.range_valid": "interval-arena bytes shipped as valid deltas",
    # -- exec plane (ExecPlane.metrics / ExecCoordinator.metrics) ------------
    "exec.dispatches": "execution-frontier kernel dispatches",
    "exec.releases": "commands released by a device frontier",
    "exec.harvest_stall_s": "wall seconds blocked on frontier readbacks",
    "exec.prefetched": "frontier readbacks drained early by the poll",
    "exec.upload_bytes": "wait-graph arena bytes shipped host->device",
    "exec.upload_bytes_full_equiv": "whole-row baseline for the same dirty sets",
    "exec.upload_bytes.full": "wait-graph bytes shipped as all-lane rows",
    "exec.upload_bytes.ts": "wait-graph bytes shipped as exec-ts deltas",
    "exec.upload_bytes.flags": "wait-graph bytes shipped as flag deltas",
    "exec.dropped_frontiers": "stale-generation frontiers discarded after arena growth",
    "exec.readback_bytes": "frontier bytes fetched (compact lanes; bitmask only on fallback)",
    "exec.readback_full_equiv": "what the full packed-bitmask fetch would have cost",
    "exec.compact_fallbacks": "checksum-mismatch degradations to the bitmask decode",
    "exec.compact_overflows": "released counts past out_cap (tier bumps, bitmask serves)",
    "exec_coord.dispatches": "fused per-node frontier dispatches",
    "exec_coord.fused_dispatches": "frontier dispatches that fused >1 store",
    "exec_coord.harvest_stall_s": "wall seconds the coordinator blocked on readbacks",
    "exec_coord.prefetched": "coordinator readbacks drained early by the poll",
    "exec_coord.staged_blocks": "exec harvests staged into fused protocol_tick launches",
    "exec_coord.readback_bytes": "coordinator frontier bytes fetched (compact lanes)",
    "exec_coord.readback_full_equiv": "full-bitmask baseline for the coordinator's harvests",
    "exec_coord.compact_fallbacks": "coordinator checksum degradations to the bitmask decode",
    "exec_coord.compact_overflows": "coordinator released counts past out_cap",
    # -- device coordination plane (CmdPlane.metrics) ------------------------
    "cmd_plane_dispatches": "batched cmd_tick kernel dispatches",
    "cmd_plane_upload_bytes": "cmd-arena lane bytes shipped host->device",
    "cmd_fastpath_device_evals": "protocol ops evaluated on-device",
    "cmd_plane_fallbacks": "inadmissible ops replayed by host handlers",
    "cmd_plane_checksum_mismatches": "cmd harvests rejected by the checksum lane",
    "cmd_plane_compactions": "cmd-arena compaction passes (generation bumps)",
    "cmd_plane_flush_s": "dirty-lane scatter upload wall seconds",
    "cmd_deferred_spans": "PreAccept spans decided by the host twin for the fused tick",
    "cmd_deferred_ops": "protocol ops deferred through the host twin (megakernel mode)",
    "cmd_defer_retired": "host-twinned PreAccept spans folded back through the fused repair stage",
    "recovery_scan_dispatches": "device recovery-scan queries issued by the progress sweep",
    "recovery_scan_candidates": "stalled candidate rows returned by verified device scans",
    "recovery_scan_fallbacks": "recovery scans degraded to the host walk (checksum mismatch)",
    "recovery_scan_overflows": "recovery scans whose candidate count overflowed out_cap",
    "recovery_scan_device_s": "wall seconds inside the device recovery query",
    "recovery_scan_host_s": "wall seconds inside the host-twin recovery walk",
    # -- per-node txn lifecycle (Node.metrics) -------------------------------
    "txn.started": "coordinations started on this node",
    "txn.failed": "coordinations failed (timeout/invalidated)",
    "txn.commit_latency_us": "sim-time coordinate-start -> client-result latency",
    "txn.apply_latency_us": "sim-time coordinate-start -> applied-quorum latency",
    # -- maelstrom runner (Runner.metrics) -----------------------------------
    "maelstrom.txn_ok": "maelstrom txns acknowledged ok",
    "maelstrom.errors": "maelstrom txns answered with an error",
    "maelstrom.reads_checked": "read results checked for prefix consistency",
    # -- serving surface (NodeServer.metrics, serve/server.py) ---------------
    "serve.admission_busy": "client txns answered BUSY by the admission governor",
    "serve.admission_shed": "overload episodes shed into the resolver's adaptive window",
    "serve.queue_depth": "high-water coordinations in flight behind admission",
    "serve.transport_bytes_in": "socket-transport bytes received (frames + headers)",
    "serve.transport_bytes_out": "socket-transport bytes sent (frames + headers)",
    "serve.txn_ok": "client txns committed and acknowledged over the socket surface",
    "serve.txn_error": "client txns answered with a protocol error",
    # -- open-loop load harness (serve/loadgen.py, per-leg registry) ---------
    "loadgen.ok": "txns acknowledged ok within the client timeout",
    "loadgen.busy": "txns shed with an explicit BUSY reply",
    "loadgen.errors": "txns answered with an error reply",
    "loadgen.lost": "txns with unknown outcome (timeout or dead connection)",
    "loadgen.latency_us": "client-observed commit latency per acknowledged txn",
    # -- cluster-tick engine (sim/mesh_burn.ClusterTickEngine.snapshot(),
    #    folded into the burn report's counters) ------------------------------
    "node_lane_dispatches": "merged node-lane device dispatches (key + range) across cluster ticks",
    "nodes_per_dispatch": "mean distinct nodes whose plans rode one merged dispatch",
    "node_pad_fraction": "share of merged subject rows that were node-tier padding",
    "mesh_tick_fallbacks": "plans launched per-node because no merge inputs were recorded",
    "megakernel_dispatches": "cluster ticks launched as one fused protocol_tick program",
    "launches_per_tick": "mean device program launches per cluster tick that dispatched",
    "fastpath_quorum_txns": "distinct txns whose PreAccept lanes met the in-kernel fast-path quorum",
    "sharded_megakernel_fallbacks": "megakernel ticks on a mesh that fell back to the unfused sharded pair",
    "exec_scan_blocks": "exec frontier blocks that rode fused protocol_tick launches",
    "exec_flush_ticks": "exec-only fused flush ticks (a staged harvest with no protocol work due)",
    # -- device message plane (sim/network.DeviceMessageNetwork
    #    .message_plane_snapshot(), folded into the burn report's counters) ---
    "device_messages_delivered": "deliveries whose payload came from the device mailbox (verified)",
    "mailbox_verify_fallbacks": "deliveries where device words mismatched and the host copy won",
    "mailbox_early_deliveries": "deliveries due before their payload rode a fused launch",
    "mailbox_depth_high_water": "max occupied slots in any destination mailbox ring",
    "mailbox_overflow_spills": "messages spilled to the host path (ring full or oversize payload)",
    "mailbox_bytes_staged": "payload bytes packed into device emit lanes",
    "mailbox_partition_epochs": "partition-mask uploads (once per link-topology epoch)",
    "message_plane_batches": "host callbacks that drained the parked-message heap",
    "message_plane_fires": "message deliveries fired by those drains",
    "messages_per_host_callback": "mean deliveries collapsed into one host callback (fires/batches)",
}
