"""FetchMaxConflict: quorum-read the max witnessed conflict timestamp.

Role-equivalent to the reference's coordinate/FetchMaxConflict.java:44
(sole production caller: Bootstrap's safe-to-read establishment,
local/Bootstrap.java:239). A quorum per shard guarantees the result is at
or above every timestamp any committed conflicting txn can carry: any
commit quorum intersects ours. The reference additionally chases topology
changes via the replies' latestEpoch; here the caller (bootstrap) already
runs inside an epoch transition and retries wholesale on failure, so the
chase is omitted.
"""
from __future__ import annotations

from typing import Optional

from accord_tpu.coordinate.errors import Timeout
from accord_tpu.coordinate.tracking import QuorumTracker, RequestStatus
from accord_tpu.messages.base import Callback
from accord_tpu.messages.getdeps import GetMaxConflict, MaxConflictOk
from accord_tpu.primitives.keyspace import Seekables
from accord_tpu.primitives.routes import Route
from accord_tpu.primitives.timestamp import Timestamp
from accord_tpu.utils.async_ import AsyncResult


class FetchMaxConflict(Callback):
    def __init__(self, node, seekables: Seekables):
        self.node = node
        self.seekables = seekables
        self.result: AsyncResult = AsyncResult()
        topologies = node.topology_manager.with_unsynced_epochs(
            Route(None, seekables), node.epoch, node.epoch)
        self.tracker = QuorumTracker(topologies, seekables)
        self.max_conflict: Optional[Timestamp] = None

    @classmethod
    def fetch(cls, node, seekables: Seekables) -> AsyncResult:
        """Completes with the max conflict Timestamp (None when no replica
        has witnessed any conflict for the seekables)."""
        self = cls(node, seekables)
        for to in self.tracker.nodes():
            node.send(to, GetMaxConflict(seekables, node.epoch), self)
        return self.result

    def on_success(self, from_node, reply) -> None:
        if self.result.done or not isinstance(reply, MaxConflictOk):
            return
        self.max_conflict = Timestamp.merge_max(self.max_conflict,
                                                reply.max_conflict)
        if self.tracker.on_success(from_node) == RequestStatus.SUCCESS:
            self.result.try_set_success(self.max_conflict)

    def on_failure(self, from_node, failure) -> None:
        if self.result.done:
            return
        if self.tracker.on_failure(from_node) == RequestStatus.FAILED:
            self.result.try_set_failure(
                Timeout(f"fetchMaxConflict {self.seekables!r}"))
