from accord_tpu.coordinate.errors import (
    CoordinationFailed, Timeout, Preempted, Invalidated, Exhausted,
)
from accord_tpu.coordinate.tracking import (
    RequestStatus, QuorumTracker, FastPathTracker, ReadTracker, AppliedTracker,
)
from accord_tpu.coordinate.transaction import CoordinateTransaction

__all__ = [
    "CoordinationFailed", "Timeout", "Preempted", "Invalidated", "Exhausted",
    "RequestStatus", "QuorumTracker", "FastPathTracker", "ReadTracker",
    "AppliedTracker", "CoordinateTransaction",
]
