"""Recovery coordination: Recover, Invalidate, MaybeRecover.

Role-equivalent to the reference's coordinate/Recover.java:80,
Invalidate.java:50 and MaybeRecover.java:39. Any node may recover a stalled
transaction by taking a ballot above every previous round:

  BeginRecovery to all replicas of txnId.epoch -> RecoveryTracker quorum ->
    most advanced known state decides where to resume:
      INVALIDATED            -> broadcast CommitInvalidate
      >= STABLE              -> re-execute at the decided (executeAt, deps)
      COMMITTED/PRE_COMMITTED-> stabilise+execute with committed deps
                                (CollectDeps for shards lacking coverage)
      ACCEPTED               -> re-propose the accepted (executeAt, proposal)
      ACCEPTED_INVALIDATE    -> finish the invalidation
      all <= PRE_ACCEPTED    -> the whitepaper's fast-path reasoning:
          if the tracker or any replica proves the fast path impossible
            -> invalidate
          else if earlier-accepted-no-witness txns exist -> await their
            commit, then retry (they could still commit without witnessing
            us, which would flip the decision)
          else -> propose executeAt = txnId (the fast path decision the
            original coordinator would have taken)
"""
from __future__ import annotations

import enum
from typing import Dict, List, Optional, Tuple

from accord_tpu.coordinate.errors import Exhausted, Invalidated, Preempted, Timeout
from accord_tpu.coordinate.tracking import QuorumTracker, RecoveryTracker, RequestStatus
from accord_tpu.local.status import Status, recovery_rank
from accord_tpu.messages.base import Callback
from accord_tpu.messages.recover import (
    AcceptInvalidate, BeginInvalidation, BeginRecovery, CheckStatus,
    CheckStatusOk, CommitInvalidate, DepsTier, InvalidateNack, InvalidateOk,
    RecoverNack, RecoverOk, WaitOnCommit, WaitOnCommitOk,
)
from accord_tpu.primitives.deps import Deps
from accord_tpu.primitives.keyspace import Keys, Ranges, Seekables
from accord_tpu.primitives.routes import Route
from accord_tpu.primitives.timestamp import Ballot, Timestamp, TxnId
from accord_tpu.primitives.txn import Txn
from accord_tpu.utils.async_ import AsyncResult
from accord_tpu.utils.invariants import Invariants


class Outcome(enum.Enum):
    """What recovery concluded (reference: ProgressToken)."""
    APPLIED = "applied"
    INVALIDATED = "invalidated"
    TRUNCATED = "truncated"  # produced once durability rounds + truncation land


class Recover(Callback):
    def __init__(self, node, txn_id: TxnId, txn: Txn, route: Route,
                 ballot: Ballot):
        self.node = node
        self.txn_id = txn_id
        self.txn = txn
        self.route = route
        self.ballot = ballot
        self.result: AsyncResult = AsyncResult()
        self.topologies = node.topology_manager.with_unsynced_epochs(
            route, txn_id.epoch, txn_id.epoch)
        # a retired txn epoch (below the universal durability floor) is
        # answered by the oldest retained topology (see
        # TopologyManager.retire_below); replies resolve TRUNCATED
        self.topology = self.topologies.for_epoch(
            max(txn_id.epoch, self.topologies.oldest_epoch()))
        self.tracker = RecoveryTracker(self.topologies, txn.keys)
        self.oks: Dict[int, RecoverOk] = {}
        self._decided = False

    @classmethod
    def recover(cls, node, txn_id: TxnId, txn: Txn, route: Route,
                ballot: Optional[Ballot] = None) -> AsyncResult:
        if ballot is None:
            ballot = Ballot.from_timestamp(node.unique_now())
        self = cls(node, txn_id, txn, route, ballot)
        node.events.on_recover(txn_id)
        for to in self.tracker.nodes():
            node.send(to, BeginRecovery(txn_id, txn, route, ballot), self)
        return self.result

    # -- BeginRecovery round -------------------------------------------------
    def on_success(self, from_node, reply) -> None:
        if self._decided or self.result.done:
            return
        if isinstance(reply, RecoverNack):
            self.node.events.on_preempted(self.txn_id)
            self.result.try_set_failure(Preempted(
                f"recovery of {self.txn_id} superseded by {reply.superseded_by}"))
            return
        assert isinstance(reply, RecoverOk)
        self.oks[from_node] = reply
        if self.tracker.on_success(from_node, reply.is_fast_path_vote) \
                == RequestStatus.SUCCESS:
            self._recover()

    def on_failure(self, from_node, failure) -> None:
        if self._decided or self.result.done:
            return
        if self.tracker.on_failure(from_node) == RequestStatus.FAILED:
            self.result.try_set_failure(Timeout(f"recover {self.txn_id}"))

    # -- the decision (reference: Recover.recover, coordinate/Recover.java:246)
    def _recover(self) -> None:
        self._decided = True
        # prefer informative replies; conclude TRUNCATED only when NO reply
        # anywhere has surviving knowledge (reference: Recover.java:252-254,
        # maxAcceptedNotTruncated): truncation implies the outcome was
        # majority-durable, so a MaybeRecover/CheckStatus pass will repair
        # local state from whatever replicas still carry it.
        oks = [ok for ok in self.oks.values() if ok.status != Status.TRUNCATED]
        if not oks:
            self.result.try_set_success(Outcome.TRUNCATED)
            return
        best = max(oks, key=lambda ok: recovery_rank(ok.status, ok.accepted_ballot))
        status = best.status

        if status == Status.INVALIDATED:
            self._commit_invalidate()
            return
        if status.has_been(Status.STABLE):
            self._with_committed_deps(
                best.execute_at,
                lambda deps: self._resume("execute", best.execute_at, deps))
            return
        if status in (Status.COMMITTED, Status.PRE_COMMITTED):
            self._with_committed_deps(
                best.execute_at,
                lambda deps: self._resume("execute", best.execute_at, deps))
            return
        if status == Status.ACCEPTED:
            deps = self._merge_proposal()
            self._resume("propose", best.execute_at, deps)
            return
        if status == Status.ACCEPTED_INVALIDATE:
            self._invalidate()
            return

        # nothing beyond PreAccepted anywhere: fast-path reasoning. A
        # REJECTED witness (sync-point floor / expiry) also forces
        # invalidation -- proposing would commit behind the floor.
        if self.tracker.rejects_fast_path() \
                or any(ok.rejects_fast_path for ok in oks) \
                or any(ok.execute_at is not None and ok.execute_at.is_rejected
                       for ok in oks):
            self._invalidate()
            return
        eanw = Deps.merge([ok.earlier_accepted_no_witness for ok in oks])
        ecw = Deps.merge([ok.earlier_committed_witness for ok in oks])
        eanw = eanw.without(ecw.contains)
        if not eanw.is_empty():
            self._await_commits(eanw)
            return
        deps = self._merge_proposal()
        self._resume("propose", self.txn_id.as_timestamp(), deps)

    # -- deps reconstruction (reference: LatestDeps merge semantics) ---------
    # Entries arrive per STORE (sub-shard granularity), so the merge must
    # resolve the best (tier, ballot) per atomic covering fragment -- taking
    # the max at whole-shard granularity would silently drop deps for the
    # store slices the winning entry does not cover.
    def _entries_for_shard(self, shard) -> List:
        window = Ranges([shard.range])
        out = []
        for node_id in shard.nodes:
            ok = self.oks.get(node_id)
            if ok is None:
                continue
            for e in ok.deps_entries:
                if e.covering.intersects(window):
                    out.append(e)
        return out

    def _merge_latest(self, entries, window: Ranges,
                      tier_floor: Optional[DepsTier] = None) -> Tuple[Deps, List[Ranges]]:
        """Resolve per atomic fragment of `window`: among entries covering the
        fragment (at/above tier_floor if given), the max (tier, ballot)
        entries win and their slices union. Returns (deps, fragments with no
        eligible entry)."""
        out = Deps.NONE
        missing: List[Ranges] = []
        for atom in _atoms(window, [e.covering for e in entries]):
            cand = [e for e in entries if e.covering.intersects(atom)]
            if tier_floor is not None:
                cand = [e for e in cand if e.tier >= tier_floor]
            if not cand:
                missing.append(atom)
                continue
            top = max((e.tier, e.ballot) for e in cand)
            parts = [e.deps.slice(atom) for e in cand if (e.tier, e.ballot) == top]
            out = out.union(Deps.merge(parts))
        return out, missing

    def _merge_proposal(self) -> Deps:
        """Best-known deps: highest (tier, ballot) per fragment
        (reference: LatestDeps.mergeProposal)."""
        out = Deps.NONE
        for shard in self.topology.shards_for(self.txn.keys):
            deps, _ = self._merge_latest(self._entries_for_shard(shard),
                                         Ranges([shard.range]))
            out = out.union(deps)
        return out

    def _with_committed_deps(self, execute_at: Timestamp, then) -> None:
        """Union of committed-tier deps, topping up fragments without
        committed coverage via a fresh GetDeps round at executeAt (reference:
        Recover.withCommittedDeps + CollectDeps.java:39)."""
        out = Deps.NONE
        missing: List[Ranges] = []
        for shard in self.topology.shards_for(self.txn.keys):
            deps, miss = self._merge_latest(self._entries_for_shard(shard),
                                            Ranges([shard.range]),
                                            tier_floor=DepsTier.COMMITTED)
            out = out.union(deps)
            missing.extend(miss)
        if not missing:
            then(out)
            return
        window = Ranges.EMPTY
        for m in missing:
            window = window.union(m)
        keys = _slice_seekables(self.txn.keys, window)
        if keys.is_empty():
            then(out)
            return
        CollectDeps.collect(self.node, self.txn_id, keys, execute_at) \
            .on_success(lambda extra: then(out.union(extra))) \
            .on_failure(self.result.try_set_failure)

    # -- resumption via the standard coordination rounds ---------------------
    def _resume(self, phase: str, execute_at: Timestamp, deps: Deps) -> None:
        from accord_tpu.coordinate.transaction import CoordinateTransaction
        CoordinateTransaction.resume(
            self.node, self.txn_id, self.txn, self.route, self.ballot,
            phase, execute_at, deps,
        ).on_success(lambda _: self.result.try_set_success(Outcome.APPLIED)) \
         .on_failure(self.result.try_set_failure)

    # -- invalidation --------------------------------------------------------
    def _invalidate(self) -> None:
        propose_invalidate(self.node, self.txn_id, self.ballot,
                           self.route.home_key) \
            .on_success(lambda _: self._commit_invalidate()) \
            .on_failure(self.result.try_set_failure)

    def _commit_invalidate(self) -> None:
        participants = self.route.participants
        for to in self.topology.nodes():
            self.node.send(to, CommitInvalidate(self.txn_id, participants))
        self.node.events.on_invalidated(self.txn_id)
        self.result.try_set_success(Outcome.INVALIDATED)

    # -- earlier-accepted-no-witness wait (reference: Recover.AwaitCommit) ---
    def _await_commits(self, waiting_on: Deps) -> None:
        ids = waiting_on.all_txn_ids()
        state = {"remaining": len(ids)}

        def one_done(_):
            state["remaining"] -= 1
            if state["remaining"] == 0:
                self._retry()

        for dep_id in ids:
            keys = waiting_on.participants_of(dep_id) or self.txn.keys
            AwaitCommit.start(self.node, dep_id, keys) \
                .on_success(one_done) \
                .on_failure(self.result.try_set_failure)

    def _retry(self) -> None:
        if self.result.done:
            return
        Recover.recover(self.node, self.txn_id, self.txn, self.route,
                        self.ballot) \
            .add_callback(lambda v, f: self.result.try_set_failure(f)
                          if f is not None else self.result.try_set_success(v))


def _atoms(window: Ranges, coverings: List[Ranges]) -> List[Ranges]:
    """Split `window` at every covering boundary into atomic fragments, each
    returned as a single-range Ranges (no entry's covering partially overlaps
    an atom)."""
    out: List[Ranges] = []
    from accord_tpu.primitives.keyspace import Range
    for w in window:
        pts = {w.start, w.end}
        for rngs in coverings:
            for r in rngs:
                if r.start > w.start and r.start < w.end:
                    pts.add(r.start)
                if r.end > w.start and r.end < w.end:
                    pts.add(r.end)
        bounds = sorted(pts)
        for i in range(len(bounds) - 1):
            out.append(Ranges([Range(bounds[i], bounds[i + 1])]))
    return out


def _slice_seekables(seekables: Seekables, window: Ranges) -> Seekables:
    return seekables.slice(window)


class CollectDeps(Callback):
    """Quorum GetDeps round (reference: coordinate/CollectDeps.java:39)."""

    def __init__(self, node, txn_id: TxnId, keys: Seekables, before: Timestamp):
        self.node = node
        self.txn_id = txn_id
        self.result: AsyncResult = AsyncResult()
        topologies = node.topology_manager.with_unsynced_epochs(
            Route(None, keys), txn_id.epoch, txn_id.epoch)
        self.tracker = QuorumTracker(topologies, keys)
        self.keys = keys
        self.before = before
        self.deps = Deps.NONE

    @classmethod
    def collect(cls, node, txn_id: TxnId, keys: Seekables,
                before: Timestamp) -> AsyncResult:
        from accord_tpu.messages.getdeps import GetDeps
        self = cls(node, txn_id, keys, before)
        for to in self.tracker.nodes():
            node.send(to, GetDeps(txn_id, keys, before), self)
        return self.result

    def on_success(self, from_node, reply) -> None:
        if self.result.done:
            return
        self.deps = self.deps.union(reply.deps)
        if self.tracker.on_success(from_node) == RequestStatus.SUCCESS:
            self.result.try_set_success(self.deps)

    def on_failure(self, from_node, failure) -> None:
        if self.result.done:
            return
        if self.tracker.on_failure(from_node) == RequestStatus.FAILED:
            self.result.try_set_failure(Timeout(f"collectDeps {self.txn_id}"))


class AwaitCommit(Callback):
    """Quorum WaitOnCommit for one txn (reference: Recover.AwaitCommit)."""

    def __init__(self, node, txn_id: TxnId, participants: Seekables):
        self.result = AsyncResult()
        topologies = node.topology_manager.with_unsynced_epochs(
            Route(None, participants), txn_id.epoch, txn_id.epoch)
        self.tracker = QuorumTracker(topologies, participants)
        self.txn_id = txn_id

    @classmethod
    def start(cls, node, txn_id: TxnId, participants: Seekables) -> AsyncResult:
        self = cls(node, txn_id, participants)
        for to in self.tracker.nodes():
            node.send(to, WaitOnCommit(txn_id, participants), self)
        return self.result

    def on_success(self, from_node, reply) -> None:
        if self.tracker.on_success(from_node) == RequestStatus.SUCCESS:
            self.result.try_set_success(None)

    def on_failure(self, from_node, failure) -> None:
        if self.tracker.on_failure(from_node) == RequestStatus.FAILED:
            self.result.try_set_failure(Timeout(f"awaitCommit {self.txn_id}"))


class WitnessedElsewhere(RuntimeError):
    """An invalidation attempt found the txn witnessed: recover it instead
    (reference: Invalidate.java switches to RecoverWithRoute)."""

    def __init__(self, txn_id: TxnId, status: Status, route: Optional[Route]):
        super().__init__(f"{txn_id} witnessed at {status.name}")
        self.status = status
        self.route = route


def propose_invalidate(node, txn_id: TxnId, ballot: Ballot, key,
                       abort_if_witnessed: bool = False) -> AsyncResult:
    """Ballot-accept invalidation on the quorum of one shard (reference:
    Propose.Invalidate.proposeInvalidate): that shard's quorum participates in
    any commit of txn_id, so a promised invalidation there blocks them all.

    The accept is only safe once `ballot` has been PREPARED on a quorum of
    the shard. With abort_if_witnessed (the blind Invalidate path, where no
    BeginRecovery round preceded us) we run that prepare here as a
    BeginInvalidation round: replicas promise the ballot *without* mutating
    status, and ANY witness aborts with WitnessedElsewhere before a single
    ACCEPTED_INVALIDATE is written — the txn's coordinator may still be
    concurrently committing its proposal, and only a full BeginRecovery round
    can reason about that safely. Without abort_if_witnessed the caller is
    the recovery coordinator, whose BeginRecovery quorum at this same ballot
    already served as the prepare."""
    topology = node.topology_manager.for_epoch(txn_id.epoch)
    shard = topology.shard_for_key(key)
    result = AsyncResult()

    def make_tracker() -> QuorumTracker:
        return QuorumTracker(
            node.topology_manager.with_unsynced_epochs(
                Route(key, Keys([key])), txn_id.epoch, txn_id.epoch),
            Keys([key]))

    def accept_round() -> None:
        tracker = make_tracker()

        class AcceptCb(Callback):
            def on_success(self, from_node, reply) -> None:
                if result.done:
                    return
                if isinstance(reply, InvalidateNack):
                    result.try_set_failure(Preempted(
                        f"invalidate {txn_id} superseded by {reply.promised}"))
                    return
                if tracker.on_success(from_node) == RequestStatus.SUCCESS:
                    result.try_set_success(None)

            def on_failure(self, from_node, failure) -> None:
                if tracker.on_failure(from_node) == RequestStatus.FAILED:
                    result.try_set_failure(Timeout(f"invalidate {txn_id}"))

        cb = AcceptCb()
        for to in shard.nodes:
            node.send(to, AcceptInvalidate(txn_id, ballot, key), cb)

    if not abort_if_witnessed:
        accept_round()
        return result

    from accord_tpu.coordinate.tracking import InvalidationTracker
    prepare_tracker = InvalidationTracker(
        node.topology_manager.with_unsynced_epochs(
            Route(key, Keys([key])), txn_id.epoch, txn_id.epoch),
        Keys([key]), txn_id.epoch)

    class PrepareCb(Callback):
        # Invalidation is a NEGATIVE decision: like MaybeRecover, wait for
        # every reachable reply before acting, because (a) a bare quorum can
        # simply have missed the one witness a straggler would report, and
        # (b) dispatching the accept round while prepare replies are still in
        # flight races a late WitnessedElsewhere abort against an accepted
        # invalidation quorum — the caller would be told "recover instead"
        # after we wrote the very state that makes recovery finish the kill.
        def __init__(self):
            self.answered = 0
            self.quorum = False
            self.witnesses: list = []          # (node, status, route)

        def on_success(self, from_node, reply) -> None:
            if result.done:
                return
            self.answered += 1
            if isinstance(reply, InvalidateNack):
                result.try_set_failure(Preempted(
                    f"invalidate {txn_id} superseded by {reply.promised}"))
                return
            if reply.status.is_decided and not reply.status.is_terminal:
                # the txn got committed while we tried to invalidate it
                result.try_set_failure(Preempted(
                    f"invalidate {txn_id}: already decided ({reply.status.name})"))
                return
            if reply.status == Status.ACCEPTED:
                # an accepted slow-path proposal exists: recovery must resume
                # it, not kill it
                result.try_set_failure(
                    WitnessedElsewhere(txn_id, reply.status, reply.route))
                return
            if reply.status.has_been(Status.PRE_ACCEPTED) \
                    and reply.status != Status.ACCEPTED_INVALIDATE \
                    and not reply.status.is_terminal:
                # witnessed but undecided: defer the verdict to the
                # electorate analysis once everyone reachable has answered
                self.witnesses.append(
                    (from_node, reply.status, reply.route))
            if prepare_tracker.on_success(from_node, reply.fast_path_vote) \
                    == RequestStatus.SUCCESS:
                self.quorum = True
            self._maybe_dispatch()

        def on_failure(self, from_node, failure) -> None:
            if result.done:
                return
            self.answered += 1
            if prepare_tracker.on_failure(from_node) == RequestStatus.FAILED:
                result.try_set_failure(Timeout(f"invalidate {txn_id}"))
                return
            self._maybe_dispatch()

        def _maybe_dispatch(self) -> None:
            if self.answered < len(shard.nodes) or not self.quorum:
                return
            if self.witnesses \
                    and not prepare_tracker.is_fast_path_rejected():
                # Witnessed-but-undecided replies force recovery unless the
                # fast path is decisively dead (reference: Invalidate.java:161
                # isSafeToInvalidate via InvalidationTracker): our promises
                # block any FUTURE ballot-0 vote (preaccept is ballot-gated),
                # so the only possible fast voters are those who already
                # voted plus electorate members we could not reach; the slow
                # path is blocked by quorum intersection with our promises.
                _, status, route = max(self.witnesses, key=lambda w: w[1])
                if route is None:
                    route = next((r for _, _, r in self.witnesses
                                  if r is not None), None)
                result.try_set_failure(
                    WitnessedElsewhere(txn_id, status, route))
                return
            accept_round()

    prep = PrepareCb()
    for to in shard.nodes:
        node.send(to, BeginInvalidation(txn_id, ballot, key), prep)
    return result


def invalidate_unwitnessed(node, txn_id: TxnId, participants: Seekables) -> AsyncResult:
    """Invalidate a txn known only by id (no definition/route reachable) --
    reference: Invalidate.java:50. Uses any key it was seen under. If a
    witness surfaces, falls back to probing (and hence recovering) it."""
    ballot = Ballot.from_timestamp(node.unique_now())
    some_key = next(iter(participants)) if isinstance(participants, Keys) \
        else participants[0].start
    result = AsyncResult()

    def committed(_):
        topology = node.topology_manager.for_epoch(txn_id.epoch)
        for to in topology.nodes():
            node.send(to, CommitInvalidate(txn_id, participants))
        result.try_set_success(Outcome.INVALIDATED)

    def failed(failure):
        if isinstance(failure, WitnessedElsewhere):
            # re-probe WITHOUT permission to invalidate again: breaks the
            # probe->invalidate->probe mutual recursion; the progress engine
            # retries from scratch later if this pass can't resolve it
            scope = failure.route.participants if failure.route is not None \
                else participants
            MaybeRecover.probe(node, txn_id, scope, allow_invalidate=False) \
                .add_callback(
                    lambda v, f: result.try_set_failure(f) if f is not None
                    else result.try_set_success(v))
        else:
            result.try_set_failure(failure)

    propose_invalidate(node, txn_id, ballot, some_key,
                       abort_if_witnessed=True) \
        .on_success(committed) \
        .on_failure(failed)
    return result


# ---------------------------------------------------------------------------
# MaybeRecover: probe, repair locally, or escalate
# ---------------------------------------------------------------------------

class MaybeRecover(Callback):
    """CheckStatus probe for a stalled txn; apply anything learned locally
    (the reference's Propagate), else escalate to full Recover/Invalidate
    (reference: MaybeRecover.java:39, RecoverWithRoute.java:57).

    Positive knowledge (an outcome, an invalidation) acts as soon as it
    arrives with a quorum; NEGATIVE decisions (recover from scratch,
    invalidate an apparently-unwitnessed txn) wait for every reachable reply,
    because a bare quorum can simply have missed the one witness."""

    def __init__(self, node, txn_id: TxnId, participants: Seekables,
                 allow_invalidate: bool, token_sink=None):
        self.node = node
        self.txn_id = txn_id
        self.participants = participants
        self.allow_invalidate = allow_invalidate
        # observer of the merged ProgressToken (reference: MaybeRecover
        # completes with a ProgressToken; the progress engine compares
        # successive tokens to detect remote movement)
        self.token_sink = token_sink
        self.result: AsyncResult = AsyncResult()
        self.topologies = node.topology_manager.with_unsynced_epochs(
            Route(None, participants), txn_id.epoch, txn_id.epoch)
        self.tracker = QuorumTracker(self.topologies, participants)
        self.oks: List[CheckStatusOk] = []
        self.contacted = 0
        self.answered = 0
        self._acted = False

    @classmethod
    def probe(cls, node, txn_id: TxnId, participants: Seekables,
              allow_invalidate: bool = True, token_sink=None) -> AsyncResult:
        self = cls(node, txn_id, participants, allow_invalidate, token_sink)
        targets = self.tracker.nodes()
        self.contacted = len(targets)
        for to in targets:
            node.send(to, CheckStatus(txn_id, participants), self)
        return self.result

    def on_success(self, from_node, reply) -> None:
        if self._acted:
            return
        self.oks.append(reply)
        self.answered += 1
        self.tracker.on_success(from_node)
        self._maybe_act()

    def on_failure(self, from_node, failure) -> None:
        if self._acted:
            return
        self.answered += 1
        if self.tracker.on_failure(from_node) == RequestStatus.FAILED:
            self._acted = True
            self.result.try_set_failure(Timeout(f"checkStatus {self.txn_id}"))
            return
        self._maybe_act()

    def _merged(self) -> CheckStatusOk:
        merged = self.oks[0]
        for ok in self.oks[1:]:
            merged = CheckStatusOk.merge(merged, ok)
        return merged

    def _maybe_act(self) -> None:
        if not self.oks:
            if self.answered >= self.contacted:
                self._acted = True
                self.result.try_set_failure(Timeout(f"checkStatus {self.txn_id}"))
            return
        merged = self._merged()
        if self.token_sink is not None:
            self.token_sink(merged.to_progress_token())
        have_quorum = self.tracker.decided == RequestStatus.SUCCESS
        all_in = self.answered >= self.contacted

        # positive knowledge: act as soon as it is quorum-confirmed reachable
        if have_quorum and merged.status == Status.INVALIDATED:
            self._acted = True
            self._propagate_invalidate(merged)
            return
        if have_quorum and merged.status == Status.TRUNCATED:
            # someone truncated the record: the outcome was durable. Apply it
            # if the MERGED knowledge still carries it (a node-local merge
            # can collapse an outcome-carrying store with a truncated sibling
            # to status TRUNCATED while keeping txn/writes/executeAt);
            # otherwise mark local records truncated so dependents stop
            # waiting (reference: Infer/Cleanup propagation of truncation)
            self._acted = True
            if merged.known_outcome:
                self._propagate_outcome(merged)
            else:
                self._propagate_truncated(merged)
            return
        if have_quorum and merged.status.has_been(Status.PRE_APPLIED) \
                and not merged.status.is_terminal:
            self._acted = True
            self._propagate_outcome(merged)
            return
        if not all_in:
            return  # wait for the stragglers before a negative decision
        if not have_quorum:
            self._acted = True
            self.result.try_set_failure(Timeout(f"checkStatus {self.txn_id}"))
            return
        self._acted = True
        if merged.status.has_been(Status.PRE_APPLIED) \
                and not merged.status.is_terminal:
            self._propagate_outcome(merged)
            return
        if merged.status == Status.INVALIDATED:
            self._propagate_invalidate(merged)
            return
        if merged.known_definition:
            txn = merged.partial_txn.reconstitute()
            Recover.recover(self.node, self.txn_id, txn, merged.route) \
                .add_callback(self._finish)
            return
        if merged.route is not None \
                and not merged.route.covering().contains_ranges(
                    self.participants.to_ranges()) \
                and not self.participants.to_ranges().contains_ranges(
                    merged.route.participants.to_ranges()):
            # learn the full participant set, then retry with the full route
            # -- but ONLY if the route actually adds participants we have not
            # probed, else this recurses on itself forever (a partially-known
            # definition can leave route.covering() narrower than the
            # participants that witnessed it)
            MaybeRecover.probe(self.node, self.txn_id,
                               merged.route.participants,
                               self.allow_invalidate) \
                .add_callback(self._finish)
            return
        if not self.allow_invalidate:
            self.result.try_set_failure(Exhausted(
                f"probe {self.txn_id}: witnessed but unrecoverable yet"))
            return
        # no replica knows the definition: race to invalidate it
        invalidate_unwitnessed(self.node, self.txn_id, self.participants) \
            .add_callback(self._finish)

    def _finish(self, value, failure) -> None:
        if failure is not None:
            self.result.try_set_failure(failure)
        else:
            if value is Outcome.TRUNCATED:
                # a full Recover concluded every reachable replica truncated
                # the record (outcome universally durable + erased): mark our
                # local records truncated too, so dependents drop their wait
                # edges instead of probing forever
                from accord_tpu.messages.propagate import Propagate
                self.node.receive_local(Propagate(
                    Propagate.TRUNCATE, self.txn_id, self.participants))
            self.result.try_set_success(value)

    # -- Propagate (messages/propagate.py; reference: Propagate.java:64).
    # Local application is a journaled LocalRequest: state repaired by a
    # probe must survive a restart's journal replay.
    def _propagate_invalidate(self, merged: Optional[CheckStatusOk] = None) -> None:
        from accord_tpu.messages.propagate import Propagate
        self.node.receive_local(Propagate(
            Propagate.INVALIDATE, self.txn_id, self.participants, merged))
        self.result.try_set_success(Outcome.INVALIDATED)

    def _inform_home_durable(self, merged: CheckStatusOk) -> None:
        """The probe discovered a durable outcome: forward that knowledge to
        the home shard so its engine stops recovery-driving (reference:
        MaybeRecover.java:109 sends InformDurable to the home shard nodes)."""
        from accord_tpu.local.status import Durability
        from accord_tpu.messages.inform import InformHomeDurable
        if merged.route is None or merged.durability < Durability.MAJORITY:
            return
        try:
            shard = self.node.topology_manager.current().shard_for_key(
                merged.route.home_key)
        except Exception:
            return  # home range not in the current topology view
        for to in shard.nodes:
            if to != self.node.id:
                self.node.counters["informs_home_durable_sent"] += 1
                self.node.send(to, InformHomeDurable(
                    self.txn_id, merged.route, merged.execute_at,
                    merged.durability))

    def _propagate_truncated(self, merged: CheckStatusOk) -> None:
        from accord_tpu.messages.propagate import Propagate
        self.node.receive_local(Propagate(
            Propagate.TRUNCATE, self.txn_id, self.participants, merged))
        self._inform_home_durable(merged)
        self.result.try_set_success(Outcome.TRUNCATED)

    def _propagate_outcome(self, merged: CheckStatusOk) -> None:
        """Apply a remotely-known outcome to our local stores; if no merged
        reply covers our slices, fall back to a full Recover (re-executes)."""
        from accord_tpu.messages.propagate import Propagate, covering_stores
        self._inform_home_durable(merged)
        if covering_stores(self.node, self.txn_id, self.participants, merged):
            self.node.receive_local(Propagate(
                Propagate.OUTCOME, self.txn_id, self.participants, merged))
            self.result.try_set_success(Outcome.APPLIED)
        else:
            # outcome exists but no reply covers us: recover (re-executes)
            if merged.route is not None and merged.partial_txn is not None \
                    and merged.partial_txn.covers(merged.route.covering()):
                Recover.recover(self.node, self.txn_id,
                                merged.partial_txn.reconstitute(), merged.route) \
                    .add_callback(self._finish)
            else:
                self.result.try_set_failure(Exhausted(
                    f"propagate {self.txn_id}: no covering outcome"))
