"""Ephemeral reads: linearizable reads with no durable protocol state.

Role-equivalent to the reference's CoordinateEphemeralRead +
ExecuteEphemeralRead (coordinate/CoordinateEphemeralRead.java,
messages/GetEphemeralReadDeps.java): collect every witnessed conflict from a
quorum of each shard (no timestamp bound), then read from one replica per
shard once those deps have applied there. The read itself is never
PreAccepted/committed/persisted -- other transactions can never depend on
it, and a client timeout simply abandons it (there is nothing to recover).

Guarantee (mirrors the reference's doc): strict-serializable for single-key
reads; per-key linearizable for multi-key reads (the burn generates only
single-key ephemeral reads so the strict verifier applies in full).
"""
from __future__ import annotations

from typing import List

from accord_tpu.coordinate.errors import Exhausted, Timeout
from accord_tpu.coordinate.tracking import QuorumTracker, ReadTracker, RequestStatus
from accord_tpu.messages.base import Callback
from accord_tpu.messages.getdeps import GetEphemeralReadDeps, GetEphemeralReadDepsOk
from accord_tpu.messages.read import EphemeralRead, ReadNack, ReadOk
from accord_tpu.primitives.deps import Deps
from accord_tpu.primitives.timestamp import TxnId
from accord_tpu.primitives.txn import Txn
from accord_tpu.utils.async_ import AsyncResult


class CoordinateEphemeralRead(Callback):
    def __init__(self, node, txn_id: TxnId, txn: Txn, route):
        self.node = node
        self.txn_id = txn_id
        self.txn = txn
        self.route = route
        self.result: AsyncResult = AsyncResult()
        self.collected_epoch = txn_id.epoch
        self.topologies = node.topology_manager.with_unsynced_epochs(
            route, txn_id.epoch, txn_id.epoch)
        self.tracker = QuorumTracker(self.topologies, txn.keys)
        self.oks: List[GetEphemeralReadDepsOk] = []
        self.latest_epoch = txn_id.epoch
        self.chases = 0
        self.executing = False
        self.round = 0

    MAX_EPOCH_CHASES = 3

    @classmethod
    def coordinate(cls, node, txn_id: TxnId, txn: Txn, route) -> AsyncResult:
        self = cls(node, txn_id, txn, route)
        self._send_round()
        return self.result

    def _send_round(self) -> None:
        # each deps round gets its own callback stamped with the round number
        # so late replies/timeouts from a superseded round (after an epoch
        # chase replaced the tracker) are never credited against the new
        # QuorumTracker -- the same cross-round crediting hazard
        # transaction.py's _ReadRoundCb guards
        cb = _DepsRoundCb(self, self.round)
        for to in self.tracker.nodes():
            self.node.send(to, GetEphemeralReadDeps(self.txn_id, self.txn.keys),
                           cb)

    # -- deps collection ------------------------------------------------------
    def on_round_success(self, round_no, from_node, reply) -> None:
        if self.result.done or self.executing or round_no != self.round:
            return
        self.oks.append(reply)
        self.latest_epoch = max(self.latest_epoch, reply.latest_epoch)
        if self.tracker.on_success(from_node) == RequestStatus.SUCCESS:
            self._quorum_reached()

    def on_round_failure(self, round_no, from_node, failure) -> None:
        if self.result.done or self.executing or round_no != self.round:
            return
        if self.tracker.on_failure(from_node) == RequestStatus.FAILED:
            self.result.try_set_failure(
                Timeout(f"ephemeral deps {self.txn_id}"))

    def _quorum_reached(self) -> None:
        # epoch chase (reference: CoordinateEphemeralRead re-contacts when
        # replies report a later epoch): deps must come from quorums of the
        # epoch the read will execute in, else a write witnessed only by
        # new-epoch replicas could be missed
        if self.latest_epoch > self.collected_epoch:
            if self.chases >= self.MAX_EPOCH_CHASES:
                # deps from a stale-epoch quorum must NOT execute against the
                # newer topology (a new-epoch-only write could be missed):
                # abandon -- the client retries with a fresh txn id
                self.result.try_set_failure(Timeout(
                    f"ephemeral {self.txn_id}: epochs outran "
                    f"{self.MAX_EPOCH_CHASES} deps rounds"))
                return
            self.chases += 1
            target = self.latest_epoch

            def rerun():
                self.collected_epoch = target
                self.round += 1  # invalidate the superseded round's callbacks
                self.topologies = self.node.topology_manager \
                    .with_unsynced_epochs(self.route, target, target)
                self.tracker = QuorumTracker(self.topologies, self.txn.keys)
                self._send_round()

            self.node.with_epoch(target, rerun)
            return
        self._execute()

    # -- execution ------------------------------------------------------------
    def _execute(self) -> None:
        self.executing = True
        deps = Deps.merge([ok.deps for ok in self.oks])
        node = self.node
        epoch = max(self.latest_epoch, self.txn_id.epoch)

        def start(_=None):
            topologies = node.topology_manager.with_unsynced_epochs(
                self.route, epoch, epoch)
            _EphemeralExecute(self, topologies, deps, epoch).start()

        if epoch > node.epoch:
            node.with_epoch(epoch, start)
        else:
            start()


class _DepsRoundCb(Callback):
    """Round-stamped adapter: replies from a superseded deps round must not
    credit the tracker of the round that replaced it."""

    __slots__ = ("parent", "round_no")

    def __init__(self, parent: CoordinateEphemeralRead, round_no: int):
        self.parent = parent
        self.round_no = round_no

    def on_success(self, from_node, reply) -> None:
        self.parent.on_round_success(self.round_no, from_node, reply)

    def on_failure(self, from_node, failure) -> None:
        self.parent.on_round_failure(self.round_no, from_node, failure)


class _EphemeralExecute(Callback):
    """Read round: one replica per shard, escalating on nacks/gaps."""

    def __init__(self, parent: CoordinateEphemeralRead, topologies, deps: Deps,
                 epoch: int):
        self.parent = parent
        self.deps = deps
        self.epoch = epoch
        self.read_tracker = ReadTracker(topologies, parent.txn.read.keys())
        self.data = None
        self.done = False

    def start(self) -> None:
        p = self.parent
        for to in self.read_tracker.initial_contacts(prefer=p.node.id):
            p.node.send(to, EphemeralRead(p.txn_id, p.txn, self.deps,
                                          self.epoch), self)

    def on_success(self, from_node, reply) -> None:
        if self.done or self.parent.result.done:
            return
        if isinstance(reply, ReadNack):
            self._step(*self.read_tracker.on_read_failure(from_node))
            return
        assert isinstance(reply, ReadOk)
        if reply.data is not None:
            self.data = reply.data if self.data is None \
                else self.data.merge(reply.data)
        if reply.unavailable is not None:
            self._step(*self.read_tracker.on_partial_data(
                from_node, reply.unavailable))
        else:
            st = self.read_tracker.on_data_success(from_node)
            if st == RequestStatus.SUCCESS:
                self._finish()

    def on_failure(self, from_node, failure) -> None:
        if self.done or self.parent.result.done:
            return
        self._step(*self.read_tracker.on_read_failure(from_node))

    def _step(self, status: RequestStatus, more) -> None:
        p = self.parent
        if status == RequestStatus.FAILED:
            self.done = True
            p.result.try_set_failure(Exhausted(f"ephemeral read {p.txn_id}"))
            return
        for to in more:
            p.node.send(to, EphemeralRead(p.txn_id, p.txn, self.deps,
                                          self.epoch), self)
        if status == RequestStatus.SUCCESS:
            self._finish()

    def _finish(self) -> None:
        self.done = True
        p = self.parent
        result = p.txn.query.compute(p.txn_id, p.txn_id.as_timestamp(),
                                     p.txn.keys, self.data, p.txn.read, None)
        p.result.try_set_success(result)
