"""CoordinateTransaction: the client-side transaction driver.

Role-equivalent to the reference's CoordinateTransaction + CoordinationAdapter
chain (coordinate/CoordinateTransaction.java:50, Propose.java:53,
StabiliseTxn.java:35, ExecuteTxn.java:53, Persist.java:43):

  PreAccept (FastPathTracker)
    fast path:  executeAt = txnId, deps = union of fast voters' deps
    slow path:  executeAt = max(witnessedAt), Accept round (Propose), deps
                extended with accept-round deps
  Stabilise+Execute: Commit(Stable) to all replicas, with the read embedded at
    one replica per shard (commit-and-read overlap); stable quorum + data.
  Persist: client callback fires with the Result BEFORE the Apply round --
    Apply is off the latency path (reference: CoordinationAdapter.java:187-192).

Fast path client latency = 2 message round trips; slow path = 3.
"""
from __future__ import annotations

from typing import Dict, Optional

from accord_tpu.coordinate.errors import Exhausted, Preempted, Timeout
from accord_tpu.coordinate.tracking import (
    AppliedTracker, FastPathTracker, QuorumTracker, ReadTracker, RequestStatus,
)
from accord_tpu.messages import (
    Accept, AcceptNack, AcceptOk, AcceptRedundant, Apply, ApplyOk, Callback,
    Commit, CommitOk, PreAccept, PreAcceptNack, PreAcceptOk, ReadNack, ReadOk,
    ReadTxnData,
)
from accord_tpu.obs.trace import REC, node_pid, node_ts
from accord_tpu.primitives.deps import Deps
from accord_tpu.primitives.routes import Route
from accord_tpu.primitives.timestamp import Ballot, Timestamp, TxnId
from accord_tpu.primitives.txn import Txn
from accord_tpu.utils.async_ import AsyncResult
from accord_tpu.utils.invariants import Invariants


class CoordinateTransaction:
    def __init__(self, node, txn_id: TxnId, txn: Txn, route: Route,
                 ballot: Ballot = Ballot.ZERO):
        self.node = node
        self.txn_id = txn_id
        self.txn = txn
        self.route = route
        self.ballot = ballot  # > ZERO when driven by recovery
        self.result: AsyncResult = AsyncResult()
        self.topologies = node.topology_manager.with_unsynced_epochs(
            route, txn_id.epoch, txn_id.epoch)
        self.execute_at: Optional[Timestamp] = None
        self.deps: Deps = Deps.NONE
        # coordination start in sim microseconds: the txn.commit_latency_us /
        # txn.apply_latency_us histograms and the trace span anchor here
        self.t0_us = node.time_service.now_micros()

    @classmethod
    def coordinate(cls, node, txn_id: TxnId, txn: Txn, route: Route) -> AsyncResult:
        self = cls(node, txn_id, txn, route)
        node.metrics.counter("txn.started").inc()
        if REC.enabled:
            REC.txn_begin(node_pid(node), txn_id, self.t0_us)
        self._start_preaccept()
        return self.result

    @classmethod
    def resume(cls, node, txn_id: TxnId, txn: Txn, route: Route, ballot: Ballot,
               phase: str, execute_at: Timestamp, deps: Deps) -> AsyncResult:
        """Entry point for recovery: re-drive the protocol from `phase`
        ('propose' -> Accept round; 'execute' -> Commit(Stable)+read) with the
        recovery's ballot and reconstructed (executeAt, deps)
        (reference: RecoveryTxnAdapter, coordinate/CoordinationAdapter.java:195)."""
        self = cls(node, txn_id, txn, route, ballot)
        self.execute_at = execute_at
        self.deps = deps
        if phase == "propose":
            self._start_propose()
        else:
            Invariants.check_argument(phase == "execute", "unknown phase %s", phase)
            self._start_execute()
        return self.result

    # -- phase 1: PreAccept --------------------------------------------------
    def _start_preaccept(self) -> None:
        round_ = _PreAcceptRound(self)
        for to in round_.tracker.nodes():
            self.node.send(to, PreAccept(self.txn_id, self.txn, self.route), round_)

    def _on_preaccepted(self, round_: "_PreAcceptRound") -> None:
        from accord_tpu.utils import faults
        if round_.tracker.has_fast_path_accepted() \
                and not faults.FAST_PATH_DISABLED:
            # (reference: CoordinateTransaction.java:73-77)
            self.execute_at = self.txn_id.as_timestamp()
            self.deps = Deps.merge([ok.deps for ok in round_.oks.values()
                                    if ok.is_fast_path_vote])
            self.node.events.on_fast_path_taken(self.txn_id)
            if REC.enabled:
                REC.txn_step(node_pid(self.node), self.txn_id, "fast_path",
                             node_ts(self.node))
            self._start_execute()
        else:
            self.execute_at = _merge_witnessed_all(
                ok.witnessed_at for ok in round_.oks.values())
            self.deps = Deps.merge([ok.deps for ok in round_.oks.values()])
            self.node.events.on_slow_path_taken(self.txn_id)
            if REC.enabled:
                REC.txn_step(node_pid(self.node), self.txn_id, "slow_path",
                             node_ts(self.node))
            if self.execute_at.is_rejected:
                # a replica refused to witness us (behind an
                # ExclusiveSyncPoint floor, or expired): invalidate instead of
                # committing behind the floor (reference:
                # CoordinateTransaction.java:87-89)
                self._invalidate_rejected()
                return
            self._maybe_extend_epochs()

    def _maybe_extend_epochs(self) -> None:
        """ExtraEpochs: a slow-path executeAt in a newer epoch means the new
        epoch's replicas must witness the txn before Accept, and every later
        round must span both replica sets (reference:
        AbstractCoordinatePreAccept.ExtraEpochs, coordinate/
        AbstractCoordinatePreAccept.java:211-238). Loops if the extra round
        pushes executeAt into a yet-newer epoch."""
        target = self.execute_at.epoch
        if target <= self.topologies.current_epoch():
            self._start_propose()
            return

        def cont():
            prev_max = self.topologies.current_epoch()
            self.topologies = self.node.topology_manager.with_unsynced_epochs(
                self.route, self.txn_id.epoch, target)
            extra = self.node.topology_manager.precise_epochs(prev_max + 1, target)
            round_ = _ExtraEpochsRound(self, extra)
            for to in round_.tracker.nodes():
                self.node.send(to, PreAccept(self.txn_id, self.txn, self.route,
                                             min_epoch=target), round_)

        self.node.with_epoch(target, cont)

    def _invalidate_rejected(self) -> None:
        """proposeAndCommitInvalidate at the original coordinator's ballot
        (reference: Propose.Invalidate.proposeAndCommitInvalidate). Safe at
        Ballot.ZERO: only the original coordinator uses ballot zero, and it
        proposes either the txn or the invalidation, never both."""
        from accord_tpu.coordinate.errors import Invalidated
        from accord_tpu.coordinate.recover import propose_invalidate
        from accord_tpu.messages.recover import CommitInvalidate

        def committed(_):
            topology = self.node.topology_manager.for_epoch(self.txn_id.epoch)
            for to in topology.nodes():
                self.node.send(to, CommitInvalidate(self.txn_id,
                                                    self.route.participants))
            self.node.events.on_invalidated(self.txn_id)
            self._fail(Invalidated(f"{self.txn_id} rejected by sync-point floor"))

        propose_invalidate(self.node, self.txn_id, self.ballot,
                           self.route.home_key) \
            .on_success(committed) \
            .on_failure(self._fail)

    # -- phase 2 (slow path): Accept -----------------------------------------
    def _start_propose(self) -> None:
        round_ = _ProposeRound(self)
        for to in round_.tracker.nodes():
            self.node.send(to, Accept(self.txn_id, self.ballot, self.route,
                                      self.txn.keys, self.execute_at,
                                      self.deps), round_)

    def _on_accepted(self, round_: "_ProposeRound") -> None:
        from accord_tpu.utils import faults
        skip = faults.SYNCPOINT_UNMERGED_DEPS \
            if self.txn_id.kind.is_sync_point \
            else faults.TRANSACTION_UNMERGED_DEPS
        if not skip:
            self.deps = Deps.merge(
                [self.deps] + [ok.deps for ok in round_.oks.values()])
        self._start_execute()

    # -- phase 3: Stabilise + Execute (commit-and-read overlap) --------------
    def _start_execute(self) -> None:
        _ExecuteRound(self).start()

    def _on_executed(self, data) -> None:
        writes = self.txn.execute(self.txn_id, self.execute_at, data)
        result = self.txn.result(self.txn_id, self.execute_at, data)
        self._persist(writes, result)

    # -- phase 4: Persist (off the client latency path) ----------------------
    def _persist(self, writes, result) -> None:
        now = node_ts(self.node)
        self.node.metrics.histogram("txn.commit_latency_us").observe(
            now - self.t0_us)
        if REC.enabled:
            REC.txn_step(node_pid(self.node), self.txn_id, "result", now)
        self.result.try_set_success(result)
        round_ = _ApplyRound(self, writes, result)
        round_.start()

    # -- shared failure handling ---------------------------------------------
    def _fail(self, failure: BaseException) -> None:
        if not self.result.done:
            self.node.events.on_timeout(self.txn_id)
            self.node.metrics.counter("txn.failed").inc()
            if REC.enabled:
                REC.txn_end(node_pid(self.node), self.txn_id,
                            node_ts(self.node),
                            args={"failed": type(failure).__name__})
            self.result.set_failure(failure)

    @property
    def done(self) -> bool:
        return self.result.done


def _merge_witnessed_all(timestamps) -> Timestamp:
    """max with sticky rejection across every vote (see
    Timestamp.merge_witnessed)."""
    out = None
    for ts in timestamps:
        out = ts if out is None else Timestamp.merge_witnessed(out, ts)
    Invariants.check_state(out is not None, "no witnessed timestamps")
    return out


class _PreAcceptRound(Callback):
    def __init__(self, parent: CoordinateTransaction):
        self.parent = parent
        self.tracker = FastPathTracker(parent.topologies, parent.txn.keys)
        self.oks: Dict[int, PreAcceptOk] = {}
        self.nacked = False

    def on_success(self, from_node, reply) -> None:
        if self.parent.done or self.tracker.decided is not None:
            return
        if isinstance(reply, PreAcceptNack):
            # a recovery coordinator holds a higher ballot
            self.nacked = True
            self._handle(self.tracker.on_failure(from_node))
            return
        self.oks[from_node] = reply
        self._handle(self.tracker.on_success(from_node, reply.is_fast_path_vote))

    def on_failure(self, from_node, failure) -> None:
        if self.parent.done or self.tracker.decided is not None:
            return
        self._handle(self.tracker.on_failure(from_node))

    def _handle(self, status: RequestStatus) -> None:
        if status == RequestStatus.SUCCESS:
            self.parent._on_preaccepted(self)
        elif status == RequestStatus.FAILED:
            self.parent._fail(Preempted(str(self.parent.txn_id)) if self.nacked
                              else Timeout(f"preaccept {self.parent.txn_id}"))


class _ExtraEpochsRound(Callback):
    """PreAccept re-contact of the replicas added by epochs
    (prev_max, executeAt.epoch] (reference: ExtraEpochs.contact)."""

    def __init__(self, parent: CoordinateTransaction, extra_topologies):
        self.parent = parent
        self.tracker = QuorumTracker(extra_topologies, parent.txn.keys)
        self.oks: Dict[int, PreAcceptOk] = {}
        self.nacked = False

    def on_success(self, from_node, reply) -> None:
        if self.parent.done or self.tracker.decided is not None:
            return
        if isinstance(reply, PreAcceptNack):
            self.nacked = True
            self._handle(self.tracker.on_failure(from_node))
            return
        self.oks[from_node] = reply
        self._handle(self.tracker.on_success(from_node))

    def on_failure(self, from_node, failure) -> None:
        if self.parent.done or self.tracker.decided is not None:
            return
        self._handle(self.tracker.on_failure(from_node))

    def _handle(self, status: RequestStatus) -> None:
        p = self.parent
        if status == RequestStatus.SUCCESS:
            p.execute_at = _merge_witnessed_all(
                [p.execute_at] + [ok.witnessed_at for ok in self.oks.values()])
            p.deps = Deps.merge([p.deps] + [ok.deps for ok in self.oks.values()])
            if p.execute_at.is_rejected:
                p._invalidate_rejected()
            else:
                p._maybe_extend_epochs()
        elif status == RequestStatus.FAILED:
            p._fail(Preempted(str(p.txn_id)) if self.nacked
                    else Timeout(f"preaccept-extra {p.txn_id}"))


class _ProposeRound(Callback):
    def __init__(self, parent: CoordinateTransaction):
        self.parent = parent
        self.tracker = QuorumTracker(parent.topologies, parent.txn.keys)
        self.oks: Dict[int, AcceptOk] = {}
        self.nacked = False

    def on_success(self, from_node, reply) -> None:
        if self.parent.done or self.tracker.decided is not None:
            return
        if isinstance(reply, AcceptRedundant):
            # the txn is already COMMITTED (a recovery superseded us, possibly
            # at a different executeAt): committing OUR proposal would hand
            # the client a result computed at the wrong timestamp. Fail as
            # preempted; the cluster already carries the decided outcome.
            self.parent._fail(Preempted(
                f"{self.parent.txn_id} already committed at "
                f"{reply.execute_at}"))
            return
        if isinstance(reply, AcceptNack):
            self.nacked = True
            self._handle(self.tracker.on_failure(from_node))
            return
        self.oks[from_node] = reply
        self._handle(self.tracker.on_success(from_node))

    def on_failure(self, from_node, failure) -> None:
        if self.parent.done or self.tracker.decided is not None:
            return
        self._handle(self.tracker.on_failure(from_node))

    def _handle(self, status: RequestStatus) -> None:
        if status == RequestStatus.SUCCESS:
            self.parent._on_accepted(self)
        elif status == RequestStatus.FAILED:
            self.parent._fail(Preempted(str(self.parent.txn_id)) if self.nacked
                              else Timeout(f"accept {self.parent.txn_id}"))


class _ReadRoundCb(Callback):
    """Round-stamping adapter: _retry_read replaces the read tracker, and a
    late reply/timeout from a previous round must not be credited against
    the NEW tracker (it could mark fresh contacts failed before they
    answer). Stable votes pass through regardless -- they belong to the
    txn, not the read round."""

    __slots__ = ("round_", "target")

    def __init__(self, target: "_ExecuteRound", round_no: int):
        self.target = target
        self.round_ = round_no

    def on_success(self, from_node, reply) -> None:
        self.target.on_success(from_node, reply, self.round_)

    def on_failure(self, from_node, failure) -> None:
        self.target.on_failure(from_node, failure, self.round_)


class _ExecuteRound(Callback):
    """Commit(Stable) to every replica; the read rides on one replica per
    shard (reference: ExecuteTxn.java:84-145 + Commit.stableAndRead)."""

    # a fully-nacked read round is usually TRANSIENT (every replica of some
    # shard awaiting a bootstrap snapshot during churn): retry the read with
    # backoff before giving up (reference: ReadCoordinator retry semantics)
    READ_RETRIES = 4
    READ_RETRY_BACKOFF_MS = 500.0

    def __init__(self, parent: CoordinateTransaction):
        self.parent = parent
        self.stable_tracker = QuorumTracker(parent.topologies, parent.txn.keys)
        read = parent.txn.read
        self.needs_read = read is not None and len(tuple(iter(read.keys()))) > 0
        self.read_tracker = (ReadTracker(parent.topologies, read.keys())
                             if self.needs_read else None)
        self.read_attempts = 0
        self.read_round = 0   # replies from superseded read rounds are
                              # ignored for READ accounting (stable votes
                              # still count -- they are round-independent)
        self.data = None
        self.data_done = not self.needs_read

    def start(self) -> None:
        p = self.parent
        read_targets = (set(self.read_tracker.initial_contacts(prefer=p.node.id))
                        if self.needs_read else set())
        cb = _ReadRoundCb(self, self.read_round)
        for to in self.stable_tracker.nodes():
            p.node.send(to, Commit(p.txn_id, p.route, p.txn, p.execute_at,
                                   p.deps, read=(to in read_targets)), cb)
        self._maybe_done()

    def on_success(self, from_node, reply, round_no: int = 0) -> None:
        p = self.parent
        if p.done:
            return
        current = round_no == self.read_round
        if isinstance(reply, (CommitOk,)):
            self._handle_stable(self.stable_tracker.on_success(from_node))
        elif isinstance(reply, ReadOk):
            if reply.data is not None:
                self.data = reply.data if self.data is None else self.data.merge(reply.data)
            self._handle_stable(self.stable_tracker.on_success(from_node))
            if self.needs_read and not self.data_done and current:
                if reply.unavailable is not None:
                    status, more = self.read_tracker.on_partial_data(
                        from_node, reply.unavailable)
                    self._after_read_step(status, more)
                else:
                    st = self.read_tracker.on_data_success(from_node)
                    if st == RequestStatus.SUCCESS:
                        self.data_done = True
            self._maybe_done()
        elif isinstance(reply, ReadNack):
            # a Commit-with-read replica commits BEFORE attempting the read
            # (Commit.process), so ITS nack is still a stable vote -- and
            # dropping it can leave the stable quorum undecidable with no
            # timeout pending (a silent hang). A bare ReadTxnData nack
            # proves nothing about the commit and must not be credited.
            if reply.committed:
                self._handle_stable(self.stable_tracker.on_success(from_node))
            if current:
                self._read_failure(from_node)

    def on_failure(self, from_node, failure, round_no: int = 0) -> None:
        if self.parent.done:
            return
        self._handle_stable(self.stable_tracker.on_failure(from_node))
        if self.needs_read and round_no == self.read_round:
            self._read_failure(from_node)

    def _read_failure(self, from_node) -> None:
        if self.data_done or self.read_tracker.decided is not None:
            return
        status, more = self.read_tracker.on_read_failure(from_node)
        self._after_read_step(status, more)

    def _after_read_step(self, status: RequestStatus, more) -> None:
        if status == RequestStatus.FAILED:
            if self.read_attempts < self.READ_RETRIES:
                self.read_attempts += 1
                self.parent.node.scheduler.once(
                    self.READ_RETRY_BACKOFF_MS * self.read_attempts,
                    self._retry_read)
            else:
                self.parent._fail(Exhausted(f"read {self.parent.txn_id}"))
            return
        p = self.parent
        cb = _ReadRoundCb(self, self.read_round)
        for to in more:
            p.node.send(to, ReadTxnData(p.txn_id, p.txn, p.execute_at), cb)
        if status == RequestStatus.SUCCESS:
            self.data_done = True
            self._maybe_done()

    def _retry_read(self) -> None:
        """Retry the read round. Escalates to the CURRENT epoch's replicas:
        under churn the txn-epoch replicas may all have lost the data (their
        abandoned bootstrap gaps never heal once the range moved on), while
        the current owners hold the full history. They may never have heard
        of the txn, so the retry rides a (idempotent) Commit-with-read that
        lets them commit, order and serve (the reference's stable-then-read
        escalation)."""
        p = self.parent
        if p.done or self.data_done:
            return
        # CURRENT epoch only: spanning down to txn_id.epoch would still
        # demand data credit from the old shard whose replicas are exactly
        # the ones gap-nacking -- the escalation must be satisfiable by the
        # current owners alone
        epoch = max(p.txn_id.epoch, p.node.epoch)
        topologies = p.node.topology_manager.with_unsynced_epochs(
            p.route, epoch, epoch)
        self.read_round += 1   # retire stale replies from the old round
        self.read_tracker = ReadTracker(topologies, p.txn.read.keys())
        cb = _ReadRoundCb(self, self.read_round)
        for to in self.read_tracker.initial_contacts(prefer=p.node.id):
            p.node.send(to, Commit(p.txn_id, p.route, p.txn, p.execute_at,
                                   p.deps, read=True), cb)

    def _handle_stable(self, status: RequestStatus) -> None:
        if status == RequestStatus.FAILED:
            self.parent._fail(Timeout(f"stabilise {self.parent.txn_id}"))
        else:
            self._maybe_done()

    def _maybe_done(self) -> None:
        if self.parent.done:
            return
        if self.stable_tracker.decided == RequestStatus.SUCCESS and self.data_done:
            self.parent._on_executed(self.data)


class _ApplyRound(Callback):
    """Background persist: broadcast Apply (the client already has its
    result). A couple of retries cover transient drops; beyond that the
    straggler-repair machinery owns convergence -- every replica's progress
    engine tracks stable-but-unapplied commands and fetches the outcome via
    CheckStatus/propagate, and durability rounds advance the floors behind it
    (reference: Persist fire-and-forget + SimpleProgressLog +
    CoordinateDurabilityScheduling)."""

    MAX_ATTEMPTS = 3

    def __init__(self, parent: CoordinateTransaction, writes, result,
                 on_applied=None):
        self.parent = parent
        self.writes = writes
        self.result = result
        self.on_applied = on_applied  # fires once a quorum has applied
        self.tracker = AppliedTracker(parent.topologies, parent.txn.keys)
        self.acked: set = set()
        self.attempts: Dict[int, int] = {}
        self._informed = False

    def _message(self) -> Apply:
        p = self.parent
        return Apply(p.txn_id, p.route, p.txn, p.execute_at, p.deps,
                     self.writes, self.result)

    def start(self) -> None:
        for to in self.tracker.nodes():
            self.attempts[to] = 1
            self.parent.node.send(to, self._message(), self)

    def on_success(self, from_node, reply) -> None:
        self.acked.add(from_node)
        if self.tracker.on_success(from_node) == RequestStatus.SUCCESS:
            self._inform_durable()
            if self.on_applied is not None:
                cb, self.on_applied = self.on_applied, None
                cb()

    def _inform_durable(self) -> None:
        """Applied quorum reached on every shard: broadcast majority-
        durability so progress engines treat the txn as fetch-only work
        (reference: Persist.java:88 sends InformDurable(Majority) to every
        node of the topologies)."""
        if self._informed:
            return
        self._informed = True
        t0 = getattr(self.parent, "t0_us", None)
        if t0 is not None:
            node = self.parent.node
            now = node_ts(node)
            node.metrics.histogram("txn.apply_latency_us").observe(now - t0)
            if REC.enabled:
                REC.txn_end(node_pid(node), self.parent.txn_id, now,
                            args={"acked": len(self.acked)})
        from accord_tpu.local.status import Durability
        from accord_tpu.messages.inform import InformDurable
        p = self.parent
        for to in self.tracker.nodes():
            p.node.counters["informs_durable_sent"] += 1
            p.node.send(to, InformDurable(p.txn_id, p.route, p.execute_at,
                                          Durability.MAJORITY))

    def on_failure(self, from_node, failure) -> None:
        if from_node in self.acked:
            return
        n = self.attempts.get(from_node, 0)
        if n >= self.MAX_ATTEMPTS:
            if self.tracker.on_failure(from_node) == RequestStatus.FAILED \
                    and self.on_applied is not None:
                # a blocking caller (sync point / barrier) is waiting on the
                # applied quorum: fail it rather than hang forever
                self.on_applied = None
                self.parent._fail(Timeout(f"apply {self.parent.txn_id}"))
            return
        self.attempts[from_node] = n + 1
        self.parent.node.send(from_node, self._message(), self)
