"""CoordinateSyncPoint + Barrier.

Role-equivalent to the reference's coordinate/CoordinateSyncPoint.java:58 (+
the sync-point CoordinationAdapters, CoordinationAdapter.java:77-131) and
coordinate/Barrier.java:64. A sync point rides the standard transaction
machinery -- PreAccept / (Accept) / Commit(Stable) / Apply -- with an empty
txn of kind SYNC_POINT or EXCLUSIVE_SYNC_POINT; what differs is only the
adapter policy:

  inclusive (async)    -> complete once stable everywhere; Apply in background
  inclusive (blocking) -> complete once a quorum has applied
  exclusive            -> always run the Accept round (never fast-path
                          straight to execute), complete once stable; the
                          durability/bootstrap machinery later drives
                          ApplyThenWaitUntilApplied against it

The result value is the SyncPoint (syncId, waitFor, ...) rather than a client
Result.
"""
from __future__ import annotations

from typing import Optional

from accord_tpu.coordinate.transaction import CoordinateTransaction, _ApplyRound
from accord_tpu.primitives.deps import Deps
from accord_tpu.primitives.keyspace import Seekables
from accord_tpu.primitives.syncpoint import SyncPoint
from accord_tpu.primitives.timestamp import TxnId, TxnKind
from accord_tpu.primitives.txn import Txn
from accord_tpu.utils.async_ import AsyncResult
from accord_tpu.utils.invariants import Invariants


class CoordinateSyncPoint(CoordinateTransaction):
    def __init__(self, node, txn_id: TxnId, txn: Txn, route, blocking: bool):
        super().__init__(node, txn_id, txn, route)
        self.blocking = blocking

    # -- entry points (reference: CoordinateSyncPoint.exclusive/inclusive) ---
    @classmethod
    def exclusive(cls, node, seekables: Seekables,
                  blocking: bool = False) -> AsyncResult:
        """blocking=True completes only once an APPLIED quorum exists per
        shard -- the durability rounds' prerequisite (everything ordered
        below the sync point is then applied at a quorum)."""
        return cls._coordinate(node, TxnKind.EXCLUSIVE_SYNC_POINT, seekables,
                               blocking=blocking)

    @classmethod
    def inclusive(cls, node, seekables: Seekables,
                  blocking: bool = False) -> AsyncResult:
        return cls._coordinate(node, TxnKind.SYNC_POINT, seekables,
                               blocking=blocking)

    @classmethod
    def build(cls, node, kind: TxnKind, seekables: Seekables,
              blocking: bool = False) -> "CoordinateSyncPoint":
        """Create without sending anything: the caller may need the txn_id
        before the first message goes out (Bootstrap sets its floor from it)."""
        txn = node.agent.empty_txn(kind, seekables)
        txn_id = node.next_txn_id(kind, seekables.domain)
        route = node.compute_route(txn)
        return cls(node, txn_id, txn, route, blocking)

    def start(self) -> AsyncResult:
        self._start_preaccept()
        return self.result

    @classmethod
    def _coordinate(cls, node, kind: TxnKind, seekables: Seekables,
                    blocking: bool) -> AsyncResult:
        return cls.build(node, kind, seekables, blocking).start()

    # -- adapter policy overrides -------------------------------------------
    def _on_preaccepted(self, round_) -> None:
        # merge deps from EVERY reply -- the waitFor set must cover everything
        # any contacted replica witnessed (reference:
        # CoordinateSyncPoint.onPreAccepted merges all oks)
        oks = round_.oks.values()
        self.deps = Deps.merge([ok.deps for ok in oks])
        if any(ok.witnessed_at.is_rejected for ok in oks):
            self._invalidate_rejected()
            return
        if round_.tracker.has_fast_path_accepted() \
                and self.txn_id.kind is TxnKind.SYNC_POINT:
            self.execute_at = self.txn_id.as_timestamp()
            self._start_execute()
        else:
            # exclusive sync points always run the Accept round: their deps
            # must be ballot-recoverable before anyone treats lower TxnIds as
            # expired (reference: CoordinateSyncPoint.java:129-133)
            self.execute_at = max(ok.witnessed_at for ok in oks)
            self._start_propose()

    def _persist(self, writes, result) -> None:
        Invariants.check_state(writes is None, "sync point computed writes")
        sp = SyncPoint(self.txn_id, self.route, self.deps, self.txn.keys)
        if self.blocking:
            _ApplyRound(self, None, None,
                        on_applied=lambda: self.result.try_set_success(sp)).start()
        else:
            self.result.try_set_success(sp)
            _ApplyRound(self, None, None).start()


class Barrier:
    """Wait for (at least) everything that happened before the barrier's
    creation to become visible (reference: coordinate/Barrier.java:64).

    local        -> a sync point has applied on THIS node
    global_sync  -> a sync point has applied on a quorum of every shard
    global_async -> a sync point is stable (committed) everywhere
    """

    @staticmethod
    def local(node, seekables: Seekables) -> AsyncResult:
        out: AsyncResult = AsyncResult()

        def on_stable(sp: SyncPoint):
            _await_local_apply(node, sp, out)

        CoordinateSyncPoint.inclusive(node, seekables, blocking=False) \
            .on_success(on_stable) \
            .on_failure(out.try_set_failure)
        return out

    @staticmethod
    def global_sync(node, seekables: Seekables) -> AsyncResult:
        return CoordinateSyncPoint.inclusive(node, seekables, blocking=True)

    @staticmethod
    def global_async(node, seekables: Seekables) -> AsyncResult:
        return CoordinateSyncPoint.inclusive(node, seekables, blocking=False)


def _await_local_apply(node, sp: SyncPoint, out: AsyncResult) -> None:
    """Complete once the sync point has applied on every local store owning
    its seekables (fires immediately when this node owns none of them)."""
    from accord_tpu.messages.wait import when_locally_applied
    when_locally_applied(node, sp.sync_id, sp.seekables,
                         lambda: out.try_set_success(sp))
