"""Coordination failure taxonomy (reference: coordinate/CoordinationFailed
hierarchy -- Timeout, Preempted, Invalidated, Exhausted, ...)."""
from __future__ import annotations


class CoordinationFailed(RuntimeError):
    pass


class Timeout(CoordinationFailed):
    """Insufficient replies before expiry; outcome unknown."""


class Preempted(CoordinationFailed):
    """A recovery coordinator took over (higher ballot witnessed)."""


class Invalidated(CoordinationFailed):
    """The transaction was invalidated and will never execute."""


class Exhausted(CoordinationFailed):
    """Every candidate replica failed (e.g. all read sources)."""


class TopologyMismatch(CoordinationFailed):
    """Route does not match the topology (e.g. key not owned by any shard)."""
