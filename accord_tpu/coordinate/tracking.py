"""Quorum trackers: per-shard x per-epoch response accounting.

Role-equivalent to the reference's coordinate/tracking package
(AbstractTracker.java:37, QuorumTracker.java:27, FastPathTracker.java:34,
ReadTracker.java:40, AppliedTracker.java:29). A coordination round sends one
request per node; each response is credited to EVERY (epoch, shard) the node
replicates, and the round completes when every shard in every spanned epoch
reaches its criterion.
"""
from __future__ import annotations

import enum
from typing import Dict, List, Optional, Sequence, Set, Tuple

from accord_tpu.primitives.keyspace import Seekables
from accord_tpu.primitives.timestamp import NodeId
from accord_tpu.topology.shard import Shard
from accord_tpu.topology.topologies import Topologies
from accord_tpu.utils.invariants import Invariants


class RequestStatus(enum.Enum):
    NO_CHANGE = "no_change"
    SUCCESS = "success"
    FAILED = "failed"


class _ShardState:
    __slots__ = ("shard", "successes", "failures", "fast_votes", "fast_rejects")

    def __init__(self, shard: Shard):
        self.shard = shard
        self.successes: Set[NodeId] = set()
        self.failures: Set[NodeId] = set()
        self.fast_votes: Set[NodeId] = set()
        self.fast_rejects: Set[NodeId] = set()  # electorate members voting non-fast or failed

    # -- slow/classic quorum -------------------------------------------------
    def has_quorum(self) -> bool:
        return len(self.successes) >= self.shard.slow_path_quorum_size

    def has_failed(self) -> bool:
        return len(self.failures) > self.shard.max_failures

    # -- fast path -----------------------------------------------------------
    def fast_achieved(self) -> bool:
        return len(self.fast_votes) >= self.shard.fast_path_quorum_size

    def fast_impossible(self) -> bool:
        e = self.shard.fast_path_electorate
        pending = len(e) - len(self.fast_votes) - len(self.fast_rejects & e)
        return len(self.fast_votes) + pending < self.shard.fast_path_quorum_size

    def fast_resolved(self) -> bool:
        return self.fast_achieved() or self.fast_impossible()


class AbstractTracker:
    def __init__(self, topologies: Topologies, seekables: Optional[Seekables] = None):
        self.topologies = topologies
        self.shards: List[_ShardState] = []
        self._by_node: Dict[NodeId, List[_ShardState]] = {}
        for topology in topologies:
            shards = (topology.shards_for(seekables) if seekables is not None
                      else topology.shards)
            for shard in shards:
                st = _ShardState(shard)
                self.shards.append(st)
                for n in shard.nodes:
                    self._by_node.setdefault(n, []).append(st)
        Invariants.check_state(bool(self.shards), "tracker over zero shards")
        self._decided: Optional[RequestStatus] = None

    def nodes(self) -> Tuple[NodeId, ...]:
        return tuple(sorted(self._by_node))

    def _decide(self) -> RequestStatus:
        if self._decided is not None:
            return RequestStatus.NO_CHANGE
        if any(s.has_failed() for s in self.shards):
            self._decided = RequestStatus.FAILED
            return RequestStatus.FAILED
        if self._is_success():
            self._decided = RequestStatus.SUCCESS
            return RequestStatus.SUCCESS
        return RequestStatus.NO_CHANGE

    def _is_success(self) -> bool:
        raise NotImplementedError

    @property
    def decided(self) -> Optional[RequestStatus]:
        return self._decided

    def on_failure(self, node: NodeId) -> RequestStatus:
        for st in self._by_node.get(node, ()):
            st.failures.add(node)
            if node in st.shard.fast_path_electorate:
                st.fast_rejects.add(node)
        return self._decide()


class QuorumTracker(AbstractTracker):
    """Simple majority in every shard of every epoch."""

    def on_success(self, node: NodeId) -> RequestStatus:
        for st in self._by_node.get(node, ()):
            st.successes.add(node)
        return self._decide()

    def _is_success(self) -> bool:
        return all(s.has_quorum() for s in self.shards)


class FastPathTracker(AbstractTracker):
    """Tracks slow quorum and the fast-path electorate simultaneously
    (reference: FastPathTracker.java:34): success requires a quorum everywhere
    AND the fast path either achieved or ruled out everywhere, so the
    coordinator never commits slow-path while fast was still possible."""

    def on_success(self, node: NodeId, fast_vote: bool) -> RequestStatus:
        for st in self._by_node.get(node, ()):
            st.successes.add(node)
            if node in st.shard.fast_path_electorate:
                (st.fast_votes if fast_vote else st.fast_rejects).add(node)
        return self._decide()

    def _is_success(self) -> bool:
        return all(s.has_quorum() and s.fast_resolved() for s in self.shards)

    def has_fast_path_accepted(self) -> bool:
        return all(s.fast_achieved() for s in self.shards)


class ReadTracker(AbstractTracker):
    """Data quorum: one successful read covering every shard, escalating to
    further replicas on failure (reference: ReadTracker.java:40 trySendMore)."""

    def __init__(self, topologies: Topologies, seekables: Optional[Seekables] = None):
        super().__init__(topologies, seekables)
        self._contacted: Set[NodeId] = set()
        self._data: Set[int] = set()  # indexes of shards with data

    def initial_contacts(self, prefer: Optional[NodeId] = None) -> Tuple[NodeId, ...]:
        """Pick one replica per shard (deduplicated), preferring `prefer`."""
        chosen: Set[NodeId] = set()
        for i, st in enumerate(self.shards):
            if any(n in chosen for n in st.shard.nodes):
                continue
            if prefer is not None and prefer in st.shard.nodes:
                chosen.add(prefer)
            else:
                chosen.add(st.shard.nodes[0])
        self._contacted.update(chosen)
        return tuple(sorted(chosen))

    def on_data_success(self, node: NodeId) -> RequestStatus:
        for i, st in enumerate(self.shards):
            if node in st.shard.nodes:
                st.successes.add(node)
                self._data.add(i)
        return self._decide()

    def on_partial_data(self, node: NodeId,
                        unavailable) -> Tuple[RequestStatus, Tuple[NodeId, ...]]:
        """A reply served SOME slices and reported others unavailable
        (reference: ReadData replies carry `unavailable` ranges): credit the
        shards the reply covered, escalate the rest to further replicas.
        `unavailable` is a Ranges."""
        from accord_tpu.primitives.keyspace import Ranges
        for i, st in enumerate(self.shards):
            if node not in st.shard.nodes or i in self._data:
                # a shard with data already cannot be failed retroactively:
                # later replicas' unrelated gaps must not flip a satisfied
                # shard (and with it the whole round) to FAILED
                continue
            if unavailable.intersects(Ranges([st.shard.range])):
                st.failures.add(node)
            else:
                st.successes.add(node)
                self._data.add(i)
        return self._escalate(node)

    def on_read_failure(self, node: NodeId) -> Tuple[RequestStatus, Tuple[NodeId, ...]]:
        """Returns (status, additional nodes to contact)."""
        for st in self._by_node.get(node, ()):
            st.failures.add(node)
        return self._escalate(node)

    def _escalate(self, node: NodeId) -> Tuple[RequestStatus, Tuple[NodeId, ...]]:
        more: Set[NodeId] = set()
        for i, st in enumerate(self.shards):
            if i in self._data or node not in st.shard.nodes:
                continue
            candidates = [n for n in st.shard.nodes if n not in self._contacted]
            if candidates:
                more.add(candidates[0])
            elif all(n in self._contacted for n in st.shard.nodes) and \
                    not any(n not in st.failures for n in st.shard.nodes if n in self._contacted):
                self._decided = RequestStatus.FAILED
                return RequestStatus.FAILED, ()
        self._contacted.update(more)
        return self._decide(), tuple(sorted(more))

    def _is_success(self) -> bool:
        return len(self._data) == len(self.shards)


class RecoveryTracker(AbstractTracker):
    """Quorum per shard, additionally counting fast-path-electorate members
    whose witnessed timestamp differs from txnId (reference:
    RecoveryTracker.java:26): once more electorate members reject than the
    electorate could spare, the original fast path provably never happened."""

    def on_success(self, node: NodeId, fast_path_vote: bool) -> RequestStatus:
        for st in self._by_node.get(node, ()):
            st.successes.add(node)
            if not fast_path_vote and node in st.shard.fast_path_electorate:
                st.fast_rejects.add(node)
        return self._decide()

    def _is_success(self) -> bool:
        return all(s.has_quorum() for s in self.shards)

    def rejects_fast_path(self) -> bool:
        # only POSITIVE rejects count: a failed/timed-out electorate member
        # (added to fast_rejects by on_failure) proves nothing about the
        # original fast path, so exclude failures from the impossibility math
        return any(
            st.shard.rejects_fast_path(
                len((st.fast_rejects & st.shard.fast_path_electorate) - st.failures))
            for st in self.shards)


class InvalidationTracker(FastPathTracker):
    """Accounting for a BeginInvalidation prepare round (reference:
    InvalidationTracker.java:28): tracks the promise quorum across every
    spanned epoch (vote accounting shared with FastPathTracker), plus the
    fast-path rejection arithmetic -- scoped to the txn's ORIGINAL epoch,
    where any ballot-0 fast quorum must have formed -- that decides whether
    the original fast path is decisively dead (safe to invalidate) or still
    arithmetically possible (must recover instead)."""

    def __init__(self, topologies: Topologies, seekables: Seekables,
                 fast_path_epoch: int):
        super().__init__(topologies, seekables)
        self._fast_states: List[_ShardState] = []
        i = 0
        for topology in topologies:
            shards = topology.shards_for(seekables)
            for _ in shards:
                if topology.epoch == fast_path_epoch:
                    self._fast_states.append(self.shards[i])
                i += 1

    def _is_success(self) -> bool:
        # unlike the parent, invalidation needs only the promise quorum;
        # fast-path resolution is consulted separately via
        # is_fast_path_rejected once every reachable reply is in
        return all(s.has_quorum() for s in self.shards)

    def is_fast_path_rejected(self) -> bool:
        """More REPLIED electorate members cast no ballot-0 fast vote than
        the electorate can spare: no fast quorum ever formed, and our
        promises gate any future vote (reference: isFastPathRejected).
        Failed members prove nothing and are excluded. ANY shard rejecting
        decides: a ballot-0 fast commit needs a fast quorum in EVERY shard,
        so one decisively dead shard kills the whole fast path (reference
        InvalidationTracker sets rejectsFastPath per-shard; the all() this
        replaces was equivalent only while propose_invalidate stayed
        single-key/single-shard)."""
        if not self._fast_states:
            return False
        return any(
            st.shard.rejects_fast_path(
                len((st.fast_rejects & st.shard.fast_path_electorate)
                    - st.failures))
            for st in self._fast_states)


class AppliedTracker(QuorumTracker):
    """Quorum of Apply acks per shard (durability tracking)."""
