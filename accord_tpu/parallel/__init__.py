from accord_tpu.parallel.mesh import make_mesh, sharded_deps_step

__all__ = ["make_mesh", "sharded_deps_step"]
