"""Device-mesh sharding of the deps data plane.

The reference scales inside a node by splitting ranges over single-threaded
CommandStores (local/CommandStores.java:79) -- an embarrassingly parallel
partition of the conflict state. On TPU the analogous dimensions are:

  'data'  axis: the micro-batch of subject transactions (rows of the
          conflict matrix) -- each device computes deps for its slice;
  'model' axis: the key-bucket dimension of the bitmaps -- the conflict
          contraction bitmap[B,K] @ bitmap[A,K]^T is summed over K with a
          psum across the axis (tensor-parallel contraction).

The execute-order closure all-gathers row blocks each squaring round
(ring-friendly collective over ICI). `sharded_deps_step` builds the whole
step -- deps matrix + adjacency closure + execution wavefronts -- as one
shard_map program jitted over the mesh; this is the multi-chip path the
driver dry-runs and the scale-out story for >1 chip.

Finalized-CSR harvest on the sharded path: the COMPACTION ITSELF is
sharded (sharded_finalize_csr) -- each device ANDs and popcounts only ITS
'data' slice of the word columns (kid table and packed candidate words
both sharded P(None, 'data')), an all-gather of the per-(slot, shard)
counts yields the global indptr plus every shard's exclusive write base
inside each segment, and the disjoint per-shard dep_rows fragments
gather-merge with one psum -- so no chip ever materializes a full
(slots x cap) conflict matrix, closing what used to be this module's open
scale-out item. Word order equals row order (cap % (32 * data) == 0) and
shards partition words contiguously, so the merged CSR is bit-identical
to the single-device kernel's. The interval-stab finalize
(range_finalize_csr) stays a plain jit: the range arena is tiny (tens of
rows) and carries no word-packed matrix worth sharding.

The sharded protocol megakernel (sharded_protocol_tick) is the multi-chip
twin of ops/kernels.protocol_tick: ONE jitted mesh program per cluster
tick composing the shard_map'd node-lane key+range resolve, every plan's
finalize-CSR compaction (the _sharded_finalize_body popcount/prefix +
all_gather merge above, sliced at each plan's merge span in-program),
cmd_tick blocks, the fast-path electorate-quorum count, and the
cross-shard mailbox routing stage -- emit lanes whose dst node lives on
another shard ride a tiled lax.all_to_all over 'data' into the
destination shard's rings (ops/mailbox._sharded_mailbox_route_part), with
partition masks and the mailbox arena sharded node-major. Finalize specs
canonically sort by static signature (kernels._fin_split), so the compile
cache keys on the tick-signature multiset exactly as the single-device
path does. sharded_node_tick (the unfused <=2-dispatch pair) stays live
as the megakernel=False baseline.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover -- older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(n_devices: Optional[int] = None,
              devices=None) -> Mesh:
    """2D mesh ('data', 'model'); 'model' gets 2 when divisible, else 1."""
    if devices is None:
        devices = jax.devices()[:n_devices] if n_devices else jax.devices()
    n = len(devices)
    model = 2 if n % 2 == 0 and n >= 4 else 1
    data = n // model
    dev_array = np.array(devices[:data * model]).reshape(data, model)
    return Mesh(dev_array, ("data", "model"))


def mesh_supports_message_plane(mesh: Mesh) -> bool:
    """Whether the device mailbox plane may fuse into sharded programs.

    True since the mailbox routing stage grew its cross-shard collective:
    sharded_protocol_tick shards the arena and the partition mask node-major
    over 'data' and exchanges src-grouped emit lanes with a tiled
    lax.all_to_all (ops/mailbox._sharded_mailbox_route_part), so every
    payload reaches its destination shard's ring inside the one fused
    launch. Kept as a predicate so a future mesh topology that cannot carry
    the collective can opt back out to host messages (the engine counts
    that in sharded_megakernel_fallbacks)."""
    return True


def sharded_deps_step(mesh: Mesh, closure_iters: int = 8):
    """Build the jitted multi-chip deps step.

    Inputs (global shapes):
      bitmaps  f32[N, K]  key bitmaps of the in-flight batch
      ts       i32[N, 3]  packed txn timestamps (ops.encoding layout)
      kinds    i32[N]
      table    i32[6, 6]  witness table
    Outputs:
      deps     bool[N, N]  pairwise dependency matrix
      levels   i32[N]      execution wavefront level per txn
    Sharding: rows over 'data'; the K contraction over 'model' via psum;
    closure all-gathers row blocks per squaring round.
    """

    def step(bitmaps, ts, kinds, table):
        # ---- deps matrix: rows sharded, K sharded, psum over 'model' ----
        def deps_part(bm_rows, ts_rows, kinds_rows, bm_all, ts_all, kinds_all, tbl):
            # bm_rows: [n_local, K_local]; bm_all: [N, K_local]
            partial = jax.lax.dot_general(
                bm_rows.astype(jnp.bfloat16), bm_all.astype(jnp.bfloat16),
                (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
            overlap = jax.lax.psum(partial, "model") > 0.5
            witness = tbl[kinds_rows[:, None], kinds_all[None, :]] == 1
            a, b = ts_all[None, :, :], ts_rows[:, None, :]
            before = ((a[..., 0] < b[..., 0])
                      | ((a[..., 0] == b[..., 0])
                         & ((a[..., 1] < b[..., 1])
                            | ((a[..., 1] == b[..., 1]) & (a[..., 2] < b[..., 2])))))
            return overlap & witness & before

        deps = shard_map(
            deps_part, mesh=mesh,
            in_specs=(P("data", "model"), P("data", None), P("data"),
                      P(None, "model"), P(None, None), P(None), P(None, None)),
            out_specs=P("data", None),
        )(bitmaps, ts, kinds, bitmaps, ts, kinds, table)

        # ---- transitive closure: row blocks, all-gather per round ----
        def closure_block(rows):
            def body(_, r):
                full = jax.lax.all_gather(r, "data", tiled=True)  # [N, N]
                sq = jax.lax.dot_general(
                    r.astype(jnp.bfloat16), full.astype(jnp.bfloat16),
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32) > 0.5
                return r | sq
            return jax.lax.fori_loop(0, closure_iters, body, rows)

        closed = shard_map(
            closure_block, mesh=mesh,
            in_specs=P("data", None), out_specs=P("data", None),
        )(deps)

        # ---- execution wavefronts over the closed graph ----
        def levels_block(adj_rows):
            def body(_, lv):
                full = jax.lax.all_gather(lv, "data", tiled=True)  # [N]
                dep_lv = jnp.where(adj_rows, full[None, :] + 1, 0)
                return jnp.maximum(lv, jnp.max(dep_lv, axis=1))

            # derive the initial carry from the (axis-varying) input so the
            # loop carry's manual-axes annotation matches the body output
            init = jnp.zeros_like(adj_rows[:, 0], dtype=jnp.int32)
            return jax.lax.fori_loop(0, closure_iters, body, init)

        levels = shard_map(
            levels_block, mesh=mesh,
            in_specs=P("data", None), out_specs=P("data"),
        )(closed)
        return deps, levels

    row_sharding = NamedSharding(mesh, P("data", "model"))
    ts_sharding = NamedSharding(mesh, P("data", None))
    vec_sharding = NamedSharding(mesh, P("data"))
    rep = NamedSharding(mesh, P(None, None))
    return jax.jit(step, in_shardings=(row_sharding, ts_sharding, vec_sharding, rep),
                   out_shardings=(NamedSharding(mesh, P("data", None)), vec_sharding))


@functools.lru_cache(maxsize=8)
def sharded_deps_resolve(mesh: Mesh):
    """Mesh-sharded twin of ops.kernels.deps_resolve -- THE production hot
    kernel, not a demo: arena rows sharded over 'data' (each device holds a
    block of the node's active set), key buckets over 'model' (the overlap
    contraction psums across it). The packed u32[B, cap/32] result comes
    back with its lane dimension sharded over 'data'; lane order equals row
    order because every data block's capacity is a multiple of 32.

    Contracts (enforced by ShardedBatchDepsResolver): cap % (32 * data) == 0
    and num_buckets % model == 0 -- both preserved by arena doubling."""
    from accord_tpu.ops.kernels import _lex_before, _pack_bits

    def run(subj_of, subj_keys, subj_before, subj_kinds,
            act_bm, act_ts, act_kinds, act_valid, table):
        def part(sof, sk, sb, sknd, bm, ts, kinds, valid, tbl):
            # bm: [cap_local, K_local]; the subject CSR scatter restricted
            # to the LOCAL bucket slice so the contraction psums over
            # 'model'. Out-of-slice entries remap to col == k_local (OOB,
            # dropped); the guard also catches negative cols, which jax
            # would otherwise WRAP into the slice.
            b = sb.shape[0]
            k_local = bm.shape[1]
            base = jax.lax.axis_index("model") * k_local
            col = sk - base
            col = jnp.where((col >= 0) & (col < k_local), col, k_local)
            subj_bm = jnp.zeros((b, k_local), jnp.float32) \
                .at[sof, col].max(1.0, mode="drop").astype(jnp.bfloat16)
            partial = jax.lax.dot_general(
                subj_bm, bm.astype(jnp.bfloat16),
                (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
            overlap = jax.lax.psum(partial, "model") > 0.5
            witness = tbl[sknd[:, None], kinds[None, :]] == 1
            before = _lex_before(ts[None, :, :], sb[:, None, :])
            return _pack_bits(overlap & witness & before & valid[None, :])

        return shard_map(
            part, mesh=mesh,
            in_specs=(P(None), P(None), P(None, None), P(None),
                      P("data", "model"), P("data", None), P("data"),
                      P("data"), P(None, None)),
            out_specs=P(None, "data"),
        )(subj_of, subj_keys, subj_before, subj_kinds,
          act_bm, act_ts, act_kinds, act_valid, table)

    rep2 = NamedSharding(mesh, P(None, None))
    rep1 = NamedSharding(mesh, P(None))
    return jax.jit(run, in_shardings=(
        rep1, rep1, rep2, rep1,
        NamedSharding(mesh, P("data", "model")),
        NamedSharding(mesh, P("data", None)),
        NamedSharding(mesh, P("data")), NamedSharding(mesh, P("data")),
        rep2), out_shardings=NamedSharding(mesh, P(None, "data")))


def _concat_lane_blocks(mesh: Mesh, blocks):
    """Concatenate per-store packed blocks along the lane axis. The blocks
    come out of the fused kernels sharded P(None, 'data'); on this jax
    version, concatenating along a 'data'-sharded axis on a 2D mesh with a
    >1 'model' axis miscompiles -- the model-replicated lanes are summed as
    if they were partial results, doubling every packed word. Resharding to
    fully replicated first makes the concat collective-free and correct
    (the blocks are a few KB, so the replication copy is noise)."""
    if len(blocks) == 1:
        return blocks[0]
    rep = NamedSharding(mesh, P(None, None))
    return jnp.concatenate([jax.device_put(blk, rep) for blk in blocks],
                           axis=1)


def _covered_buckets(iv_of, iv_start, iv_end, b, k_local, model):
    """The subject intervals' bucket-coverage bitmap, restricted to THIS
    'model' shard's bucket slice -> bf16[b, k_local]. Thin wrapper over the
    shared kernels.covered_buckets modular test (the single-device range
    kernel contracts over the same helper with base == 0): this shard covers
    global buckets [axis_index * k_local, (axis_index + 1) * k_local).
    Widths that overflow int32 go negative (true width < 2^32 always), so
    the helper's `wide` branch catches both them and genuinely-full
    intervals; coverage is a conservative superset either way (the host
    decode re-filters per real key)."""
    from accord_tpu.ops.kernels import covered_buckets
    base = jax.lax.axis_index("model") * k_local
    return covered_buckets(iv_of, iv_start, iv_end, b, k_local, base,
                           k_local * model)


@functools.lru_cache(maxsize=8)
def sharded_range_deps_resolve(mesh: Mesh):
    """Mesh-sharded twin of ops.kernels.range_deps_resolve. Range-arena rows
    shard over 'data' (the interval compares have no bucket dimension, so
    'model' lanes replicate the tiny subject CSR and each compute their data
    block). The key-side test CONTRACTS over 'model' buckets like
    sharded_deps_resolve: the subject intervals scatter into per-shard
    bucket coverage (_covered_buckets) and contract against the key bitmap
    [cap, K] sharded ('data', 'model') -- the same contraction the
    single-device kernel now runs, so no key-arena row lane is replicated
    across 'model'. Both packed outputs come back lane-sharded over 'data';
    lane order equals row order because rcap % (32 * data) == 0 and
    cap % (32 * data) == 0 (the resolver's capacity contracts, preserved by
    doubling). Bucket coverage is a conservative superset of the true key
    overlap; the host decode re-filters per real key, so single-device and
    sharded answers stay differentially identical."""
    from accord_tpu.ops.kernels import _lex_before, _pack_bits
    model = mesh.shape["model"]

    def run(iv_of, iv_start, iv_end, subj_before, subj_kinds, subj_is_range,
            r_start, r_end, r_ts, r_kinds, r_valid,
            act_bm, k_ts, k_kinds, k_valid, table):
        def part(ivo, ivs, ive, sb, sknd, srng,
                 rs, re_, rts, rkd, rvl, bm, kts, kknd, kvl, tbl):
            b = sb.shape[0]
            rcap_l = rs.shape[0]
            hit_r = (ivs[:, None] < re_[None, :]) & (rs[None, :] < ive[:, None])
            any_r = jnp.zeros((b, rcap_l), jnp.int32) \
                .at[ivo].max(hit_r.astype(jnp.int32), mode="drop") > 0
            witness_r = tbl[sknd[:, None], rkd[None, :]] == 1
            before_r = _lex_before(rts[None, :, :], sb[:, None, :])
            m_r = any_r & witness_r & before_r & rvl[None, :]
            cov = _covered_buckets(ivo, ivs, ive, b, bm.shape[1], model)
            partial = jax.lax.dot_general(
                cov, bm.astype(jnp.bfloat16),
                (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
            any_k = jax.lax.psum(partial, "model") > 0.5
            witness_k = tbl[sknd[:, None], kknd[None, :]] == 1
            before_k = _lex_before(kts[None, :, :], sb[:, None, :])
            m_k = any_k & witness_k & before_k & kvl[None, :] & srng[:, None]
            return _pack_bits(m_r), _pack_bits(m_k)

        return shard_map(
            part, mesh=mesh,
            in_specs=(P(None), P(None), P(None), P(None, None), P(None),
                      P(None),
                      P("data"), P("data"), P("data", None), P("data"),
                      P("data"),
                      P("data", "model"), P("data", None), P("data"),
                      P("data"), P(None, None)),
            out_specs=(P(None, "data"), P(None, "data")),
        )(iv_of, iv_start, iv_end, subj_before, subj_kinds, subj_is_range,
          r_start, r_end, r_ts, r_kinds, r_valid,
          act_bm, k_ts, k_kinds, k_valid, table)

    rep2 = NamedSharding(mesh, P(None, None))
    rep1 = NamedSharding(mesh, P(None))
    d1 = NamedSharding(mesh, P("data"))
    d2 = NamedSharding(mesh, P("data", None))
    out = NamedSharding(mesh, P(None, "data"))
    return jax.jit(run, in_shardings=(
        rep1, rep1, rep1, rep2, rep1, rep1,
        d1, d1, d2, d1, d1,
        NamedSharding(mesh, P("data", "model")), d2, d1, d1,
        rep2), out_shardings=(out, out))


# per-store arena in_specs for shard_map'd resolve stages (key arenas:
# rows over 'data', buckets over 'model'; range arenas: rows over 'data')
_KEY_ARENA_SPEC = (P("data", "model"), P("data", None), P("data"), P("data"))
_RNG_ARENA_SPEC = (P("data"), P("data"), P("data", None), P("data"),
                   P("data"))


def _fused_key_resolve_blocks(nstores, sof, sk, sst, sb, sknd, sl, ars, tbl):
    """Per-shard LOCAL key-resolve packed blocks, one per store: the body
    shared by sharded_fused_deps_resolve and the sharded protocol
    megakernel (must run inside a shard_map over ('data', 'model')). The
    subject bitmap is built once per shard restricted to the local bucket
    slice; each arena block applies its store's slot mask and packs its own
    lane block."""
    from accord_tpu.ops.kernels import _lex_before, _pack_bits
    b = sb.shape[0]
    k_local = ars[0][0].shape[1]
    base = jax.lax.axis_index("model") * k_local
    col = sk - base
    col = jnp.where((col >= 0) & (col < k_local), col, k_local)
    subj_bm = jnp.zeros((b, k_local), jnp.float32) \
        .at[sof, col].max(1.0, mode="drop").astype(jnp.bfloat16)
    outs = []
    for s in range(nstores):
        bm, ts, kinds, valid = ars[s]
        partial = jax.lax.dot_general(
            subj_bm, bm.astype(jnp.bfloat16),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        overlap = jax.lax.psum(partial, "model") > 0.5
        witness = tbl[sknd[:, None], kinds[None, :]] == 1
        before = _lex_before(ts[None, :, :], sb[:, None, :])
        mine = (sst == sl[s])[:, None]
        outs.append(_pack_bits(
            overlap & witness & before & valid[None, :] & mine))
    return outs


def _fused_range_resolve_blocks(nr, nk, model, ivo, ivs, ive, sst, sb, sknd,
                                srng, rsl, rars, ksl, kars, tbl):
    """Per-shard LOCAL range-resolve packed blocks -- (r-side list, k-side
    list), shared like _fused_key_resolve_blocks. NR range arenas answer
    the interval stab over their 'data' row blocks; NK key arenas contract
    the subject intervals' bucket coverage over 'model'."""
    from accord_tpu.ops.kernels import _lex_before, _pack_bits
    b = sb.shape[0]
    routs = []
    for s in range(nr):
        rs, re_, rts, rkd, rvl = rars[s]
        rcap_l = rs.shape[0]
        hit_r = (ivs[:, None] < re_[None, :]) \
            & (rs[None, :] < ive[:, None])
        any_r = jnp.zeros((b, rcap_l), jnp.int32) \
            .at[ivo].max(hit_r.astype(jnp.int32), mode="drop") > 0
        witness_r = tbl[sknd[:, None], rkd[None, :]] == 1
        before_r = _lex_before(rts[None, :, :], sb[:, None, :])
        mine = (sst == rsl[s])[:, None]
        routs.append(_pack_bits(
            any_r & witness_r & before_r & rvl[None, :] & mine))
    kouts = []
    if nk:
        cov = _covered_buckets(ivo, ivs, ive, b, kars[0][0].shape[1], model)
        for s in range(nk):
            bm, kts, kknd, kvl = kars[s]
            partial = jax.lax.dot_general(
                cov, bm.astype(jnp.bfloat16),
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            any_k = jax.lax.psum(partial, "model") > 0.5
            witness_k = tbl[sknd[:, None], kknd[None, :]] == 1
            before_k = _lex_before(kts[None, :, :], sb[:, None, :])
            mine = (sst == ksl[s])[:, None] & srng[:, None]
            kouts.append(_pack_bits(
                any_k & witness_k & before_k & kvl[None, :] & mine))
    return routs, kouts


@functools.lru_cache(maxsize=32)
def sharded_fused_deps_resolve(mesh: Mesh, nstores: int):
    """Mesh-sharded twin of ops.kernels.fused_deps_resolve: one call
    resolves subjects against NSTORES arenas, each sharded like
    sharded_deps_resolve (rows over 'data', buckets over 'model'). The
    subject bitmap is built once per shard; each arena block applies its
    store's slot mask and packs its own lane block
    (_fused_key_resolve_blocks, shared with the sharded protocol
    megakernel), and the per-store blocks concatenate OUTSIDE the shard_map
    (inside, the 'data'-sharded lane axes would interleave across stores)
    -- and outside the jit, via _concat_lane_blocks (see its docstring for
    the sharded-axis concat miscompile it routes around). lru_cached by
    (mesh, store count) so same-width dispatches share one compiled
    kernel."""

    def run(subj_of, subj_keys, subj_store, subj_before, subj_kinds,
            slots, arenas, table):
        def part(sof, sk, sst, sb, sknd, sl, ars, tbl):
            return tuple(_fused_key_resolve_blocks(
                nstores, sof, sk, sst, sb, sknd, sl, ars, tbl))

        arena_specs = tuple(_KEY_ARENA_SPEC for _ in range(nstores))
        return shard_map(
            part, mesh=mesh,
            in_specs=(P(None), P(None), P(None), P(None, None), P(None),
                      P(None), arena_specs, P(None, None)),
            out_specs=tuple(P(None, "data") for _ in range(nstores)),
        )(subj_of, subj_keys, subj_store, subj_before, subj_kinds,
          slots, arenas, table)

    jitted = jax.jit(run)

    def call(subj_of, subj_keys, subj_store, subj_before, subj_kinds,
             slots, arenas, table):
        blocks = jitted(subj_of, subj_keys, subj_store, subj_before,
                        subj_kinds, slots, arenas, table)
        return _concat_lane_blocks(mesh, blocks)

    return call


@functools.lru_cache(maxsize=32)
def sharded_fused_range_deps_resolve(mesh: Mesh, nr: int, nk: int):
    """Mesh-sharded twin of ops.kernels.fused_range_deps_resolve: NR range
    arenas (interval stab, rows over 'data') and NK key arenas
    (bucket-contracted coverage test over 'model', like
    sharded_range_deps_resolve) answer one fused call; per-store blocks
    concatenate outside the shard_map and outside the jit via
    _concat_lane_blocks (see its docstring); the per-shard body is
    _fused_range_resolve_blocks, shared with the sharded protocol
    megakernel. Empty sides return a (b, 0) packed array the caller
    discards."""
    model = mesh.shape["model"]

    def run(iv_of, iv_start, iv_end, subj_store, subj_before, subj_kinds,
            subj_is_range, r_slots, rarenas, k_slots, karenas, table):
        def part(ivo, ivs, ive, sst, sb, sknd, srng,
                 rsl, rars, ksl, kars, tbl):
            routs, kouts = _fused_range_resolve_blocks(
                nr, nk, model, ivo, ivs, ive, sst, sb, sknd, srng,
                rsl, rars, ksl, kars, tbl)
            return tuple(routs) + tuple(kouts)

        rarena_specs = tuple(_RNG_ARENA_SPEC for _ in range(nr))
        karena_specs = tuple(_KEY_ARENA_SPEC for _ in range(nk))
        return shard_map(
            part, mesh=mesh,
            in_specs=(P(None), P(None), P(None), P(None), P(None, None),
                      P(None), P(None), P(None), rarena_specs, P(None),
                      karena_specs, P(None, None)),
            out_specs=tuple(P(None, "data") for _ in range(nr + nk)),
        )(iv_of, iv_start, iv_end, subj_store, subj_before, subj_kinds,
          subj_is_range, r_slots, rarenas, k_slots, karenas, table)

    jitted = jax.jit(run)

    def call(iv_of, iv_start, iv_end, subj_store, subj_before, subj_kinds,
             subj_is_range, r_slots, rarenas, k_slots, karenas, table):
        blocks = jitted(iv_of, iv_start, iv_end, subj_store, subj_before,
                        subj_kinds, subj_is_range, r_slots, rarenas,
                        k_slots, karenas, table)
        b = subj_before.shape[0]
        rpacked = _concat_lane_blocks(mesh, blocks[:nr]) if nr \
            else jnp.zeros((b, 0), jnp.uint32)
        kpacked = _concat_lane_blocks(mesh, blocks[nr:]) if nk \
            else jnp.zeros((b, 0), jnp.uint32)
        return rpacked, kpacked

    return call


def sharded_node_tick(mesh: Mesh, key_merge, range_merge, table):
    """Multi-chip twin of the node-lane cluster tick (ops/node_lane.py):
    evaluate a whole cluster's merged key/range deps-resolve dispatches on
    the mesh, sharding the node-major BLOCK axis over 'data' rows and
    reusing the existing 'model'-axis kid-table sharding. The merged
    node-lane inputs are exactly a fused cross-store call with more blocks
    and a node-qualified slot space, so this delegates to the lru-cached
    sharded fused kernels at the merge's block-count tier -- same math,
    same `_concat_lane_blocks` readback layout, so the engine's per-plan
    span demux is unchanged. Returns (packed, rpacked, kpacked), any of
    them None when that merge is absent."""
    packed = rpacked = kpacked = None
    if key_merge is not None and key_merge.blocks:
        kern = sharded_fused_deps_resolve(mesh, len(key_merge.blocks))
        packed = kern(
            jnp.asarray(key_merge.subj_of), jnp.asarray(key_merge.subj_keys),
            jnp.asarray(key_merge.subj_node), jnp.asarray(key_merge.sb),
            jnp.asarray(key_merge.sknd), jnp.asarray(key_merge.slots),
            key_merge.blocks, table)
    if range_merge is not None \
            and (range_merge.r_blocks or range_merge.k_blocks):
        kern = sharded_fused_range_deps_resolve(
            mesh, len(range_merge.r_blocks), len(range_merge.k_blocks))
        rpacked, kpacked = kern(
            jnp.asarray(range_merge.iv_of), jnp.asarray(range_merge.iv_s),
            jnp.asarray(range_merge.iv_e),
            jnp.asarray(range_merge.subj_node),
            jnp.asarray(range_merge.sb), jnp.asarray(range_merge.sknd),
            jnp.asarray(range_merge.srng), jnp.asarray(range_merge.r_slots),
            range_merge.r_blocks, jnp.asarray(range_merge.k_slots),
            range_merge.k_blocks, table)
    return packed, rpacked, kpacked


def _sharded_finalize_body(mesh: Mesh, packed, word_off, kid_rows,
                           slot_subj, slot_kid, subj_row, act_ts,
                           out_cap: int):
    """Mesh-sharded twin of ops.kernels._finalize_csr_body: the
    finalized-CSR COMPACTION distributed over 'data' word columns, shared
    by the standalone sharded_finalize_csr jit and the sharded protocol
    megakernel (which inlines it per canonically-sorted finalize spec).
    Each shard holds a
    contiguous block of every kid-table row mask and of the packed
    candidate words (P(None, 'data') -- the layout the sharded candidate
    kernels already emit), so the AND + self-bit clear + SWAR popcount all
    run on local slices and no device materializes the full
    (slots x cap) bit matrix:

      1. per-shard popcount -> counts_local i32[S];
      2. all_gather over 'data' -> the global per-slot counts (summed:
         indptr) AND each shard's exclusive prefix within its slot's
         segment (write base);
      3. each shard compacts ITS nonzero words at (segment base + lower
         shards' counts + local bit prefix) -- disjoint global positions
         by construction -- and emits its fragment as a 'data'-stacked
         lane block; the fragments sum-merge (disjoint positions, zeros
         elsewhere) outside the shard_map body, in the same jit.

    The device out-cap BOUND (the kid-table row-mask popcount riding back
    with each result) is additionally sharded over the 'model' axis: each
    model replica popcounts a contiguous slot block of the kid table and a
    psum over 'model' restores the replicated scalar, so the model lanes
    stop duplicating the full [slots x words] SWAR pass.

    Word order equals row order and shards partition words contiguously,
    so (indptr, dep_rows, dep_ts, bound, csum) is bit-identical to the
    single-device finalize_csr -- the csr_checksum integrity word is
    computed over the MERGED triple, after the fragment sum, so it folds
    exactly the arrays the harvest will read back. Overflow keeps the
    same contract
    (indptr[-1] > out_cap; the exact total comes from the gathered counts,
    never from the possibly-dropped scatters)."""
    from accord_tpu.ops.kernels import _popcount_u32
    data = mesh.shape["data"]
    model = mesh.shape["model"]

    b = packed.shape[0]
    kc, w = kid_rows.shape
    blk = jax.lax.dynamic_slice_in_dim(packed, word_off, w, axis=1)

    def part(blk_l, kid_l, ssub, skid, srow):
        wl = blk_l.shape[1]
        d = jax.lax.axis_index("data")
        base_w = d * wl
        s = ssub.shape[0]
        ok = (ssub >= 0) & (ssub < b) & (skid >= 0) & (skid < kc)
        kid_m = kid_l[jnp.clip(skid, 0, kc - 1)]
        if s % model == 0:
            # kid-table popcount sharded over 'model': each model
            # replica bounds a contiguous slot block (the nnz tiers
            # are 32-multiples, so the split is exact), psum restores
            # the model-replicated scalar the out_specs promise --
            # integer partial sums, so bit-identical to the full
            # reduction the single-device kernel computes
            mi = jax.lax.axis_index("model")
            sl = s // model
            skid_b = jax.lax.dynamic_slice_in_dim(skid, mi * sl, sl)
            ok_b = jax.lax.dynamic_slice_in_dim(ok, mi * sl, sl)
            kid_b = kid_l[jnp.clip(skid_b, 0, kc - 1)]
            bound_l = jax.lax.psum(jnp.sum(jnp.where(
                ok_b,
                jnp.sum(_popcount_u32(kid_b), axis=1, dtype=jnp.int32),
                0), dtype=jnp.int32), "model")
        else:
            bound_l = jnp.sum(jnp.where(
                ok,
                jnp.sum(_popcount_u32(kid_m), axis=1, dtype=jnp.int32),
                0), dtype=jnp.int32)
        so = jnp.clip(ssub, 0, b - 1)
        m = jnp.where(ok[:, None], blk_l[so] & kid_m, jnp.uint32(0))
        r = srow[so]
        widx = base_w + jnp.arange(wl, dtype=jnp.int32)
        selfbit = jnp.where(
            (r >= 0)[:, None] & (widx[None, :] == (r >> 5)[:, None]),
            (jnp.uint32(1) << (r & 31).astype(jnp.uint32))[:, None],
            jnp.uint32(0))
        m = m & ~selfbit
        pop = _popcount_u32(m)                            # i32[S, wl]
        counts_l = jnp.sum(pop, axis=1, dtype=jnp.int32)  # i32[S]
        counts_all = jax.lax.all_gather(counts_l, "data")  # i32[D, S]
        counts = jnp.sum(counts_all, axis=0)
        seg0 = jnp.cumsum(counts, dtype=jnp.int32) - counts
        # this shard's exclusive write base within each slot's segment
        prefix = jnp.sum(jnp.where(
            jnp.arange(data, dtype=jnp.int32)[:, None] < d,
            counts_all, 0), axis=0, dtype=jnp.int32)
        seg_base = seg0 + prefix
        # local word compaction (kernels._packed_segment_compact with
        # shard-global bit offsets and row bases)
        flat_pop = pop.reshape(-1)
        flat_val = m.reshape(-1)
        within_seg = jnp.cumsum(pop, axis=1, dtype=jnp.int32) - pop
        bit_off = (seg_base[:, None] + within_seg).reshape(-1)
        nz = flat_pop > 0
        slot = jnp.where(
            nz, jnp.cumsum(nz.astype(jnp.int32), dtype=jnp.int32) - 1,
            out_cap)
        src = jnp.zeros(out_cap, jnp.int32) \
            .at[slot].set(jnp.arange(s * wl, dtype=jnp.int32),
                          mode="drop")
        live = jnp.arange(out_cap, dtype=jnp.int32) \
            < jnp.sum(nz.astype(jnp.int32))
        cw_val = jnp.where(live, flat_val[src], jnp.uint32(0))
        cw_off = bit_off[src]
        cw_row = (base_w + src % wl) * 32
        bits = ((cw_val[:, None] >> jnp.arange(32, dtype=jnp.uint32))
                & 1).astype(jnp.int32)
        within = jnp.cumsum(bits, axis=1, dtype=jnp.int32) - bits
        pos = jnp.where((bits > 0) & live[:, None],
                        cw_off[:, None] + within, out_cap)
        rows = cw_row[:, None] + jnp.arange(32, dtype=jnp.int32)[None, :]
        frag = jnp.zeros(out_cap, jnp.int32) \
            .at[pos.reshape(-1)].set(rows.reshape(-1), mode="drop")
        return counts_l[None], frag[None], bound_l[None]

    counts_all, frags, bounds = shard_map(
        part, mesh=mesh,
        in_specs=(P(None, "data"), P(None, "data"), P(None), P(None),
                  P(None)),
        out_specs=(P("data", None), P("data", None), P("data")),
    )(blk, kid_rows, slot_subj, slot_kid, subj_row)
    counts = jnp.sum(counts_all, axis=0)
    indptr = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(counts, dtype=jnp.int32)])
    dep_rows = jnp.sum(frags, axis=0)
    bound = jnp.sum(bounds, dtype=jnp.int32)
    dep_ts = act_ts[dep_rows]
    from accord_tpu.ops.kernels import csr_checksum
    return (indptr, dep_rows, dep_ts, bound,
            csr_checksum(indptr, dep_rows, dep_ts))


@functools.lru_cache(maxsize=8)
def sharded_finalize_csr(mesh: Mesh):
    """Standalone jit over _sharded_finalize_body (the unfused dispatch
    the sharded resolver uses when the megakernel is off). lru_cached by
    mesh: every resolver on the mesh shares one compiled kernel per
    (shape, out_cap)."""

    def run(packed, word_off, kid_rows, slot_subj, slot_kid,
            subj_row, act_ts, out_cap: int):
        return _sharded_finalize_body(mesh, packed, word_off, kid_rows,
                                      slot_subj, slot_kid, subj_row,
                                      act_ts, out_cap)

    return jax.jit(run, static_argnames=("out_cap",))


# -- the sharded protocol megakernel ------------------------------------------

_SHARDED_TICK_FNS: dict = {}


def _sharded_tick_fn(mesh: Mesh, statics):
    """Build (or fetch) the one fused mesh program for a tick-signature
    multiset: a single jax.jit composing shard_map regions for the
    node-lane resolve, every finalize compaction, the cross-shard mailbox
    exchange, and the replicated cmd/quorum/repair stages -- one XLA
    executable, so the engine's launch ledger counts exactly one dispatch
    per cluster tick, like the single-device _protocol_tick_fn."""
    key = (mesh, statics)
    fn = _SHARDED_TICK_FNS.get(key)
    if fn is not None:
        return fn
    has_key, has_rng, fin_statics, cmd_promotes, qsize, has_mail, \
        n_repairs, exec_statics = statics
    from accord_tpu.ops import kernels as _k
    from accord_tpu.ops.mailbox import _sharded_mailbox_route_part
    data = mesh.shape["data"]
    rep = NamedSharding(mesh, P(None, None))

    def assemble(blocks):
        # replicate each store's P(None, 'data') lane block before the
        # lane-axis concat -- the in-jit twin of _concat_lane_blocks'
        # workaround for the sharded-axis concat miscompile
        blocks = [jax.lax.with_sharding_constraint(blk, rep)
                  for blk in blocks]
        return blocks[0] if len(blocks) == 1 \
            else jnp.concatenate(blocks, axis=1)

    def run(witness_table, key_in, rng_in, fin_in, cmd_in, q_in,
            mail_in, rep_in, exec_in):
        packed = ()
        rng_out = ()
        if has_key:
            sof, sk, sst, sb, sknd, sl, blocks = key_in
            nstores = len(blocks)

            def kpart(sof, sk, sst, sb, sknd, sl, ars, tbl):
                return tuple(_fused_key_resolve_blocks(
                    nstores, sof, sk, sst, sb, sknd, sl, ars, tbl))

            blks = shard_map(
                kpart, mesh=mesh,
                in_specs=(P(None), P(None), P(None), P(None, None),
                          P(None), P(None),
                          tuple(_KEY_ARENA_SPEC for _ in range(nstores)),
                          P(None, None)),
                out_specs=tuple(P(None, "data") for _ in range(nstores)),
            )(sof, sk, sst, sb, sknd, sl, blocks, witness_table)
            packed = assemble(list(blks))
        if has_rng:
            (iv_of, iv_s, iv_e, snode, sb, sknd, srng, r_slots, r_blocks,
             k_slots, k_blocks) = rng_in
            nr, nk = len(r_blocks), len(k_blocks)
            model = mesh.shape["model"]

            def rpart(ivo, ivs, ive, sst, sbx, skndx, srngx, rsl, rars,
                      ksl, kars, tbl):
                routs, kouts = _fused_range_resolve_blocks(
                    nr, nk, model, ivo, ivs, ive, sst, sbx, skndx, srngx,
                    rsl, rars, ksl, kars, tbl)
                return tuple(routs) + tuple(kouts)

            blks = shard_map(
                rpart, mesh=mesh,
                in_specs=(P(None), P(None), P(None), P(None),
                          P(None, None), P(None), P(None), P(None),
                          tuple(_RNG_ARENA_SPEC for _ in range(nr)),
                          P(None),
                          tuple(_KEY_ARENA_SPEC for _ in range(nk)),
                          P(None, None)),
                out_specs=tuple(P(None, "data") for _ in range(nr + nk)),
            )(iv_of, iv_s, iv_e, snode, sb, sknd, srng, r_slots, r_blocks,
              k_slots, k_blocks, witness_table)
            b = sb.shape[0]
            rp = assemble(list(blks[:nr])) if nr \
                else jnp.zeros((b, 0), jnp.uint32)
            kp = assemble(list(blks[nr:])) if nk \
                else jnp.zeros((b, 0), jnp.uint32)
            rng_out = (rp, kp)
        fin_outs = []
        for spec, args in zip(fin_statics, fin_in):
            kind = spec[0]
            if kind == "range":
                # the range arena is tiny (tens of rows): the interval
                # stab runs replicated, like the unfused sharded path
                (iv_of, iv_s, iv_e, ent_ok, f_sb, f_sknd,
                 (r_start, r_end, r_ts, r_kinds, r_valid)) = args
                fin_outs.append(_k._range_finalize_csr_body(
                    iv_of, iv_s, iv_e, ent_ok, f_sb, f_sknd,
                    r_start, r_end, r_ts, r_kinds, r_valid,
                    witness_table, spec[1]))
            else:
                _kk, rows, words, out_cap = spec
                (r0, w_lo, word_off, kid_rows, slot_subj, slot_kid,
                 subj_row, act_ts) = args
                src = packed if kind == "key" else rng_out[1]
                blk = jax.lax.dynamic_slice(src, (r0, w_lo), (rows, words))
                fin_outs.append(_sharded_finalize_body(
                    mesh, blk, word_off, kid_rows, slot_subj, slot_kid,
                    subj_row, act_ts, out_cap))
        cmd_outs = []
        for promote, args in zip(cmd_promotes, cmd_in):
            cmd_outs.append(_k._cmd_tick_body(*args, promote=promote))
        q_out = ()
        if qsize is not None:
            q_txn, q_ts, q_code, q_valid = q_in
            fast = q_valid & ((q_code & 7) == _k.CMD_OUT_SUCCESS) \
                & jnp.all(q_ts == q_txn, axis=1)
            same = jnp.all(q_txn[:, None, :] == q_txn[None, :, :], axis=2)
            votes = jnp.sum(same & fast[None, :], axis=1, dtype=jnp.int32)
            q_out = (fast, votes, fast & (votes >= qsize))
        mail_out = ()
        if has_mail:
            def mpart(*args):
                return _sharded_mailbox_route_part(data, "data", *args)

            mail_out = shard_map(
                mpart, mesh=mesh,
                in_specs=(P("data", None), P("data", None), P("data"),
                          P("data"), P("data"), P("data"), P("data"),
                          P("data"), P("data", None), P("data", None)),
                out_specs=(P("data", None), P("data", None),
                           P("data", None), P("data", None), P("data")),
            )(*mail_in)
        rep_outs = tuple(_k._cmd_repair_body(*rep_in[i])
                         for i in range(n_repairs))
        # exec arenas are host-owned replicated lanes (like cmd/quorum):
        # the frontier compaction runs as a plain body beside the sharded
        # stages -- same source of truth as the single-device exec block
        exec_outs = tuple(_k._frontier_compact_body(exec_in[i], oc)
                          for i, oc in enumerate(exec_statics))
        return (packed, rng_out, tuple(fin_outs), tuple(cmd_outs), q_out,
                mail_out, rep_outs, exec_outs)

    fn = jax.jit(run)
    _SHARDED_TICK_FNS[key] = fn
    return fn


def sharded_protocol_tick(mesh: Mesh, witness_table, key_in=None,
                          rng_in=None, fins=(), cmds=(), quorum=None,
                          quorum_size=1, mailbox=None, cmd_repairs=(),
                          execs=()):
    """Multi-chip twin of ops.kernels.protocol_tick: ONE fused mesh
    program per cluster tick. Same argument contract (see protocol_tick's
    docstring) with `mesh` prepended; key_in/rng_in are the node-lane
    merge inputs sharded_node_tick would dispatch, fins the same finalize
    specs (key/rkey spans compact through _sharded_finalize_body's
    word-column sharding), and `mailbox` a MailboxPlane staged with
    shards == mesh.shape['data'] so the routing stage's all_to_all lands
    cross-shard payloads. Finalize specs canonically sort by static
    signature via kernels._fin_split -- the compile cache keys on the
    tick-signature multiset exactly as the single-device path does."""
    from accord_tpu.ops.kernels import _fin_split, _fin_unsort
    fin_statics, fin_traced, order = _fin_split(fins)
    cmd_statics = tuple(bool(c[-1]) for c in cmds)
    cmd_traced = tuple(tuple(c[:-1]) for c in cmds)
    exec_statics = tuple(int(oc) for (_pl, oc) in execs)
    exec_traced = tuple(tuple(tuple(p) for p in pl) for (pl, _oc) in execs)
    statics = (key_in is not None, rng_in is not None, tuple(fin_statics),
               cmd_statics, int(quorum_size) if quorum is not None else None,
               mailbox is not None, len(cmd_repairs), exec_statics)
    fn = _sharded_tick_fn(mesh, statics)
    (packed, rng_out, fin_outs, cmd_outs, q_out, mail_out, rep_outs,
     exec_outs) = fn(
        witness_table,
        tuple(key_in) if key_in is not None else (),
        tuple(rng_in) if rng_in is not None else (),
        tuple(fin_traced), cmd_traced,
        tuple(quorum) if quorum is not None else (),
        tuple(mailbox) if mailbox is not None else (),
        tuple(tuple(r) for r in cmd_repairs),
        exec_traced)
    return (packed, rng_out, _fin_unsort(fin_outs, order), cmd_outs,
            q_out, mail_out, rep_outs, exec_outs)


def sharded_protocol_tick_cache_sizes() -> int:
    """Total compiled sharded_protocol_tick variants across every
    (mesh, static signature) -- folded into kernels.jit_cache_sizes."""
    return sum(f._cache_size() for f in _SHARDED_TICK_FNS.values())


def warmup_sharded(mesh: Mesh, num_buckets: int = 256, cap: int = 4096,
                   batch_tiers: Tuple[int, ...] = (8, 64, 128),
                   nnz_tiers: Optional[Tuple[int, ...]] = None,
                   range_cap: Optional[int] = None,
                   store_tiers: Tuple[int, ...] = (1, 2),
                   out_tiers: Tuple[int, ...] = (),
                   kid_cap: int = 4096,
                   cmd_caps: Tuple[int, ...] = (),
                   cmd_key_caps: Tuple[int, ...] = (1024,),
                   cmd_kpad: int = 4,
                   cmd_op_tiers: Optional[Tuple[int, ...]] = None,
                   cmd_promote_modes: Tuple[bool, ...] = (False,),
                   node_tiers: Tuple[int, ...] = (),
                   node_batch_tiers: Optional[Tuple[int, ...]] = None,
                   mega_quorum_sizes: Tuple[int, ...] = (),
                   mega_lane_tiers: Optional[Tuple[int, ...]] = None,
                   exec_caps: Tuple[int, ...] = (),
                   exec_tiers: Tuple[int, ...] = (),
                   recovery_tiers: Tuple[int, ...] = ()) -> None:
    """Pre-compile the sharded hot kernels' (batch tier, nnz tier, store
    tier) jit cross product (the sharded twin of ops.resolver.warmup; same
    padding ladders the overlapped pipeline dispatches). Store tiers >= 2
    warm the fused cross-store kernels; single-group dispatches reuse the
    plain kernels. `out_tiers` additionally warms the sharded finalize
    compaction over (batch x nnz x out_cap) at `kid_cap` -- with the
    resolver's OutCapTiers hysteresis pinning tiers, this covers every
    finalize shape a steady-state burn dispatches. One call covers every
    ShardedBatchDepsResolver on the same mesh + (num_buckets, cap,
    range_cap) -- the kernel builders are lru_cached by (mesh, width) and
    jit caches by shape. `cmd_caps` (opt-in) folds in the device
    coordination plane's warmup (cmd_tick + its lane scatters) -- the cmd
    arena is store-local and replicated, so the single-device variants are
    the ones a sharded deployment dispatches too. `node_tiers` (opt-in)
    warms the cluster-tick node-lane path (`sharded_node_tick` delegates to
    the fused kernels at the merge's block-count tier) across every
    (block tier x merged-row tier x nnz tier) -- the sharded twin of
    ops.resolver.warmup's node_tiers. `mega_quorum_sizes` (opt-in) warms
    the sharded protocol megakernel's quorum-count stage across the lane
    tiers a megakernel burn pads PreAccept spans to -- the sharded twin of
    resolver.warmup's mega block. `exec_tiers` / `recovery_tiers` (opt-in)
    warm the compacted exec-frontier and recovery-scan blocks through the
    sharded megakernel's exec-only variant (the exec arenas are host-owned
    replicated lanes, so the bodies match the single-device kernels bit for
    bit) across (`exec_caps` x plane count x out_cap) and (`cmd_caps` x
    out_cap) respectively."""
    from accord_tpu.ops.encoding import WITNESS_TABLE
    from accord_tpu.ops.kernels import NNZ_TIERS
    if nnz_tiers is None:
        nnz_tiers = NNZ_TIERS
    if range_cap is None:
        range_cap = max(64, 32 * mesh.shape["data"])
    kern = sharded_deps_resolve(mesh)
    rkern = sharded_range_deps_resolve(mesh)
    bm = jnp.zeros((cap, num_buckets), jnp.float32)
    ts = jnp.zeros((cap, 3), jnp.int32)
    kinds = jnp.zeros(cap, jnp.int32)
    valid = jnp.zeros(cap, bool)
    rs = jnp.zeros(range_cap, jnp.int32)
    re_ = jnp.zeros(range_cap, jnp.int32)
    rts = jnp.zeros((range_cap, 3), jnp.int32)
    rkd = jnp.zeros(range_cap, jnp.int32)
    rvl = jnp.zeros(range_cap, bool)
    table = jnp.asarray(WITNESS_TABLE)
    out = None
    for b in batch_tiers:
        sb = jnp.zeros((b, 3), jnp.int32)
        sknd = jnp.zeros(b, jnp.int32)
        srng = jnp.zeros(b, bool)
        sst = jnp.zeros(b, jnp.int32)
        for z in nnz_tiers:
            of = jnp.full(z, b, jnp.int32)
            zz = jnp.zeros(z, jnp.int32)
            out = kern(of, zz, sb, sknd, bm, ts, kinds, valid, table)
            out = rkern(of, zz, zz, sb, sknd, srng,
                        rs, re_, rts, rkd, rvl,
                        bm, ts, kinds, valid, table)
            for s in store_tiers:
                if s < 2:
                    continue  # single group runs the plain kernels
                fkern = sharded_fused_deps_resolve(mesh, s)
                frkern = sharded_fused_range_deps_resolve(mesh, s, s)
                slots = jnp.arange(s, dtype=jnp.int32)
                arenas = tuple((bm, ts, kinds, valid) for _ in range(s))
                out = fkern(of, zz, sst, sb, sknd, slots, arenas, table)
                rarenas = tuple((rs, re_, rts, rkd, rvl) for _ in range(s))
                out = frkern(of, zz, zz, sst, sb, sknd, srng,
                             slots, rarenas, slots, arenas, table)
    if out_tiers:
        fin = sharded_finalize_csr(mesh)
        w = cap // 32
        kid_rows = jnp.zeros((kid_cap, w), jnp.uint32)
        zero_off = jnp.asarray(0, jnp.int32)
        for b in batch_tiers:
            srow = jnp.full(b, -1, jnp.int32)
            # the live packed words arrive lane-sharded out of the sharded
            # candidate kernels; warm with the same committed sharding or
            # the jit lowers a second, single-device entry
            packed = jax.device_put(
                jnp.zeros((b, w), jnp.uint32),
                NamedSharding(mesh, P(None, "data")))
            for z in nnz_tiers:
                subj = jnp.full(z, b, jnp.int32)
                kidx = jnp.full(z, kid_cap, jnp.int32)
                for oc in out_tiers:
                    out = fin(packed, zero_off, kid_rows, subj, kidx,
                              srow, ts, out_cap=oc)
    if cmd_caps:
        from accord_tpu.ops.cmd_plane import (CMD_OP_TIERS,
                                              warmup_cmd_plane)
        warmup_cmd_plane(
            caps=cmd_caps, key_caps=cmd_key_caps, kpad=cmd_kpad,
            op_tiers=(CMD_OP_TIERS if cmd_op_tiers is None
                      else cmd_op_tiers),
            promote_modes=cmd_promote_modes)
    if node_tiers:
        from accord_tpu.ops.node_lane import NODE_SUBJECT_TIERS
        nb_tiers = (tuple(node_batch_tiers) if node_batch_tiers is not None
                    else NODE_SUBJECT_TIERS[:2])
        for nblk in node_tiers:
            fkern = sharded_fused_deps_resolve(mesh, nblk)
            frkern = sharded_fused_range_deps_resolve(mesh, nblk, nblk)
            slots = jnp.arange(nblk, dtype=jnp.int32)
            arenas = tuple((bm, ts, kinds, valid) for _ in range(nblk))
            rarenas = tuple((rs, re_, rts, rkd, rvl) for _ in range(nblk))
            for b in nb_tiers:
                sb = jnp.zeros((b, 3), jnp.int32)
                sknd = jnp.zeros(b, jnp.int32)
                srng = jnp.zeros(b, bool)
                snode = jnp.zeros(b, jnp.int32)
                for z in nnz_tiers:
                    of = jnp.full(z, b, jnp.int32)
                    zz = jnp.zeros(z, jnp.int32)
                    out = fkern(of, zz, snode, sb, sknd, slots, arenas,
                                table)
                    out = frkern(of, zz, zz, snode, sb, sknd, srng, slots,
                                 rarenas, slots, arenas, table)
    if mega_quorum_sizes:
        from accord_tpu.ops.tiers import MEGA_LANE_TIERS
        lt = (tuple(mega_lane_tiers) if mega_lane_tiers is not None
              else MEGA_LANE_TIERS[:2])
        for qs in mega_quorum_sizes:
            for t in lt:
                out = sharded_protocol_tick(
                    mesh, table,
                    quorum=(jnp.zeros((t, 3), jnp.int32),
                            jnp.zeros((t, 3), jnp.int32),
                            jnp.zeros(t, jnp.int32),
                            jnp.zeros(t, bool)),
                    quorum_size=qs)[4][2]
    if exec_tiers:
        from accord_tpu.ops.kernels import frontier_compact
        neg = np.iinfo(np.int32).min
        for ecap in (tuple(exec_caps) or (1024,)):
            plane = (jnp.zeros((ecap, ecap), bool),
                     jnp.full((ecap, 3), neg, jnp.int32),
                     jnp.zeros(ecap, bool), jnp.zeros(ecap, bool),
                     jnp.zeros(ecap, bool))
            counts = (1,) + tuple(s for s in store_tiers if s > 1)
            for n in counts:
                planes = tuple(plane for _ in range(n))
                for oc in exec_tiers:
                    # both homes: the standalone coordinator dispatch and
                    # the engine's exec-only fused flush on this mesh
                    out = frontier_compact(planes, out_cap=oc)[0]
                    out = sharded_protocol_tick(
                        mesh, table, execs=((planes, oc),))[7][0][0]
    if recovery_tiers:
        # the cmd arena is store-local and replicated: sharded deployments
        # dispatch the same single-device recovery_scan
        from accord_tpu.ops.kernels import recovery_scan
        for ccap in (tuple(cmd_caps) or (1024,)):
            st = jnp.zeros(ccap, jnp.int32)
            tm = jnp.zeros(ccap, jnp.int32)
            for oc in recovery_tiers:
                out = recovery_scan(st, tm, np.int32(0), np.int32(0),
                                    out_cap=oc)[0]
    if out is not None:
        jax.block_until_ready(out)


def example_batch(n: int = 64, k: int = 256, seed: int = 0):
    """Deterministic example inputs for compile checks and dry runs."""
    rng = np.random.default_rng(seed)
    bitmaps = (rng.random((n, k)) < 0.05).astype(np.float32)
    hlcs = np.sort(rng.integers(0, 100_000, n))
    ts = np.stack([np.zeros(n, np.int32), hlcs.astype(np.int32),
                   rng.integers(0, 1 << 16, n).astype(np.int32)], axis=1)
    kinds = rng.integers(0, 2, n).astype(np.int32)  # READ/WRITE mix
    from accord_tpu.ops.encoding import WITNESS_TABLE
    return bitmaps, ts, kinds, WITNESS_TABLE.copy()


def example_resolve_batch(cap: int = 512, k: int = 256, b: int = 16,
                          nnz: int = 64, seed: int = 0):
    """Deterministic random inputs in deps_resolve's exact signature shape
    (CSR subject entries padded with out-of-bounds row B, 3-lane int32
    timestamps, arena lanes) -- shared by the dry-run and the
    sharded-vs-single differential tests so the invariants live in one
    place."""
    from accord_tpu.ops.encoding import WITNESS_TABLE
    rng = np.random.default_rng(seed)
    live = rng.random(nnz) < 0.6
    subj_of = np.where(live, rng.integers(0, b, nnz), b).astype(np.int32)
    subj_keys = rng.integers(0, k, nnz).astype(np.int32)
    sb = np.stack([np.zeros(b, np.int32),
                   rng.integers(1000, 100_000, b).astype(np.int32),
                   rng.integers(0, 100, b).astype(np.int32)], 1)
    sknd = rng.integers(0, 5, b).astype(np.int32)
    act_bm = (rng.random((cap, k)) < 0.05).astype(np.float32)
    act_ts = np.stack([np.zeros(cap, np.int32),
                       rng.integers(0, 90_000, cap).astype(np.int32),
                       rng.integers(0, 100, cap).astype(np.int32)], 1)
    act_kinds = rng.integers(0, 5, cap).astype(np.int32)
    act_valid = rng.random(cap) < 0.9
    return (subj_of, subj_keys, sb, sknd, act_bm, act_ts, act_kinds,
            act_valid, WITNESS_TABLE.copy())
