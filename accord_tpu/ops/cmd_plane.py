"""Device-resident coordination plane: the per-txn protocol state machines
of local/commands.py (PreAccept witness, Accept ballot checks, Commit/Apply
status promotions) restructured as SoA arena columns on device and evaluated
in batches by ONE kernel dispatch (ops/kernels.cmd_tick).

The Python handlers stay authoritative for everything the device cannot hold
(routes, deps objects, wait graphs, progress logs): a device-evaluated op is
followed by a HOST RESIDUAL that replays the handler's side effects with the
decision (witnessed timestamp, outcome code, status promotion) taken from the
kernel output instead of recomputed. The differential contract -- asserted by
tests/test_cmd_plane.py -- is that cmd_plane=True and cmd_plane=False produce
bit-identical status histories, executeAt choices and HLC clocks.

Arena columns (int lanes; generation-pinned compaction; per-field dirty masks
uploaded through ops/deltas.flush_lane, same discipline as the PR 5 exec
plane):

    status      i32[cap]     Status ladder value
    flags       i32[cap]     bit0 = definition recorded (cmd.txn is not None)
    promised    i32[cap,3]   promised ballot lanes
    accepted    i32[cap,3]   accepted ballot lanes
    execute_at  i32[cap,3]   executeAt lanes (INT32_MIN lanes == None)
    durability  i32[cap]     Durability ladder value
    kmax        i32[kcap,3]  per-key max-conflict lanes (MaxConflicts twin)
    kmax_valid  bool[kcap]

Timestamps ride ABSOLUTE base-(0,0) lanes -- lane0 epoch, lane1 hlc, lane2
(flags << 16 | node) - 2^31 -- so TxnId lanes double as txn_id.as_timestamp()
(TxnId.as_timestamp keeps the flags) and the packed lex order equals the host
Timestamp total order.

Admission is conservative: an op the kernel cannot evaluate exactly (reject /
truncation floors active, range-domain conflicts, sync points, out-of-window
lanes, too many owned keys) falls back to the host handler and is counted in
cmd_plane_fallbacks. Order is preserved: an inadmissible op flushes the
pending device run first.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from accord_tpu.local.status import Durability, Status
from accord_tpu.obs.metrics import MetricsRegistry, RegCounter, RegTimer
from accord_tpu.primitives.keyspace import Keys
from accord_tpu.primitives.timestamp import (Ballot, Timestamp, TxnId,
                                             TxnKind)

_NEG = np.iinfo(np.int32).min
_WINDOW = (1 << 31) - 1
_LANE2_OFF = 1 << 31
# Ballot.ZERO's absolute lanes: lane2 = (0 << 16 | 0) - 2^31, NOT 0 -- a
# zeroed lane2 would compare above every real ballot
_BAL0 = (0, 0, -_LANE2_OFF)

# the kernel mirrors these ladders as plain ints; a drifting enum would turn
# into silent protocol corruption, so pin them at import
from accord_tpu.ops.kernels import (CMD_F_DEPS_EMPTY, CMD_F_EPOCH_OK,  # noqa: E402
                                    CMD_F_EXPIRED, CMD_F_MSG_HAS_TXN,
                                    CMD_F_PERMIT_FAST, CMD_F_VALID,
                                    CMD_OP_ACCEPT, CMD_OP_APPLY,
                                    CMD_OP_COMMIT, CMD_OP_PREACCEPT,
                                    CMD_OP_TIERS, CMD_OUT_INCONSISTENT_BIT,
                                    CMD_OUT_REDUNDANT, CMD_OUT_REJECTED_BALLOT,
                                    CMD_OUT_SUCCESS, CMD_OUT_TRUNCATED,
                                    CMD_OUT_WAS_STABLE_BIT, CMD_ST_ACCEPTED,
                                    CMD_ST_APPLIED, CMD_ST_INVALIDATED,
                                    CMD_ST_PRE_ACCEPTED, CMD_ST_PRE_APPLIED,
                                    CMD_ST_READY, CMD_ST_STABLE,
                                    CMD_ST_TRUNCATED, cmd_checksum_host,
                                    cmd_op_tier, cmd_tick)

assert int(Status.PRE_ACCEPTED) == CMD_ST_PRE_ACCEPTED
assert int(Status.ACCEPTED) == CMD_ST_ACCEPTED
assert int(Status.STABLE) == CMD_ST_STABLE
assert int(Status.READY_TO_EXECUTE) == CMD_ST_READY
assert int(Status.PRE_APPLIED) == CMD_ST_PRE_APPLIED
assert int(Status.APPLIED) == CMD_ST_APPLIED
assert int(Status.INVALIDATED) == CMD_ST_INVALIDATED
assert int(Status.TRUNCATED) == CMD_ST_TRUNCATED


def _enc(ts) -> Tuple[int, int, int]:
    """Timestamp/TxnId/Ballot -> absolute base-(0,0) lanes."""
    return (ts.epoch, ts.hlc, ((ts.flags << 16) | ts.node) - _LANE2_OFF)


def _dec(l0: int, l1: int, l2: int) -> Timestamp:
    v = int(l2) + _LANE2_OFF
    return Timestamp(int(l0), int(l1), v >> 16, v & 0xFFFF)


def _in_window(ts) -> bool:
    return 0 <= ts.epoch < _WINDOW and 0 <= ts.hlc < _WINDOW


class CmdOp:
    """One protocol transition queued for batched device evaluation."""

    __slots__ = ("kind", "txn_id", "txn", "route", "ballot", "execute_at",
                 "deps", "writes", "result", "keys", "owned")

    def __init__(self, kind, txn_id, txn=None, route=None,
                 ballot=Ballot.ZERO, execute_at=None, deps=None,
                 writes=None, result=None, keys=None):
        self.kind = kind
        self.txn_id = txn_id
        self.txn = txn
        self.route = route
        self.ballot = ballot
        self.execute_at = execute_at
        self.deps = deps
        self.writes = writes
        self.result = result
        self.keys = keys
        self.owned = None   # filled by admission

    @staticmethod
    def preaccept(txn_id, txn, route, ballot=Ballot.ZERO) -> "CmdOp":
        return CmdOp(CMD_OP_PREACCEPT, txn_id, txn=txn, route=route,
                     ballot=ballot)

    @staticmethod
    def accept(txn_id, ballot, route, keys, execute_at,
               deps=None) -> "CmdOp":
        return CmdOp(CMD_OP_ACCEPT, txn_id, route=route, ballot=ballot,
                     execute_at=execute_at, deps=deps, keys=keys)

    @staticmethod
    def commit(txn_id, route, txn, execute_at, deps) -> "CmdOp":
        return CmdOp(CMD_OP_COMMIT, txn_id, txn=txn, route=route,
                     execute_at=execute_at, deps=deps)

    @staticmethod
    def apply(txn_id, route, txn, execute_at, deps, writes=None,
              result=None) -> "CmdOp":
        return CmdOp(CMD_OP_APPLY, txn_id, txn=txn, route=route,
                     execute_at=execute_at, deps=deps, writes=writes,
                     result=result)


class CmdResult:
    """Outcome of one evaluated op: handler-equivalent outcome enum, the
    resulting Status, the witnessed/echoed executeAt, and the raw code."""

    __slots__ = ("outcome", "status", "execute_at", "code")

    def __init__(self, outcome, status, execute_at, code):
        self.outcome = outcome
        self.status = status
        self.execute_at = execute_at
        self.code = code

    def __repr__(self):
        return (f"CmdResult({self.outcome}, {self.status}, "
                f"{self.execute_at}, code={self.code})")


_LANES = ("status", "flags", "promised", "accepted", "execute_at",
          "durability")


class CmdPlane:
    """Per-store device command arena + batched transition evaluator.

    apply_to_store=True (the protocol mode): every device decision is
    followed by a host residual replaying the handler's side effects, so
    the Command objects / cfks / wait graphs stay authoritative and
    bit-identical to the Python path. apply_to_store=False (the arena-only
    bench mode): the arena IS the state -- empty-deps promotions
    (STABLE -> READY_TO_EXECUTE, PRE_APPLIED -> APPLIED + durability merge)
    run on device via cmd_tick(promote=True).

    authoritative=True (the cluster-tick mode, ClusterConfig
    `cmd_plane_authoritative`): device promotions run even WITH the store
    attached -- the arena decides status transitions and the host residuals
    only replay the side effects the device cannot hold (Command objects,
    cfks, wait graphs). Safe because cmd_tick's predicates are >=-band
    status compares, so arena rows running ahead of the store (STABLE ->
    READY_TO_EXECUTE, PRE_APPLIED -> APPLIED) never change a decision;
    `tests/test_cmd_plane.py` gates this differentially.
    """

    dispatches = RegCounter("cmd_plane_dispatches")
    upload_bytes = RegCounter("cmd_plane_upload_bytes")
    fastpath_device_evals = RegCounter("cmd_fastpath_device_evals")
    fallbacks = RegCounter("cmd_plane_fallbacks")
    checksum_mismatches = RegCounter("cmd_plane_checksum_mismatches")
    compactions = RegCounter("cmd_plane_compactions")
    deferred_spans = RegCounter("cmd_deferred_spans")
    deferred_ops = RegCounter("cmd_deferred_ops")
    defer_retired = RegCounter("cmd_defer_retired")
    flush_s = RegTimer("cmd_plane_flush_s")
    # recovery-candidate scan (kernels.recovery_scan): one device query per
    # progress sweep instead of the host walk over every live waiter;
    # checksum mismatch / out_cap overflow fall back to the host twin,
    # counted (the exec-plane degradation contract)
    recovery_scan_dispatches = RegCounter("recovery_scan_dispatches")
    recovery_scan_candidates = RegCounter("recovery_scan_candidates")
    recovery_scan_fallbacks = RegCounter("recovery_scan_fallbacks")
    recovery_scan_overflows = RegCounter("recovery_scan_overflows")
    recovery_scan_device_s = RegTimer("recovery_scan_device_s")
    recovery_scan_host_s = RegTimer("recovery_scan_host_s")

    def __init__(self, store, initial_cap: int = 1024, key_cap: int = 1024,
                 kpad: int = 4, apply_to_store: bool = True,
                 authoritative: bool = False):
        self.store = store
        self.kpad = int(kpad)
        self.apply_to_store = bool(apply_to_store)
        self.authoritative = bool(authoritative)
        self.metrics = MetricsRegistry()
        self._lock = threading.RLock()

        cap, kcap = int(initial_cap), int(key_cap)
        self.cap, self.kcap = cap, kcap
        self.status_h = np.zeros(cap, np.int32)
        self.flags_h = np.zeros(cap, np.int32)
        self.promised_h = np.tile(np.asarray(_BAL0, np.int32), (cap, 1))
        self.accepted_h = np.tile(np.asarray(_BAL0, np.int32), (cap, 1))
        self.ea_h = np.full((cap, 3), _NEG, np.int32)
        self.dur_h = np.zeros(cap, np.int32)
        self.kmax_h = np.full((kcap, 3), _NEG, np.int32)
        self.kvalid_h = np.zeros(kcap, bool)

        self.row_of: Dict[TxnId, int] = {}
        self.kid_of: Dict[object, int] = {}
        # row -> TxnId reverse map (dense, rows allocate sequentially):
        # lets the recovery scan translate candidate row lists back to
        # TxnIds without a per-sweep dict inversion
        self.tid_by_row: List[TxnId] = []
        self.n_rows = 0
        self.gen = 0
        self._poison: set = set()
        self._dirty: Dict[str, set] = {name: set() for name in _LANES}
        self._kdirty: set = set()
        self._device = None        # dict of jnp columns once built
        self._device_stale = True  # full rebuild pending
        # last-arena-touch times (sim ms) feeding the recovery scan's stall
        # predicate; a separate column OUTSIDE _LANES so the repair block's
        # 18-array arity is untouched -- flushed only at scan time
        self.touched_h = np.zeros(cap, np.int32)
        self._tdirty: set = set()
        self._touched_dev = None
        self._touched_stale = True
        self._tnode = None         # cached store.node handle for _touch
        self._rec_tiers = None     # OutCapTiers, built on first device scan

    # -- shadows <-> store ---------------------------------------------------

    def _shadow_of(self, name: str) -> np.ndarray:
        return {"status": self.status_h, "flags": self.flags_h,
                "promised": self.promised_h, "accepted": self.accepted_h,
                "execute_at": self.ea_h, "durability": self.dur_h}[name]

    def _sync_row(self, row: int, cmd) -> None:
        """Diff a Command's protocol fields into the shadow columns, marking
        only genuinely changed lanes dirty."""
        tid = cmd.txn_id
        for ts in (cmd.promised, cmd.accepted_ballot, cmd.execute_at):
            if ts is not None and not _in_window(ts):
                self._poison.add(tid)
                return
        vals = {
            "status": np.int32(int(cmd.status)),
            "flags": np.int32(1 if cmd.txn is not None else 0),
            "promised": np.asarray(_enc(cmd.promised), np.int32),
            "accepted": np.asarray(_enc(cmd.accepted_ballot), np.int32),
            "execute_at": (np.asarray(_enc(cmd.execute_at), np.int32)
                           if cmd.execute_at is not None
                           else np.full(3, _NEG, np.int32)),
            "durability": np.int32(int(cmd.durability)),
        }
        changed = False
        for name, v in vals.items():
            sh = self._shadow_of(name)
            if not np.array_equal(sh[row], v):
                sh[row] = v
                self._dirty[name].add(row)
                changed = True
        if changed:
            self._touch(row)

    def _touch(self, row: int) -> None:
        """Stamp a row's last-arena-touch time (recovery scan stall ages);
        a pure sim-clock read, so touching never perturbs determinism.
        Rides every changed _sync_row, so it stays lean: the node handle is
        cached on first sight and the stamp is a plain-int store."""
        node = self._tnode
        if node is None:
            node = self._tnode = getattr(self.store, "node", None)
            if node is None:
                return
        now = int(node.now_millis())
        if self.touched_h[row] != now:
            self.touched_h[row] = now
            self._tdirty.add(row)

    def on_status(self, cmd) -> None:
        """notify_listeners hook: refresh an EXISTING row from host-side
        transitions (recovery, invalidation, durability, the residuals
        themselves). Rows are created lazily at the first plane op."""
        row = self.row_of.get(cmd.txn_id)
        if row is not None:
            self._sync_row(row, cmd)

    def on_max_conflict(self, seekables, ts: Timestamp) -> None:
        """store.update_max_conflicts hook: keep seeded kid slots tracking
        the host per-key MaxConflicts fold."""
        if not isinstance(seekables, Keys) or not _in_window(ts):
            return
        lanes = np.asarray(_enc(ts), np.int32)
        for k in seekables:
            kid = self.kid_of.get(k)
            if kid is None:
                continue
            if not self.kvalid_h[kid] \
                    or tuple(self.kmax_h[kid]) < tuple(int(x) for x in lanes):
                self.kmax_h[kid] = lanes
                self.kvalid_h[kid] = True
                self._kdirty.add(kid)

    # -- row / kid allocation ------------------------------------------------

    def _grow_rows(self, need: int) -> None:
        cap = self.cap
        while cap < need:
            cap *= 2
        grow = cap - self.cap
        self.status_h = np.concatenate([self.status_h,
                                        np.zeros(grow, np.int32)])
        self.flags_h = np.concatenate([self.flags_h,
                                       np.zeros(grow, np.int32)])
        self.promised_h = np.concatenate(
            [self.promised_h,
             np.tile(np.asarray(_BAL0, np.int32), (grow, 1))])
        self.accepted_h = np.concatenate(
            [self.accepted_h,
             np.tile(np.asarray(_BAL0, np.int32), (grow, 1))])
        self.ea_h = np.concatenate(
            [self.ea_h, np.full((grow, 3), _NEG, np.int32)])
        self.dur_h = np.concatenate([self.dur_h, np.zeros(grow, np.int32)])
        self.touched_h = np.concatenate([self.touched_h,
                                         np.zeros(grow, np.int32)])
        self.cap = cap
        self._device_stale = True
        self._touched_stale = True

    def _row_for(self, txn_id: TxnId) -> int:
        row = self.row_of.get(txn_id)
        if row is not None:
            return row
        if self.n_rows >= self.cap:
            self._grow_rows(self.n_rows + 1)
        row = self.n_rows
        self.n_rows += 1
        self.row_of[txn_id] = row
        self.tid_by_row.append(txn_id)
        cmd = self.store.command_if_present(txn_id)
        if cmd is not None:
            # seed clean, then diff: a fresh row starts at the ladder floor,
            # and _sync_row dirties exactly the lanes the command moved
            self.status_h[row] = 0
            self.flags_h[row] = 0
            self.promised_h[row] = _BAL0
            self.accepted_h[row] = _BAL0
            self.ea_h[row] = _NEG
            self.dur_h[row] = 0
            self._sync_row(row, cmd)
        # no command: the row IS the device's resting default (fresh rows
        # past n_rows are never kernel-written), so nothing to upload
        return row

    def _kid_for(self, key) -> int:
        kid = self.kid_of.get(key)
        if kid is not None:
            return kid
        if len(self.kid_of) >= self.kcap:
            kcap = self.kcap * 2
            self.kmax_h = np.concatenate(
                [self.kmax_h, np.full((kcap - self.kcap, 3), _NEG,
                                      np.int32)])
            self.kvalid_h = np.concatenate(
                [self.kvalid_h, np.zeros(kcap - self.kcap, bool)])
            self.kcap = kcap
            self._device_stale = True
        kid = len(self.kid_of)
        self.kid_of[key] = kid
        seed = self.store.max_conflicts_by_key.get(key)
        if seed is not None and _in_window(seed):
            self.kmax_h[kid] = np.asarray(_enc(seed), np.int32)
            self.kvalid_h[kid] = True
        self._kdirty.add(kid)
        return kid

    def compact(self) -> None:
        """Generation-pinned compaction: drop rows whose commands reached a
        resting state (APPLIED / terminal) -- the store's Command objects
        keep the full record, so a late redundant delivery just re-seeds a
        fresh row. Ops hold TxnIds, not row indices, and rows resolve at
        dispatch time, so compaction between op construction and eval_batch
        is safe (the differential test drives exactly that interleaving)."""
        if not self.apply_to_store:
            raise RuntimeError("arena-only plane cannot compact: the arena "
                               "is the sole copy of the state")
        with self._lock:
            keep = [(tid, row) for tid, row in sorted(
                self.row_of.items(), key=lambda kv: kv[1])
                if self.status_h[row] < CMD_ST_APPLIED]
            new_row_of: Dict[TxnId, int] = {}
            for i, (tid, old) in enumerate(keep):
                for name in _LANES:
                    sh = self._shadow_of(name)
                    sh[i] = sh[old]
                self.touched_h[i] = self.touched_h[old]
                new_row_of[tid] = i
            n = len(keep)
            self.status_h[n:self.n_rows] = 0
            self.flags_h[n:self.n_rows] = 0
            self.promised_h[n:self.n_rows] = _BAL0
            self.accepted_h[n:self.n_rows] = _BAL0
            self.ea_h[n:self.n_rows] = _NEG
            self.dur_h[n:self.n_rows] = 0
            self.touched_h[n:self.n_rows] = 0
            self.row_of = new_row_of
            self.tid_by_row = [tid for tid, _old in keep]
            self.n_rows = n
            self.gen += 1
            for name in _LANES:
                self._dirty[name].clear()
            self._tdirty.clear()
            self._device_stale = True
            self._touched_stale = True
            self.compactions += 1

    # -- admission -----------------------------------------------------------

    def _store_ok(self) -> bool:
        s = self.store
        return (s.truncated_before.is_empty()
                and s.reject_before.is_empty()
                and s.max_conflicts.is_empty())

    def _admit(self, op: CmdOp, store_ok: bool) -> bool:
        """Exact-evaluation precondition; False routes the op to the host
        handler. Computes op.owned (the kid-slot key set) as a side effect.
        `store_ok` is _store_ok() hoisted out of the batch loop (the floors
        it checks only move through host handlers, never mid-batch)."""
        if not store_ok or op.txn_id in self._poison:
            return False
        if not _in_window(op.txn_id) or not _in_window(op.ballot):
            return False
        if op.execute_at is not None and not _in_window(op.execute_at):
            return False
        if op.kind == CMD_OP_PREACCEPT:
            if op.txn_id.kind is TxnKind.EXCLUSIVE_SYNC_POINT \
                    or op.txn is None:
                return False
            owned = self.store.owned(op.txn.keys)
        elif op.kind == CMD_OP_ACCEPT:
            if op.keys is None or op.execute_at is None:
                return False
            owned = self.store.owned(op.keys)
        else:   # commit / apply
            if op.execute_at is None or op.route is None:
                return False
            cmd = self.store.command_if_present(op.txn_id)
            known = cmd.txn if cmd is not None else None
            if known is not None and op.txn is not None \
                    and known.keys != op.txn.keys:
                return False   # union could change the registered key set
            body = op.txn if op.txn is not None else known
            if body is None:
                owned = Keys([])   # INSUFFICIENT on device, no registration
            else:
                owned = self.store.owned(body.keys)
        if not isinstance(owned, Keys) or len(owned) > self.kpad:
            return False
        op.owned = owned
        return True

    # -- device flush --------------------------------------------------------

    def _build_device(self) -> None:
        import jax.numpy as jnp
        self._device = {
            "status": jnp.asarray(self.status_h),
            "flags": jnp.asarray(self.flags_h),
            "promised": jnp.asarray(self.promised_h),
            "accepted": jnp.asarray(self.accepted_h),
            "execute_at": jnp.asarray(self.ea_h),
            "durability": jnp.asarray(self.dur_h),
            "kmax": jnp.asarray(self.kmax_h),
            "kvalid": jnp.asarray(self.kvalid_h),
        }
        self.upload_bytes += (self.status_h.nbytes + self.flags_h.nbytes
                              + self.promised_h.nbytes
                              + self.accepted_h.nbytes + self.ea_h.nbytes
                              + self.dur_h.nbytes + self.kmax_h.nbytes
                              + self.kvalid_h.nbytes)
        for name in _LANES:
            self._dirty[name].clear()
        self._kdirty.clear()
        self._device_stale = False

    def _flush(self) -> None:
        from accord_tpu.ops.deltas import flush_lane
        if self._device is None or self._device_stale:
            self._build_device()
            return

        def account(nbytes: int, _tier: int) -> None:
            self.upload_bytes += nbytes

        d = self._device
        for name in _LANES:
            rows = self._dirty[name]
            if rows:
                d[name] = flush_lane(d[name], sorted(rows),
                                     self._shadow_of(name), account)
                rows.clear()
        if self._kdirty:
            kids = sorted(self._kdirty)
            d["kmax"] = flush_lane(d["kmax"], kids, self.kmax_h, account)
            d["kvalid"] = flush_lane(d["kvalid"], kids, self.kvalid_h,
                                     account)
            self._kdirty.clear()

    # -- recovery scan (kernels.recovery_scan) -------------------------------

    def _flush_touched(self) -> None:
        """Ship the touched column's dirty rows (or rebuild after growth /
        compaction). Only the scan paths pay for this lane -- it stays off
        the repair block and the dispatch flush entirely."""
        import jax.numpy as jnp
        if self._touched_dev is None or self._touched_stale \
                or int(self._touched_dev.shape[0]) != self.cap:
            self._touched_dev = jnp.asarray(self.touched_h)
            self.upload_bytes += self.touched_h.nbytes
            self._tdirty.clear()
            self._touched_stale = False
        elif self._tdirty:
            from accord_tpu.ops.deltas import flush_lane

            def account(nbytes: int, _tier: int) -> None:
                self.upload_bytes += nbytes

            self._touched_dev = flush_lane(self._touched_dev,
                                           sorted(self._tdirty),
                                           self.touched_h, account)
            self._tdirty.clear()

    def _stalled_mask(self, now_ms: int, stall_ms: int) -> np.ndarray:
        """The scan predicate over the numpy shadows -- bit for bit the
        fold kernels._recovery_scan_body computes on device: status in the
        live band (excludes the INVALIDATED/TRUNCATED terminals above
        APPLIED) and last arena touch at least stall_ms old."""
        st = self.status_h
        live = (st >= CMD_ST_PRE_ACCEPTED) & (st < CMD_ST_APPLIED)
        return live & ((np.int32(now_ms) - self.touched_h)
                       >= np.int32(stall_ms))

    def recovery_scan_host(self, now_ms: float, stall_ms: float) -> list:
        """Recovery-candidate TxnIds, row-ascending: the host twin of the
        device scan and the fallback target for its counted checksum /
        overflow degradations."""
        t0 = time.perf_counter()
        with self._lock:
            rows = np.nonzero(self._stalled_mask(int(now_ms),
                                                 int(stall_ms)))[0]
            out = [self.tid_by_row[r] for r in rows.tolist()]
        self.recovery_scan_host_s += time.perf_counter() - t0
        return out

    def recovery_scan_device(self, now_ms: float, stall_ms: float) -> list:
        """ONE device query answering recovery-candidate selection over the
        arena columns: compacted row list + checksum, host-verified.
        Mismatch or out_cap overflow falls back to recovery_scan_host --
        counted, and bit-identical by construction (the device predicate is
        the same integer fold over the same flushed columns)."""
        from accord_tpu.ops.kernels import (RECOVERY_OUT_TIERS,
                                            frontier_checksum_host,
                                            recovery_scan)
        t0 = time.perf_counter()
        with self._lock:
            if self._rec_tiers is None:
                from accord_tpu.ops.tiers import OutCapTiers
                self._rec_tiers = OutCapTiers(RECOVERY_OUT_TIERS,
                                              RECOVERY_OUT_TIERS[-1] * 2)
            est = self._rec_tiers.estimate(1)
            out_cap = self._rec_tiers.pick(
                est if est is not None else max(1, self.n_rows // 8))
            self._flush()
            self._flush_touched()
            indptr, rows, csum = recovery_scan(
                self._device["status"], self._touched_dev,
                np.int32(int(now_ms)), np.int32(int(stall_ms)),
                out_cap=out_cap)
            indptr = np.asarray(indptr)
            rows = np.asarray(rows)
            total = int(indptr[-1])
            self.recovery_scan_dispatches += 1
            if frontier_checksum_host(indptr, rows) != int(csum):
                self.recovery_scan_fallbacks += 1
                self.recovery_scan_device_s += time.perf_counter() - t0
                return self.recovery_scan_host(now_ms, stall_ms)
            self._rec_tiers.observe(total, 1)
            if total > out_cap:
                self._rec_tiers.overflowed()
                self.recovery_scan_overflows += 1
                self.recovery_scan_device_s += time.perf_counter() - t0
                return self.recovery_scan_host(now_ms, stall_ms)
            self.recovery_scan_candidates += total
            out = [self.tid_by_row[r] for r in rows[:total].tolist()]
        self.recovery_scan_device_s += time.perf_counter() - t0
        return out

    # -- fused repair (the device-messages megakernel path) ------------------

    def collect_repair(self):
        """Package the shadows' outstanding flush debt -- the deferred
        twin's dirty rows/kids plus any host-residual updates -- as one
        kernels._cmd_repair_body scatter block to ride the next
        protocol_tick, instead of standalone flush_lane dispatches.

        Returns None when the device arena is not live (a full rebuild is
        pending; nothing to repair in-kernel), the string "clean" when the
        arena is live with nothing dirty (an interleaved flush already
        repaired it), else (block, (rows, kids)). A repair scatters exactly
        what a flush would -- current shadow values -- so it is idempotent
        and can never go stale."""
        with self._lock:
            if self._device is None or self._device_stale:
                return None
            rows = sorted(set().union(*self._dirty.values()))
            kids = sorted(self._kdirty)
            if not rows and not kids:
                return "clean"
            from accord_tpu.ops.deltas import lane_row_tier
            rpad = lane_row_tier(max(1, len(rows)))
            kpad = lane_row_tier(max(1, len(kids)))
            ridx = np.zeros(rpad, np.intp)
            ridx[:len(rows)] = rows
            kidx = np.zeros(kpad, np.intp)
            kidx[:len(kids)] = kids
            rows_idx = np.full(rpad, self.cap, np.int32)   # pad -> drop
            rows_idx[:len(rows)] = rows
            kid_idx = np.full(kpad, self.kcap, np.int32)
            kid_idx[:len(kids)] = kids
            st_v = self.status_h[ridx]
            fl_v = self.flags_h[ridx]
            pr_v = self.promised_h[ridx]
            ab_v = self.accepted_h[ridx]
            ea_v = self.ea_h[ridx]
            du_v = self.dur_h[ridx]
            km_v = self.kmax_h[kidx]
            kv_v = self.kvalid_h[kidx]
            self.upload_bytes += (rows_idx.nbytes + st_v.nbytes + fl_v.nbytes
                                  + pr_v.nbytes + ab_v.nbytes + ea_v.nbytes
                                  + du_v.nbytes + kid_idx.nbytes
                                  + km_v.nbytes + kv_v.nbytes)
            d = self._device
            block = (d["status"], d["flags"], d["promised"], d["accepted"],
                     d["execute_at"], d["durability"], d["kmax"],
                     d["kvalid"], rows_idx, st_v, fl_v, pr_v, ab_v, ea_v,
                     du_v, kid_idx, km_v, kv_v)
            return block, (rows, kids)

    def adopt_repair(self, outs, meta, spans: int = 0) -> None:
        """Take protocol_tick's repaired device columns: the collected
        rows/kids are clean now (diffed out, not cleared, so anything
        dirtied since collect_repair stays dirty) and `spans` deferred twin
        spans retired their flush debt inside the fused program."""
        with self._lock:
            rows, kids = meta
            st, fl, pr, ab, ea, du, km, kv = outs
            self._device = {"status": st, "flags": fl, "promised": pr,
                            "accepted": ab, "execute_at": ea,
                            "durability": du, "kmax": km, "kvalid": kv}
            rs = set(rows)
            for name in _LANES:
                self._dirty[name] -= rs
            self._kdirty -= set(kids)
            self.defer_retired += spans

    # -- evaluation ----------------------------------------------------------

    def eval_batch(self, ops: Sequence[CmdOp]) -> List[CmdResult]:
        """Evaluate ops IN ORDER: admissible spans run as device dispatches,
        inadmissible ops flush the pending span and take the host handler."""
        with self._lock:
            results: List[Optional[CmdResult]] = [None] * len(ops)
            run: List[Tuple[int, CmdOp]] = []
            store_ok = self._store_ok()
            for i, op in enumerate(ops):
                if self._admit(op, store_ok):
                    run.append((i, op))
                else:
                    self._run_device(run, results)
                    run = []
                    self.fallbacks += 1
                    results[i] = self._host_one(op)
                    # a host handler can move the admission floors (reject/
                    # truncation/range max-conflicts) -- re-sample
                    store_ok = self._store_ok()
            self._run_device(run, results)
            return results   # type: ignore[return-value]

    def _run_device(self, run: List[Tuple[int, CmdOp]],
                    results: List[Optional[CmdResult]]) -> None:
        if not run:
            return
        import jax.numpy as jnp
        import time
        node = self.store.node
        ops = [op for _, op in run]
        rows = [self._row_for(op.txn_id) for op in ops]
        kid_rows = [[self._kid_for(k) for k in op.owned] for op in ops]

        n = len(ops)
        tier = cmd_op_tier(n)
        op_kind = np.zeros(tier, np.int32)
        op_row = np.zeros(tier, np.int32)
        op_txn = np.zeros((tier, 3), np.int32)
        op_bal = np.zeros((tier, 3), np.int32)
        op_exec = np.full((tier, 3), _NEG, np.int32)
        op_keys = np.full((tier, self.kpad), -1, np.int32)
        op_flags = np.zeros(tier, np.int32)
        # intra-batch dependency links: the kernel's loop carries only
        # op-sized state, so a later op on the same row / kid reads its
        # previous writer's slot instead of the arena
        op_prev = np.full(tier, -1, np.int32)
        op_rlast = np.zeros(tier, bool)
        op_kprev = np.full((tier, self.kpad), -1, np.int32)
        op_klast = np.zeros((tier, self.kpad), bool)
        last_row: Dict[int, int] = {}
        last_kid: Dict[int, Tuple[int, int]] = {}
        for j in range(n):
            r = rows[j]
            op_prev[j] = last_row.get(r, -1)
            last_row[r] = j
            for s, kid in enumerate(kid_rows[j]):
                if kid in last_kid:
                    p, ps = last_kid[kid]
                    op_kprev[j, s] = p * self.kpad + ps
                last_kid[kid] = (j, s)
        for j in last_row.values():
            op_rlast[j] = True
        for j, s in last_kid.values():
            op_klast[j, s] = True
        now = int(node.time_service.now_micros())
        op_now = np.full(tier, now, np.int32)
        timeout_us = node.agent.pre_accept_timeout_ms() * 1000.0
        for j, op in enumerate(ops):
            op_kind[j] = op.kind
            op_row[j] = rows[j]
            op_txn[j] = _enc(op.txn_id)
            op_bal[j] = _enc(op.ballot)
            if op.execute_at is not None:
                op_exec[j] = _enc(op.execute_at)
            for s, kid in enumerate(kid_rows[j]):
                op_keys[j, s] = kid
            f = CMD_F_VALID
            if op.ballot == Ballot.ZERO:
                f |= CMD_F_PERMIT_FAST
            if op.txn_id.epoch >= node.epoch:
                f |= CMD_F_EPOCH_OK
            if op.kind == CMD_OP_PREACCEPT \
                    and not op.txn_id.kind.is_sync_point \
                    and now - op.txn_id.hlc >= timeout_us:
                f |= CMD_F_EXPIRED
            if op.txn is not None:
                f |= CMD_F_MSG_HAS_TXN
            if op.deps is None or op.deps.is_empty():
                f |= CMD_F_DEPS_EMPTY
            op_flags[j] = f

        t0 = time.perf_counter()
        self._flush()
        d = self._device
        lane2_clean = node.id - _LANE2_OFF
        lane2_rej = ((0x8000 << 16) | node.id) - _LANE2_OFF
        out = cmd_tick(
            d["status"], d["flags"], d["promised"], d["accepted"],
            d["execute_at"], d["durability"], d["kmax"], d["kvalid"],
            jnp.int32(node._last_hlc),
            jnp.asarray(op_kind), jnp.asarray(op_row), jnp.asarray(op_txn),
            jnp.asarray(op_bal), jnp.asarray(op_exec),
            jnp.asarray(op_keys), jnp.asarray(op_flags),
            jnp.asarray(op_now), jnp.asarray(op_prev),
            jnp.asarray(op_rlast), jnp.asarray(op_kprev),
            jnp.asarray(op_klast), jnp.int32(node.epoch),
            jnp.int32(lane2_clean), jnp.int32(lane2_rej),
            jnp.int32(int(Durability.LOCAL)),
            promote=(not self.apply_to_store) or self.authoritative)
        (n_status, n_flags, n_promised, n_accepted, n_ea, n_dur,
         n_kmax, n_kvalid, n_clock, out_code, out_ts, out_status,
         csum) = out
        out_code = np.asarray(out_code)
        out_ts = np.asarray(out_ts)
        out_status = np.asarray(out_status)
        clock = int(n_clock)
        self.flush_s += time.perf_counter() - t0
        if cmd_checksum_host(out_code, out_status, out_ts, clock) \
                != int(csum):
            # readback integrity lost (PR 11 discipline): do NOT adopt the
            # device result; rebuild from the still-authoritative shadows
            # and answer this span with the host handlers
            self.checksum_mismatches += 1
            self._device_stale = True
            for i, op in zip((i for i, _ in run), ops):
                self.fallbacks += 1
                results[i] = self._host_one(op)
            return

        self._device = {"status": n_status, "flags": n_flags,
                        "promised": n_promised, "accepted": n_accepted,
                        "execute_at": n_ea, "durability": n_dur,
                        "kmax": n_kmax, "kvalid": n_kvalid}
        self.dispatches += 1
        node._last_hlc = clock

        # shadow sync: the device columns are authoritative for every row /
        # kid this span touched; pull them down so a later dirty upload
        # cannot regress the arena
        touched = sorted(set(rows))
        host_cols = {name: np.asarray(self._device[name])
                     for name in _LANES}
        for name in _LANES:
            sh = self._shadow_of(name)
            sh[touched] = host_cols[name][touched]
            self._dirty[name] -= set(touched)
        tkids = sorted({k for ks in kid_rows for k in ks})
        if tkids:
            self.kmax_h[tkids] = np.asarray(self._device["kmax"])[tkids]
            self.kvalid_h[tkids] = np.asarray(self._device["kvalid"])[tkids]
            self._kdirty -= set(tkids)

        # fast-path accounting: a successful preaccept whose witness IS the
        # TxnId took the device fast path (slow/rejected witnesses always
        # carry a bumped hlc or the REJECTED flag lane)
        for j, op in enumerate(ops):
            if op.kind == CMD_OP_PREACCEPT and (int(out_code[j]) & 7) == 0 \
                    and np.array_equal(out_ts[j], op_txn[j]):
                self.fastpath_device_evals += 1

        for (i, op), j in zip(run, range(len(ops))):
            code = int(out_code[j])
            ts = (None if out_ts[j][0] == _NEG
                  else _dec(*(int(x) for x in out_ts[j])))
            if self.apply_to_store:
                self._residual(op, code, ts)
            results[i] = self._result(op, code, ts, int(out_status[j]))

    # -- deferred evaluation (the protocol megakernel) -----------------------

    def defer_batch(self, ops: Sequence[CmdOp],
                    sink=None, fuse=None) -> List[CmdResult]:
        """eval_batch's megakernel twin: decide each admissible PreAccept
        span with the HOST INTEGER TWIN of cmd_tick's PreAccept lane (the
        drain needs the decisions synchronously, before the tick's single
        fused dispatch is assembled) and hand the resulting transition
        lanes to `sink` so they ride protocol_tick's quorum stage. Shadows
        stay authoritative; touched rows mark dirty and the next _flush
        repairs the device columns lazily -- no device dispatch for the
        PreAccept spans, which is the whole point. Admission, ordering, and
        fallback interleaving mirror eval_batch exactly: an admissible
        non-PreAccept op flushes the pending twin span and runs as its own
        DEVICE span (eval_batch would have put it on device, and device vs
        host handlers differ observably for Commit/Apply), an inadmissible
        op flushes and takes the host handler -- so histories are
        bit-identical to the device path for any op mix.

        `fuse` (the device-messages path): called once per nonempty twin
        span with this plane, registering the span's flush debt for
        retirement inside the next protocol_tick via collect_repair()
        instead of a standalone flush_lane dispatch."""
        with self._lock:
            results: List[Optional[CmdResult]] = [None] * len(ops)
            run: List[Tuple[int, CmdOp]] = []
            store_ok = self._store_ok()
            for i, op in enumerate(ops):
                adm = self._admit(op, store_ok)
                if adm and op.kind == CMD_OP_PREACCEPT:
                    run.append((i, op))
                    continue
                self._twin_run(run, results, sink, fuse)
                run = []
                if adm:
                    self._run_device([(i, op)], results)
                else:
                    self.fallbacks += 1
                    results[i] = self._host_one(op)
                    store_ok = self._store_ok()
            self._twin_run(run, results, sink, fuse)
            return results   # type: ignore[return-value]

    def _twin_run(self, run: List[Tuple[int, CmdOp]],
                  results: List[Optional[CmdResult]], sink=None,
                  fuse=None) -> None:
        """Sequential host integer twin of cmd_tick's PreAccept lane over
        one admissible span: same gathers, same predicates, same unique_now
        arithmetic, same writebacks -- executed op by op against the shadow
        columns, so intra-span chains resolve exactly like the kernel's
        prev-writer links (tests/test_megakernel.py runs the differential
        against eval_batch)."""
        if not run:
            return
        node = self.store.node
        ops = [op for _, op in run]
        # _row_for/_kid_for lazily create and seed rows -- same call order
        # as _run_device so allocation histories match bit for bit
        rows = [self._row_for(op.txn_id) for op in ops]
        kid_rows = [[self._kid_for(k) for k in op.owned] for op in ops]
        now = int(node.time_service.now_micros())
        timeout_us = node.agent.pre_accept_timeout_ms() * 1000.0
        node_epoch = int(node.epoch)
        lane2_clean = node.id - _LANE2_OFF
        lane2_rej = ((0x8000 << 16) | node.id) - _LANE2_OFF
        clock = int(node._last_hlc)
        n = len(ops)
        q_txn = np.zeros((n, 3), np.int32)
        q_ts = np.full((n, 3), _NEG, np.int32)
        q_code = np.zeros(n, np.int32)
        out_status = np.zeros(n, np.int32)

        for j, op in enumerate(ops):
            r = rows[j]
            txn = _enc(op.txn_id)
            bal = _enc(op.ballot)
            permit_fast = op.ballot == Ballot.ZERO
            epoch_ok = op.txn_id.epoch >= node_epoch
            expired = (not op.txn_id.kind.is_sync_point
                       and now - op.txn_id.hlc >= timeout_us)
            st = int(self.status_h[r])
            fl = int(self.flags_h[r])
            pr = tuple(int(x) for x in self.promised_h[r])
            ea = tuple(int(x) for x in self.ea_h[r])
            has_txn = (fl & 1) != 0
            ea_set = ea[0] != _NEG
            terminal = st in (CMD_ST_INVALIDATED, CMD_ST_TRUNCATED)
            pr_gt_bal = bal < pr
            term_code = (CMD_OUT_REJECTED_BALLOT
                         if st == CMD_ST_INVALIDATED else CMD_OUT_TRUNCATED)
            mc = None
            for kid in kid_rows[j]:
                if self.kvalid_h[kid]:
                    v = tuple(int(x) for x in self.kmax_h[kid])
                    if mc is None or v > mc:
                        mc = v
            mc_any = mc is not None

            def unow(al_ep, al_hlc, lane2):
                h = max(now, clock + 1)
                if al_hlc >= h:
                    h = al_hlc + 1
                return (max(node_epoch, al_ep), h, lane2), h

            rej_w, rej_h = unow(txn[0], txn[1], lane2_rej)
            al = mc if mc_any else txn
            slow_w, slow_h = unow(al[0], al[1], lane2_clean)
            fast = permit_fast and epoch_ok \
                and (not mc_any or not (txn < mc))
            witness = rej_w if expired else (txn if fast else slow_w)
            wit_clock = rej_h if expired else (clock if fast else slow_h)
            blocked = terminal or pr_gt_bal
            code = (term_code if terminal
                    else CMD_OUT_REJECTED_BALLOT if pr_gt_bal
                    else CMD_OUT_REDUNDANT if has_txn and permit_fast
                    else CMD_OUT_SUCCESS)
            pa_wit = not blocked and not has_txn and not ea_set
            if blocked or has_txn:
                new_st = st
            elif ea_set:
                new_st = max(st, CMD_ST_PRE_ACCEPTED)
            else:
                new_st = CMD_ST_PRE_ACCEPTED
            new_fl = fl if blocked else (fl | 1)
            new_pr = pr if blocked else max(pr, bal)
            new_ea = witness if pa_wit else ea
            if pa_wit:
                clock = wit_clock

            vals = {"status": np.int32(new_st),
                    "flags": np.int32(new_fl),
                    "promised": np.asarray(new_pr, np.int32),
                    "execute_at": np.asarray(new_ea, np.int32)}
            changed = False
            for name, v in vals.items():
                sh = self._shadow_of(name)
                if not np.array_equal(sh[r], v):
                    sh[r] = v
                    self._dirty[name].add(r)
                    changed = True
            if changed:
                self._touch(r)
            if pa_wit:
                w_arr = np.asarray(witness, np.int32)
                for kid in kid_rows[j]:
                    kv = bool(self.kvalid_h[kid])
                    km = tuple(int(x) for x in self.kmax_h[kid])
                    if not kv or km < witness:
                        self.kmax_h[kid] = w_arr
                        self._kdirty.add(kid)
                    if not kv:
                        self.kvalid_h[kid] = True
                        self._kdirty.add(kid)

            q_txn[j] = txn
            q_ts[j] = new_ea
            q_code[j] = code
            out_status[j] = new_st

        node._last_hlc = clock
        self.deferred_spans += 1
        self.deferred_ops += n
        if fuse is not None:
            fuse(self)
        if sink is not None:
            sink(q_txn, q_ts, q_code)
        for (i, op), j in zip(run, range(n)):
            code = int(q_code[j])
            ts = (None if q_ts[j][0] == _NEG
                  else _dec(*(int(x) for x in q_ts[j])))
            if self.apply_to_store:
                self._residual(op, code, ts)
            results[i] = self._result(op, code, ts, int(out_status[j]))

    # -- host paths ----------------------------------------------------------

    def _host_one(self, op: CmdOp) -> CmdResult:
        from accord_tpu.local import commands
        store = self.store
        if op.kind == CMD_OP_PREACCEPT:
            outcome = commands.preaccept(store, op.txn_id, op.txn, op.route,
                                         op.ballot)
        elif op.kind == CMD_OP_ACCEPT:
            outcome = commands.accept(store, op.txn_id, op.ballot, op.route,
                                      op.keys, op.execute_at, op.deps)
        elif op.kind == CMD_OP_COMMIT:
            outcome = commands.commit(store, op.txn_id, op.route, op.txn,
                                      op.execute_at, op.deps)
        else:
            outcome = commands.apply(store, op.txn_id, op.route, op.txn,
                                     op.execute_at, op.deps, op.writes,
                                     op.result)
        cmd = store.command_if_present(op.txn_id)
        st = cmd.status if cmd is not None else Status.NOT_DEFINED
        ea = cmd.execute_at if cmd is not None else None
        return CmdResult(outcome, st, ea, -1)

    def _result(self, op: CmdOp, code: int, ts, status_i: int) -> CmdResult:
        from accord_tpu.local.commands import AcceptOutcome, CommitOutcome
        low = code & 7
        if op.kind in (CMD_OP_PREACCEPT, CMD_OP_ACCEPT):
            outcome = (AcceptOutcome.SUCCESS, AcceptOutcome.REDUNDANT,
                       AcceptOutcome.REJECTED_BALLOT,
                       AcceptOutcome.TRUNCATED)[low]
        else:
            outcome = {0: CommitOutcome.SUCCESS, 1: CommitOutcome.REDUNDANT,
                       4: CommitOutcome.INSUFFICIENT}[low]
        return CmdResult(outcome, Status(status_i), ts, code)

    def _residual(self, op: CmdOp, code: int, ts) -> None:
        """Replay the handler's host-side effects for a device-decided op:
        same mutations as local/commands.py with the decision (witness
        timestamp / outcome / promotion) taken from the kernel output."""
        from accord_tpu.local import commands
        from accord_tpu.local.cfk import CfkStatus
        from accord_tpu.local.commands import (REC, _init_waiting_on,
                                               _is_home, _rec_step,
                                               maybe_execute,
                                               notify_listeners)
        from accord_tpu.primitives.timestamp import Domain
        store = self.store
        low = code & 7
        if op.kind == CMD_OP_PREACCEPT:
            if low in (2, 3):
                return   # rejected/truncated: handler mutates nothing
            cmd = store.command(op.txn_id)
            if cmd.txn is not None:
                cmd.promised = max(cmd.promised, op.ballot)
                return   # REDUNDANT / non-zero-ballot SUCCESS: promise only
            cmd.txn = op.txn
            cmd.route = op.route if cmd.route is None else cmd.route
            cmd.promised = max(cmd.promised, op.ballot)
            if cmd.execute_at is None:
                witnessed = (op.txn_id if ts is not None
                             and ts == op.txn_id.as_timestamp()
                             and not ts.is_rejected else ts)
                cmd.execute_at = witnessed
                cmd.status = Status.PRE_ACCEPTED
                if REC.enabled:
                    _rec_step(store, op.txn_id, "preaccepted")
                store.register(op.txn_id, op.txn.keys, CfkStatus.WITNESSED,
                               witnessed)
                store.progress_log.preaccepted(cmd, _is_home(store, cmd))
            else:
                cmd.status = max(cmd.status, Status.PRE_ACCEPTED)
            notify_listeners(store, cmd)
        elif op.kind == CMD_OP_ACCEPT:
            if low != 0:
                return
            cmd = store.command(op.txn_id)
            cmd.route = op.route if cmd.route is None else cmd.route
            cmd.execute_at = op.execute_at
            cmd.promised = op.ballot
            cmd.accepted_ballot = op.ballot
            if op.deps is not None:
                cmd.deps = op.deps.slice(store.ranges)
                cmd.accepted_scope = op.keys.to_ranges()
            cmd.status = Status.ACCEPTED
            if REC.enabled:
                _rec_step(store, op.txn_id, "accepted")
            store.register(op.txn_id, op.keys, CfkStatus.WITNESSED,
                           op.execute_at)
            store.progress_log.accepted(cmd, _is_home(store, cmd))
            notify_listeners(store, cmd)
        elif op.kind == CMD_OP_COMMIT:
            cmd = store.command_if_present(op.txn_id)
            if low == 1:
                if code & CMD_OUT_INCONSISTENT_BIT and cmd is not None:
                    store.node.agent.on_inconsistent_timestamp(
                        cmd, cmd.execute_at, op.execute_at)
                return
            if low != 0:
                return
            cmd = store.command(op.txn_id)
            if op.txn is not None:
                cmd.txn = op.txn if cmd.txn is None else cmd.txn.union(op.txn)
            cmd.route = op.route if cmd.route is None else cmd.route
            cmd.execute_at = op.execute_at
            cmd.deps = op.deps
            cmd.status = Status.STABLE
            if REC.enabled:
                _rec_step(store, op.txn_id, "stable")
            store.register(op.txn_id, cmd.txn.keys, CfkStatus.COMMITTED,
                           max(op.execute_at, op.txn_id.as_timestamp()),
                           op.execute_at)
            if op.txn_id.kind is TxnKind.WRITE \
                    and op.txn_id.domain is Domain.KEY:
                store.register_commit_cover(op.txn_id, op.execute_at,
                                            op.deps)
            _init_waiting_on(store, cmd)
            if store.exec_plane is not None:
                store.exec_plane.on_stable(cmd)
            store.progress_log.stable(cmd, _is_home(store, cmd))
            store.node.events.on_stable(cmd)
            notify_listeners(store, cmd)
            maybe_execute(store, cmd)
        else:   # apply
            cmd = store.command_if_present(op.txn_id)
            if low == 1:
                if code & CMD_OUT_INCONSISTENT_BIT and cmd is not None:
                    store.node.agent.on_inconsistent_timestamp(
                        cmd, cmd.execute_at, op.execute_at)
                return
            if low != 0:
                return
            cmd = store.command(op.txn_id)
            if op.txn is not None:
                cmd.txn = op.txn if cmd.txn is None else cmd.txn.union(op.txn)
            cmd.route = op.route if cmd.route is None else cmd.route
            was_stable = bool(code & CMD_OUT_WAS_STABLE_BIT)
            cmd.execute_at = op.execute_at
            if not was_stable:
                cmd.deps = op.deps
            cmd.writes = op.writes
            cmd.result = op.result
            cmd.status = Status.PRE_APPLIED
            store.register(op.txn_id, cmd.txn.keys, CfkStatus.COMMITTED,
                           max(op.execute_at, op.txn_id.as_timestamp()),
                           op.execute_at)
            if not was_stable:
                _init_waiting_on(store, cmd)
            if store.exec_plane is not None:
                store.exec_plane.on_stable(cmd)
            store.progress_log.executed(cmd, _is_home(store, cmd))
            notify_listeners(store, cmd)
            maybe_execute(store, cmd)

    # -- observability -------------------------------------------------------

    def snapshot(self) -> dict:
        return self.metrics.snapshot()


def warmup_cmd_plane(caps: Sequence[int] = (1024,),
                     key_caps: Sequence[int] = (1024,),
                     kpad: int = 4,
                     op_tiers: Sequence[int] = CMD_OP_TIERS,
                     promote_modes: Sequence[bool] = (False,)) -> int:
    """Compile cmd_tick (and the cmd-lane scatter shapes) for every arena /
    op-tier combination the workload will dispatch, so the timed window
    mints zero new jit entries. Returns the number of variants compiled."""
    import jax.numpy as jnp
    from accord_tpu.ops.deltas import LANE_ROW_TIERS
    from accord_tpu.ops.kernels import scatter_rows
    compiled = 0
    for cap in caps:
        for kcap in key_caps:
            cols = (jnp.zeros(cap, jnp.int32), jnp.zeros(cap, jnp.int32),
                    jnp.zeros((cap, 3), jnp.int32),
                    jnp.zeros((cap, 3), jnp.int32),
                    jnp.full((cap, 3), _NEG, jnp.int32),
                    jnp.zeros(cap, jnp.int32))
            kmax = jnp.full((kcap, 3), _NEG, jnp.int32)
            kvalid = jnp.zeros(kcap, bool)
            for t in op_tiers:
                argset = (jnp.zeros(t, jnp.int32), jnp.zeros(t, jnp.int32),
                          jnp.zeros((t, 3), jnp.int32),
                          jnp.zeros((t, 3), jnp.int32),
                          jnp.full((t, 3), _NEG, jnp.int32),
                          jnp.full((t, kpad), -1, jnp.int32),
                          jnp.zeros(t, jnp.int32), jnp.zeros(t, jnp.int32),
                          jnp.full(t, -1, jnp.int32),
                          jnp.zeros(t, bool),
                          jnp.full((t, kpad), -1, jnp.int32),
                          jnp.zeros((t, kpad), bool))
                for promote in promote_modes:
                    r = cmd_tick(*cols, kmax, kvalid, jnp.int32(0),
                                 *argset, jnp.int32(0), jnp.int32(-1),
                                 jnp.int32(0), jnp.int32(1),
                                 promote=bool(promote))
                    r[0].block_until_ready()
                    compiled += 1
            for m in LANE_ROW_TIERS:
                idx = jnp.zeros(m, jnp.int32)
                for col in (*cols, kmax, kvalid):
                    scatter_rows(col, idx, jnp.zeros((m,) + col.shape[1:],
                                                     col.dtype))
                    compiled += 1
    return compiled
