"""Device-plane fault injection + per-node device health state machine.

The protocol plane already burns under injected drops, partitions, crashes,
and topology churn (`utils/faults.py`, `sim/burn.py`); this module gives the
DEVICE plane -- the resolver's dispatch/harvest pipeline -- the same
treatment. A seeded `DeviceFaultPlane` (installed with the same scoped
module-global pattern as `utils/faults.py`) injects four fault kinds at the
dispatch+harvest boundary:

  dispatch_exc  the kernel launch raises (driver/OOM/transfer error);
                the resolver retries a bounded number of times, then
                answers the whole dispatch host-side (degraded).
  stuck         the in-flight call never (or only late) becomes ready;
                the harvest watchdog spends a bounded probe budget, then
                declares the call wedged and answers host-side.
  corrupt       a readback buffer arrives bit-flipped; the checksum lane
                fused into the finalize kernels' returns catches it before
                decode and the group falls back to the legacy decode of
                the (uncorrupted) raw candidate buffers.
  overflow      an out-cap overflow storm: the finalize result reports
                indptr[-1] > out_cap, driving the OutCapTiers policy's
                bump path (and, windowed, proving it bumps once instead
                of oscillating).

Every draw comes from a RandomSource forked from the burn rng, and every
injection is consumed at a deterministic point of the single-threaded sim
event order -- so `--reconcile` determinism holds, and because all four
handling paths deliver their (bit-identical) results at the SAME simulated
harvest event the dispatch would have used, a chaos run's committed history
is bit-identical to the fault-free run of the same seed.

`DeviceHealth` is the per-node degradation ladder the resolver consults:

    HEALTHY --fault--> DEGRADED --more faults--> QUARANTINED
       ^                  |(quiet)                    | (countdown)
       |                  v                           v
       +--canaries ok-- PROBATION <-------------------+
                          |(canary mismatch)
                          +-----> QUARANTINED

Quarantined nodes route every dispatch through the host differential path
(`_Item.fallback == "full"` -> `store.host_calculate_deps`, bit-identical
by the device path's own differential tests); probation re-enters the
device path with canary dispatches whose finalized-CSR decode is checked
against the legacy decode of the same plan-time snapshot, re-using warmed
jit tiers so recovery mints zero recompiles.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional

import numpy as np

# the four injectable fault kinds, in the (fixed) order draws consume rng
FAULT_KINDS = ("dispatch_exc", "stuck", "corrupt", "overflow")

# module-global active plane, utils/faults.py style: the simulator installs
# one for a run and restores on exit (single-threaded, deterministic)
ACTIVE: Optional["DeviceFaultPlane"] = None


class InjectedDispatchError(RuntimeError):
    """The fault plane's simulated kernel-launch failure."""


class DeviceFaultPlane:
    """Seeded device-fault schedule. One instance per burn run; all nodes'
    resolvers share it, which is deterministic because the sim is
    single-threaded and dispatch/harvest events are totally ordered.

    rates: per-kind injection probability per device dispatch.
    dispatch_exc_burst: max consecutive launch failures per injected
        dispatch fault (drawn uniformly in [1, burst]); a draw above the
        resolver's retry limit exhausts the retries and degrades the
        dispatch, driving the health ladder.
    stuck_probes_max: max not-ready harvest probes an injected stuck call
        eats (drawn in [1, max]); a draw above the resolver's watchdog
        probe budget trips the watchdog (wedged), at or below it the call
        completes late (recovered).
    """

    def __init__(self, rng, *, dispatch_exc_rate: float = 0.0,
                 stuck_rate: float = 0.0, corrupt_rate: float = 0.0,
                 overflow_rate: float = 0.0, mailbox_rate: float = 0.0,
                 dispatch_exc_burst: int = 4, stuck_probes_max: int = 6):
        self.rng = rng
        self.rates: Dict[str, float] = {
            "dispatch_exc": dispatch_exc_rate,
            "stuck": stuck_rate,
            "corrupt": corrupt_rate,
            "overflow": overflow_rate,
            # NOT in FAULT_KINDS / draw(): mailbox corruption is drawn at
            # the message plane's landed-readback point, not per dispatch,
            # so enabling it never shifts the dispatch fault stream of an
            # existing chaos seed
            "mailbox": mailbox_rate,
        }
        self.dispatch_exc_burst = max(1, dispatch_exc_burst)
        self.stuck_probes_max = max(1, stuck_probes_max)
        # injections actually APPLIED (a corrupt draw on a call with no
        # finalized buffer is dropped, not counted), per kind
        self.injected: Dict[str, int] = {k: 0 for k in
                                         FAULT_KINDS + ("mailbox",)}

    @property
    def total_injected(self) -> int:
        return sum(self.injected.values())

    def draw(self) -> Optional[str]:
        """Per-dispatch fault decision, consumed at launch. Fixed kind
        order so the rng stream is schedule-stable."""
        for kind in FAULT_KINDS:
            r = self.rates[kind]
            if r > 0.0 and self.rng.decide(r):
                return kind
        return None

    def draw_burst(self) -> int:
        """Consecutive launch failures for an injected dispatch_exc."""
        return 1 + self.rng.next_int(self.dispatch_exc_burst)

    def draw_stuck(self) -> int:
        """Not-ready probes an injected stuck call eats before readiness."""
        return 1 + self.rng.next_int(self.stuck_probes_max)

    def note(self, kind: str) -> None:
        self.injected[kind] += 1

    def corrupt_arrays(self, bufs) -> bool:
        """Flip one bit of one array in `bufs` (host numpy copies of a
        fetched finalize triple) -- the simulated corrupted readback. The
        flip lands in the arrays the checksum lane covers (never the
        trailing bound/csum words), so every injection is detectable.
        Returns False (and draws nothing) when there is nothing to hit."""
        targets = [b for b in bufs[:3]
                   if isinstance(b, np.ndarray) and b.size > 0]
        if not targets:
            return False
        arr = targets[self.rng.next_int(len(targets))]
        flat = arr.reshape(-1).view(np.uint32)
        pos = self.rng.next_int(int(flat.shape[0]))
        bit = self.rng.next_int(32)
        flat[pos] ^= np.uint32(1) << np.uint32(bit)
        self.note("corrupt")
        return True

    def corrupt_mailbox(self, words: np.ndarray) -> bool:
        """Maybe flip one bit of a landed mailbox message's word lanes (a
        local copy the caller owns) -- the simulated corrupted device
        routing. Drawn at the delivery readback point; the message plane's
        verify-against-staged-bytes contract catches every injection and
        falls back to the host copy, so chaos histories stay bit-identical.
        Draws nothing when the mailbox rate is zero (stream stability)."""
        rate = self.rates.get("mailbox", 0.0)
        if rate <= 0.0 or words.size == 0 or not self.rng.decide(rate):
            return False
        flat = words.reshape(-1).view(np.uint32)
        # flip within the LIVE bytes (payload, else the length header),
        # never the zero padding -- a padding flip would be invisible to
        # the unpack and the injection ledger must match observable
        # verify fallbacks exactly
        nbytes = int(flat[0] & 0x7FFFFFFF)
        as_bytes = words.reshape(-1).view(np.uint8)
        limit = min(nbytes, int(as_bytes.shape[0]) - 4)
        if limit > 0:
            pos = 4 + self.rng.next_int(limit)
        else:
            pos = self.rng.next_int(4)  # empty payload: corrupt the header
        bit = self.rng.next_int(8)
        as_bytes[pos] ^= np.uint8(1) << np.uint8(bit)
        self.note("mailbox")
        return True


class scoped:
    """Install a plane for a with-block, restoring the previous one on
    exit (the utils/faults.py pattern, object-valued)."""

    def __init__(self, plane: Optional[DeviceFaultPlane]):
        self.plane = plane
        self.saved: Optional[DeviceFaultPlane] = None

    def __enter__(self):
        global ACTIVE
        self.saved = ACTIVE
        ACTIVE = self.plane
        return self.plane

    def __exit__(self, *exc):
        global ACTIVE
        ACTIVE = self.saved
        return False


HEALTHY = "HEALTHY"
DEGRADED = "DEGRADED"
QUARANTINED = "QUARANTINED"
PROBATION = "PROBATION"


class DeviceHealth:
    """Per-node device-path health ladder (see module docstring diagram).

    quarantine_after: consecutive faulted dispatches (from DEGRADED) that
        quarantine the node. recover_after: consecutive clean dispatches
        that walk DEGRADED back to HEALTHY. quarantine_dispatches: host-
        routed dispatches served before probation. probation_canaries:
        consecutive clean canary dispatches that restore HEALTHY.
    on_transition(old, new) fires once per state change (the resolver
    wires it to the obs counters + flight recorder)."""

    __slots__ = ("state", "quarantine_after", "recover_after",
                 "quarantine_dispatches", "probation_canaries",
                 "on_transition", "transitions", "_faults", "_clean",
                 "_host_left", "_canaries_ok")

    def __init__(self, *, quarantine_after: int = 2, recover_after: int = 4,
                 quarantine_dispatches: int = 4, probation_canaries: int = 2,
                 on_transition: Optional[Callable[[str, str], None]] = None):
        self.state = HEALTHY
        self.quarantine_after = max(1, quarantine_after)
        self.recover_after = max(1, recover_after)
        self.quarantine_dispatches = max(1, quarantine_dispatches)
        self.probation_canaries = max(1, probation_canaries)
        self.on_transition = on_transition
        self.transitions = 0
        self._faults = 0      # consecutive faulted dispatches
        self._clean = 0       # consecutive clean dispatches (DEGRADED)
        self._host_left = 0   # quarantine countdown
        self._canaries_ok = 0

    def _to(self, state: str) -> None:
        if state == self.state:
            return
        old, self.state = self.state, state
        self.transitions += 1
        if self.on_transition is not None:
            self.on_transition(old, state)

    @property
    def route_host(self) -> bool:
        """True while every dispatch must answer through the host
        differential path (the quarantine reroute)."""
        return self.state == QUARANTINED

    @property
    def wants_canary(self) -> bool:
        return self.state == PROBATION

    def on_fault(self, kind: str) -> None:
        """A device fault was handled (retry exhausted, watchdog trip,
        checksum mismatch, ...). Escalates HEALTHY -> DEGRADED ->
        QUARANTINED; a probation fault falls straight back."""
        self._clean = 0
        if self.state == QUARANTINED:
            return
        if self.state == PROBATION:
            self.canary_failed()
            return
        self._faults += 1
        if self.state == HEALTHY:
            self._to(DEGRADED)
        if self._faults >= self.quarantine_after:
            self.enter_quarantine()

    def on_clean_dispatch(self) -> None:
        """A device dispatch harvested with no fault. Walks DEGRADED back
        to HEALTHY after recover_after consecutive clean harvests."""
        self._faults = 0
        if self.state == DEGRADED:
            self._clean += 1
            if self._clean >= self.recover_after:
                self._clean = 0
                self._to(HEALTHY)

    def enter_quarantine(self) -> None:
        self._faults = 0
        self._canaries_ok = 0
        self._host_left = self.quarantine_dispatches
        self._to(QUARANTINED)

    def on_host_dispatch(self) -> None:
        """One quarantined dispatch served host-side; after the countdown
        the node re-enters the device path on probation."""
        if self.state != QUARANTINED:
            return
        self._host_left -= 1
        if self._host_left <= 0:
            self._canaries_ok = 0
            self._to(PROBATION)

    def canary_ok(self) -> None:
        if self.state != PROBATION:
            return
        self._canaries_ok += 1
        if self._canaries_ok >= self.probation_canaries:
            self._canaries_ok = 0
            self._to(HEALTHY)

    def canary_failed(self) -> None:
        """A probation canary's device decode diverged from the host
        recompute (or a fault landed during probation): back to
        quarantine for another full countdown."""
        self.enter_quarantine()
