"""Host<->device encoding for protocol values.

Timestamps: the protocol orders by (epoch, hlc, flags, node). TPUs prefer
int32 lanes (int64 is emulated), so the device encoding is three int32 lanes
relative to a per-batch base, compared lexicographically:

  lane0 = epoch - base_epoch          (small non-negative int)
  lane1 = hlc - base_hlc              (window-checked: |delta| < 2^31 us)
  lane2 = flags << 16 | node

The hlc window (~35 minutes of microseconds) vastly exceeds any active-set
span; the encoder verifies membership and the resolver asserts rather than
silently dropping out-of-window entries.

Keys: the burn test's hash-key domain maps keys directly to bitmap columns
via key % K buckets. Bucketing makes the bitmap a *conservative overestimate*
(two keys may share a column), which is safe for deps (extra deps are merely
redundant edges, and the host CSR conversion re-filters per real key).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from accord_tpu.primitives.timestamp import Timestamp, TxnId, TxnKind

# WITNESS_TABLE[a, b] == 1 iff kind a witnesses kind b (mirrors
# primitives.timestamp._WITNESSES, itself a mirror of reference
# Txn.Kind.witnesses primitives/Txn.java:224).
WITNESS_TABLE = np.zeros((6, 6), dtype=np.int32)
for _a in TxnKind:
    for _b in TxnKind:
        WITNESS_TABLE[int(_a), int(_b)] = 1 if _a.witnesses(_b) else 0

_WINDOW = (1 << 31) - 1


class TimestampEncoder:
    """Encodes a batch of timestamps as int32 (lane0, lane1) pairs with a
    shared (epoch, hlc) base."""

    def __init__(self, base_epoch: int, base_hlc: int):
        self.base_epoch = base_epoch
        self.base_hlc = base_hlc

    @classmethod
    def for_timestamps(cls, tss: Sequence[Timestamp]) -> "TimestampEncoder":
        if not tss:
            return cls(0, 0)
        lo = min(tss)
        return cls(lo.epoch, lo.hlc)

    def in_window(self, ts: Timestamp) -> bool:
        return (0 <= ts.epoch - self.base_epoch < _WINDOW
                and -_WINDOW < ts.hlc - self.base_hlc < _WINDOW)

    def encode(self, tss: Sequence[Timestamp]) -> np.ndarray:
        """-> int32[len(tss), 3]; raises if any timestamp out of window."""
        out = np.empty((len(tss), 3), dtype=np.int32)
        for i, ts in enumerate(tss):
            if not self.in_window(ts):
                raise ValueError(f"timestamp {ts} outside encoder window")
            out[i, 0] = ts.epoch - self.base_epoch
            out[i, 1] = ts.hlc - self.base_hlc
            # biased so the full 32-bit (flags, node) space -- including the
            # REJECTED flag in bit 15 of flags -- fits a SIGNED int32 lane
            # while preserving order
            out[i, 2] = ((ts.flags << 16) | ts.node) - (1 << 31)
        return out

    def encode_one(self, ts: Timestamp) -> Tuple[int, int, int]:
        """Single-timestamp fast path (no array round trip): the arena's
        per-registration lane updates assign the 3 lanes directly."""
        if not self.in_window(ts):
            raise ValueError(f"timestamp {ts} outside encoder window")
        return (ts.epoch - self.base_epoch, ts.hlc - self.base_hlc,
                ((ts.flags << 16) | ts.node) - (1 << 31))

    def encode_many(self, tss: Sequence[Timestamp]) -> np.ndarray:
        """Bulk twin of encode(): attribute gathers via np.fromiter and a
        vectorized window check instead of a per-timestamp Python loop --
        the dispatch encode at large batch sizes is bounded by this."""
        n = len(tss)
        out = np.empty((n, 3), dtype=np.int64)
        out[:, 0] = np.fromiter((t.epoch for t in tss), np.int64, n)
        out[:, 0] -= self.base_epoch
        out[:, 1] = np.fromiter((t.hlc for t in tss), np.int64, n)
        out[:, 1] -= self.base_hlc
        out[:, 2] = np.fromiter(((t.flags << 16) | t.node for t in tss),
                                np.int64, n)
        out[:, 2] -= 1 << 31
        if n and not (
                (out[:, 0] >= 0).all() and (out[:, 0] < _WINDOW).all()
                and (np.abs(out[:, 1]) < _WINDOW).all()):
            for t in tss:
                if not self.in_window(t):
                    raise ValueError(f"timestamp {t} outside encoder window")
        return out.astype(np.int32)


def encode_key_bitmaps(key_sets: Sequence[Sequence[int]], num_buckets: int) -> np.ndarray:
    """-> float bitmap [len(key_sets), num_buckets] with 1.0 where the txn
    touches a key hashing to that bucket (float for MXU matmul). Vectorized
    NumPy scatter -- one fancy-index assignment, no per-key Python loop."""
    n = len(key_sets)
    out = np.zeros((n, num_buckets), dtype=np.float32)
    counts = np.fromiter((len(ks) for ks in key_sets), dtype=np.int64, count=n)
    total = int(counts.sum())
    if total == 0:
        return out
    rows = np.repeat(np.arange(n), counts)
    cols = np.fromiter((int(k) for ks in key_sets for k in ks),
                       dtype=np.int64, count=total) % num_buckets
    out[rows, cols] = 1.0
    return out


def encode_kinds(txn_ids: Sequence[TxnId]) -> np.ndarray:
    return np.array([int(t.kind) for t in txn_ids], dtype=np.int32)


# Half-open [start, end) intervals as int32 pairs for the range arena /
# range-subject CSR. A _Successor endpoint (Range.point(k) ends "just above
# k") encodes as k+1 -- exact for integer keys, where nothing orders strictly
# between k and k+1. Non-integer or out-of-window endpoints are unencodable:
# the resolver falls back to the host range scan for those (counted).
_I32_MIN = -(1 << 31)
_I32_MAX = (1 << 31) - 1


def _encode_endpoint(p, successor: bool = False) -> Optional[int]:
    from accord_tpu.primitives.keyspace import _Successor
    if isinstance(p, _Successor):
        p = p.key
        successor = True
    if not isinstance(p, (int, np.integer)):
        return None
    v = int(p) + 1 if successor else int(p)
    if not (_I32_MIN < v < _I32_MAX):
        return None
    return v


def encode_interval(r) -> Optional[Tuple[int, int]]:
    """Range -> (start, end) int32 pair, or None when unencodable."""
    s = _encode_endpoint(r.start)
    e = _encode_endpoint(r.end)
    if s is None or e is None:
        return None
    return s, e


def encode_key_point_intervals(keys) -> Optional[List[Tuple[int, int, int]]]:
    """A KEY subject's owned keys as (key, start, end) point intervals
    [k, k+1), keeping the key alongside its entry so the finalized-CSR
    range path can attribute each device hit segment back to its real key
    (entries are 1:1 with keys; no merging). The interval pairs are exactly
    what encode_seekable_intervals emits for Keys, so feeding these to the
    candidate range kernel is bit-identical. None when any key is
    unencodable (the caller answers that subject's range deps host-side)."""
    out: List[Tuple[int, int, int]] = []
    for k in keys:
        s = _encode_endpoint(k)
        if s is None:
            return None
        out.append((k, s, s + 1))
    return out


def encode_seekable_intervals(seekables) -> Optional[List[Tuple[int, int]]]:
    """A subject's owned keys/ranges as interval pairs for the range kernel:
    keys become point intervals [k, k+1). None when any piece is
    unencodable (the caller answers that subject host-side)."""
    from accord_tpu.primitives.keyspace import Keys
    out: List[Tuple[int, int]] = []
    if isinstance(seekables, Keys):
        for k in seekables:
            s = _encode_endpoint(k)
            if s is None:
                return None
            out.append((s, s + 1))
        return out
    for r in seekables:
        iv = encode_interval(r)
        if iv is None:
            return None
        out.append(iv)
    return out
