"""Shared padded-size ladders and the out-cap hysteresis policy.

Every jit boundary in the data plane pads its data-dependent dimension to
a small named ladder (batch tiers, CSR nnz tiers, finalized-CSR out tiers,
lane-delta row tiers) so warmup() can pre-compile every shape the pipeline
will ever dispatch. `snap` is that ladder lookup written once -- kernels,
deltas, and the exec plane all route through it, so a new tier cannot
appear in one caller without the others (and warmup) seeing it.

`OutCapTiers` is the piece that makes the FINALIZE kernels warmable: their
out_cap used to be sized from an exact per-dispatch host popcount bound,
which (a) cost a host O(keys) pass per dispatch and (b) made the picked
tier data-dependent, so the bench had to exempt finalize kernels from its
zero-recompile assertion. The policy instead pins a tier with
grow-immediately / shrink-after-hysteresis dynamics, fed by the DEVICE
computed bound that rides back with each finalize result:

  * grow: a bound estimate above the pinned tier switches up immediately
    (correctness -- an undersized out_cap overflows and forces a host
    fallback decode);
  * shrink: only after `shrink_after` consecutive dispatches whose
    estimate fits a smaller tier (stability -- one quiet dispatch in a
    contended run must not flap the jit cache);
  * overflow: an observed `indptr[-1] > out_cap` bumps to the next rung
    right away, so at most one dispatch pays the fallback.

Estimates are WINDOWED: each dispatch's device bound lands in a rolling
window of the last `window` observations, and the estimate projects the
window's HIGH-WATER per-slot ratio onto the current slot count (plus
`headroom`, a >>3 fractional pad floored at `headroom_min`, absorbing the
staleness of riding one in-flight window behind the truth). High-water --
not last-value -- is what keeps bursty mixes stable: one overflow storm
bumps the tier once, and the storm's bound then holds the estimate up for
a full window, so quiet dispatches in between cannot oscillate the pinned
tier back down and re-trip the overflow (shrink hysteresis still applies
on top, after `shrink_after` consecutive below-tier estimates).
"""
from __future__ import annotations

from collections import deque
from typing import Callable, Optional, Tuple


def snap(n: int, tiers: Tuple[int, ...], floor: int) -> int:
    """Smallest named tier >= n; above the ladder, the next power-of-two
    bucket >= max(n, floor) (so oversized shapes stay warmable too)."""
    for tier in tiers:
        if n <= tier:
            return tier
    size = floor
    while size < n:
        size *= 2
    return size


# PreAccept quorum-lane ladder for the protocol megakernel
# (kernels.protocol_tick): one cluster tick's deferred cmd-plane spans stack
# into a single lane block for the fast-path electorate count, padded here
# so lane-count churn between ticks re-lands on compiled signatures.
MEGA_LANE_TIERS = (64, 256, 1024)


def mega_lane_tier(n: int) -> int:
    """Padded PreAccept quorum-lane count for one megakernel cluster tick."""
    return snap(n, MEGA_LANE_TIERS, 4096)


class OutCapTiers:
    """Hysteresis-pinned out_cap tier picker for the finalize kernels.

    One instance per (arena, finalize lane): the per-slot mean bound is a
    property of that arena's contention, not of the resolver globally.
    `on_switch` fires once per pinned-tier change (wired to the resolver's
    `outcap_tier_switches` counter).
    """

    __slots__ = ("tiers", "floor", "shrink_after", "headroom_shift",
                 "headroom_min", "on_switch", "current", "switches",
                 "_window", "_below")

    def __init__(self, tiers: Tuple[int, ...], floor: int,
                 shrink_after: int = 6, headroom_shift: int = 3,
                 headroom_min: int = 64, window: int = 16,
                 on_switch: Optional[Callable[[], None]] = None):
        self.tiers = tiers
        self.floor = floor
        self.shrink_after = shrink_after
        self.headroom_shift = headroom_shift
        self.headroom_min = headroom_min
        self.on_switch = on_switch
        self.current: Optional[int] = None
        self.switches = 0
        # rolling (bound, slots) observations; estimates project the
        # window's high-water per-slot ratio, so a burst's bound keeps the
        # estimate (and the pinned tier) up for `window` dispatches
        self._window: "deque[Tuple[int, int]]" = deque(maxlen=max(1, window))
        self._below = 0

    @property
    def cold(self) -> bool:
        """True until the first device bound has been observed -- the one
        dispatch where the caller must seed with its host-exact bound."""
        return not self._window

    def observe(self, bound: int, slots: int) -> None:
        """Record a dispatch's (device-computed) bound over `slots` CSR
        slots into the rolling window."""
        self._window.append((int(bound), max(int(slots), 1)))

    def estimate(self, slots: int) -> Optional[int]:
        """Projected bound for a dispatch of `slots` slots: the window
        high-water of each observation's per-slot ratio scaled to `slots`,
        plus headroom; None while cold (no observation to scale)."""
        if not self._window:
            return None
        s = max(int(slots), 1)
        base = max((num * s + den - 1) // den for num, den in self._window)
        pad = max(base >> self.headroom_shift, self.headroom_min)
        return base + pad

    def pick(self, bound: int) -> int:
        """Pin and return the out_cap tier for a dispatch whose bound
        estimate is `bound` (grow now, shrink after hysteresis)."""
        want = snap(max(int(bound), 1), self.tiers, self.floor)
        cur = self.current
        if cur is None:
            self.current = want
        elif want > cur:
            self._switch(want)
        elif want < cur:
            self._below += 1
            if self._below >= self.shrink_after:
                self._switch(want)
        else:
            self._below = 0
        return self.current

    def overflowed(self) -> int:
        """The pinned tier overflowed (indptr[-1] > out_cap): bump to the
        next rung immediately and return it."""
        cur = self.current if self.current is not None else self.floor
        self._switch(snap(cur + 1, self.tiers, self.floor))
        return self.current

    def _switch(self, tier: int) -> None:
        self.current = tier
        self._below = 0
        self.switches += 1
        if self.on_switch is not None:
            self.on_switch()
