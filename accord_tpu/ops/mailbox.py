"""Device mailbox arena: replica traffic as a routing stage in protocol_tick.

Each node lane owns a bounded SoA ring of `depth` slots x `words` int32
payload words in one flat arena of shape [(n+1)*depth, words] (row 0..depth-1
is the unused node-0 lane, matching the 1-based node-id convention of every
other lane family). A parallel meta arena [(n+1)*depth, 3] carries
(src, kind, seq) per slot -- kind is the interned message-class id, seq the
message's queue ticket, so delivery can verify provenance and ordering.

Message flow per cluster tick:

  emit   -- DeviceMessageNetwork.mailbox_flush() packs every in-flight
            payload (sim/wire bytes, word 0 = byte length header) into emit
            lanes padded to a MEGA_LANE_TIERS tier, allocating one slot in
            the destination's ring (deterministic lowest-free-first order);
  scatter -- _mailbox_route_body, fused into ops/kernels.protocol_tick,
            lands each kept emit at row dst*depth+slot unless the uploaded
            partition mask cuts the (src, dst) link, and gathers the landed
            words + meta straight back so the host can verify without
            copying the whole arena;
  drain  -- next deliveries read the device copy via read_landed(), compare
            it against the staged host bytes, and fall back to the host
            copy on any mismatch (partition epoch races, injected faults,
            overflow spills) -- the device path degrades, never diverges.

Overflow is graceful by design: an emit whose payload exceeds the slot width
or whose destination ring is full simply keeps its host bytes and bumps
`mailbox_overflow_spills`; the bench steady-state gate asserts that counter
stays zero at tuned depths.

Sharded meshes (`shards > 1`): the node lanes pad up so shard boundaries
fall on node boundaries (node v lives on shard v // npsh), the arena and
the partition mask both shard node-major over the mesh's 'data' axis, and
emit lanes stage GROUPED by (src shard, dst shard) -- segment (s, t) of
the flat lane arrays holds the lanes shard s emits toward shard t, so the
fused routing stage's `lax.all_to_all` over 'data' delivers every
cross-shard payload into its destination shard's rings in one collective
(`_sharded_mailbox_route_part`, composed into the sharded protocol
megakernel by parallel/mesh.sharded_protocol_tick). shards == 1 degrades
to the exact single-device layout bit for bit.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from accord_tpu.ops.tiers import mega_lane_tier


def pack_words(payload: bytes, width: int) -> Optional[np.ndarray]:
    """Encode payload bytes as [width] int32: word 0 the byte length, the
    rest the zero-padded little-words of the payload. None when the payload
    cannot fit (caller spills to the host path)."""
    if len(payload) > 4 * (width - 1):
        return None
    w = np.zeros(width, np.int32)
    w[0] = len(payload)
    if payload:
        buf = payload + b"\0" * (-len(payload) % 4)
        arr = np.frombuffer(buf, np.int32)
        w[1:1 + arr.size] = arr
    return w


def unpack_words(w: np.ndarray) -> bytes:
    """Inverse of pack_words: header-length bytes out of the word lanes."""
    n = int(w[0])
    return np.ascontiguousarray(w[1:1 + (n + 3) // 4],
                                np.int32).tobytes()[:n]


def _mailbox_route_body(arena, meta, e_src, e_dst, e_slot, e_keep,
                        e_kind, e_seq, e_words, part):
    """The fused routing stage: masked scatter of the tick's emits into
    destination rings plus a gather-back of what actually landed.

    arena  i32[(n+1)*depth, words]  payload rings (row = dst*depth + slot)
    meta   i32[(n+1)*depth, 3]      (src, kind, seq) per slot
    e_*    emit lanes, padded to a MEGA_LANE_TIERS tier (keep=False pads)
    part   bool[n+1, n+1]           True cuts the directed (src, dst) link

    Returns (arena, meta, landed_words, landed_meta, land): non-landing
    emits scatter to an out-of-range row (mode="drop"), and the gather-back
    lets the host verify each landed message without reading the arena.
    """
    rows = arena.shape[0]
    depth = rows // part.shape[0]
    land = e_keep & ~part[e_src, e_dst]
    flat = jnp.where(land, e_dst * depth + e_slot, rows)
    arena = arena.at[flat].set(e_words, mode="drop")
    meta = meta.at[flat].set(
        jnp.stack([e_src, e_kind, e_seq], axis=1), mode="drop")
    back = jnp.minimum(flat, rows - 1)
    return arena, meta, arena[back], meta[back], land


def _sharded_mailbox_route_part(shards, axis, arena_l, meta_l, e_src, e_dst,
                                e_slot, e_keep, e_kind, e_seq, e_words,
                                part_l):
    """Per-shard body of the cross-shard mailbox routing stage, run inside
    a shard_map over the mesh's `axis` ('data') by the sharded protocol
    megakernel.

    arena_l  i32[npsh*depth, words]  THIS shard's node rings (node-major)
    meta_l   i32[npsh*depth, 3]      (src, kind, seq) per local slot
    part_l   bool[npsh, rows_nodes]  partition rows for this shard's nodes
    e_*      this shard's SRC-grouped emit lanes, flat [shards*bcap]:
             segment t holds the lanes destined to shard t (keep=False pads)

    The land decision runs on the SOURCE shard (it owns the partition-mask
    rows for its src nodes), then every lane field -- land flag and payload
    words included -- rides one tiled `lax.all_to_all` over `axis`: segment
    t of each source shard's lanes lands as segment s on destination shard
    t, so after the exchange this shard holds exactly the lanes addressed
    to ITS rings, in (src shard, stage order) order. The local scatter and
    the verify gather-back then mirror _mailbox_route_body on the local row
    frame; the returned landed block stacks receiver-major, giving the
    host's per-entry return position (dst_shard*shards + src_shard)*bcap+j.
    shards == 1 is the degenerate identity exchange: same scatter, same
    landed order as the single-device body."""
    rows_l = arena_l.shape[0]
    npsh = part_l.shape[0]
    depth = rows_l // npsh
    d = jax.lax.axis_index(axis)
    src_loc = jnp.clip(e_src - d * npsh, 0, npsh - 1)
    land = e_keep & ~part_l[src_loc, e_dst]

    def xch(x):
        return jax.lax.all_to_all(x, axis, 0, 0, tiled=True)

    r_src, r_dst, r_slot = xch(e_src), xch(e_dst), xch(e_slot)
    r_kind, r_seq = xch(e_kind), xch(e_seq)
    r_words, r_land = xch(e_words), xch(land)
    dst_loc = r_dst - d * npsh
    flat = jnp.where(r_land & (dst_loc >= 0) & (dst_loc < npsh),
                     dst_loc * depth + r_slot, rows_l)
    arena_l = arena_l.at[flat].set(r_words, mode="drop")
    meta_l = meta_l.at[flat].set(
        jnp.stack([r_src, r_kind, r_seq], axis=1), mode="drop")
    back = jnp.minimum(flat, rows_l - 1)
    return arena_l, meta_l, arena_l[back], meta_l[back], r_land


class _Batch:
    """One flush's worth of landed device outputs, materialized host-side
    lazily (one transfer per launch, not per message). Entries reference
    their batch through slot tuples; the batch is garbage once the last of
    them delivers -- no explicit retirement needed."""

    __slots__ = ("outs", "host")

    def __init__(self):
        self.outs = None   # (landed, landed_meta, land) device arrays
        self.host = None   # same, as numpy, on first read


class MailboxPlane:
    """Host-side manager of the device mailbox arena: slot allocation per
    destination ring, emit-lane staging, partition-mask epochs, and the
    verify-on-read landing buffers."""

    def __init__(self, num_nodes: int, depth: int = 64, words: int = 384,
                 shards: int = 1):
        self.n = int(num_nodes)
        self.depth = int(depth)
        self.words = int(words)
        # shards > 1: pad the node-lane count so shard boundaries fall on
        # node boundaries (node v -> shard v // npsh); shards == 1 keeps
        # rows_nodes == n + 1, the exact single-device layout
        self.shards = max(int(shards), 1)
        self.npsh = -(-(self.n + 1) // self.shards)
        self.rows_nodes = self.npsh * self.shards
        self.arena = None       # device arrays, created on first stage
        self.meta = None
        self.part = None        # device partition mask for current epoch
        self.link_version: Optional[int] = None
        self._free: Dict[int, List[int]] = {}
        self._launched: Optional[_Batch] = None  # staged, awaiting adopt
        self.c: Dict[str, int] = {
            "mailbox_depth_high_water": 0,
            "mailbox_overflow_spills": 0,
            "mailbox_bytes_staged": 0,
            "mailbox_partition_epochs": 0,
        }

    # -- epoch config --------------------------------------------------------
    def set_partitions(self, partitioned, version: int) -> None:
        mask = np.zeros((self.rows_nodes, self.rows_nodes), bool)
        for pair in partitioned:
            a, b = tuple(pair)
            mask[a, b] = mask[b, a] = True
        self.part = jnp.asarray(mask)
        self.link_version = version
        self.c["mailbox_partition_epochs"] += 1

    # -- staging -------------------------------------------------------------
    def stage_batch(self, entries):
        """Allocate a destination slot per entry (lowest-free-first, so the
        order is deterministic), pack payloads into emit lanes, and return
        the kernel-ready mailbox input tuple -- or None when every entry
        spilled. Entries that cannot be slotted keep slot=None and deliver
        from their host bytes (counted as overflow spills)."""
        staged = []
        for e in entries:
            w = pack_words(e.payload, self.words)
            free = self._free.get(e.dst)
            if free is None:
                free = self._free[e.dst] = list(range(self.depth - 1, -1, -1))
            if w is None or not free:
                self.c["mailbox_overflow_spills"] += 1
                continue
            idx = free.pop()
            occupancy = self.depth - len(free)
            if occupancy > self.c["mailbox_depth_high_water"]:
                self.c["mailbox_depth_high_water"] = occupancy
            staged.append((e, idx, w))
        if not staged:
            return None
        if self.arena is None:
            rows = self.rows_nodes * self.depth
            self.arena = jnp.zeros((rows, self.words), jnp.int32)
            self.meta = jnp.zeros((rows, 3), jnp.int32)
        if self.part is None:
            self.set_partitions((), self.link_version or 0)
        # lanes stage grouped by (src shard, dst shard): segment (s, t) of
        # the flat arrays holds shard s's emits toward shard t, so the
        # sharded route's all_to_all delivers each segment whole. With
        # shards == 1 there is one group and this is exactly the old flat
        # staging-order layout.
        S, npsh = self.shards, self.npsh
        groups: Dict[tuple, list] = {}
        for ent in staged:
            e = ent[0]
            groups.setdefault((e.src // npsh, e.dst // npsh), []).append(ent)
        bcap = mega_lane_tier(max(len(g) for g in groups.values()))
        cap = S * S * bcap
        e_src = np.zeros(cap, np.int32)
        e_dst = np.zeros(cap, np.int32)
        e_slot = np.zeros(cap, np.int32)
        e_keep = np.zeros(cap, bool)
        e_kind = np.zeros(cap, np.int32)
        e_seq = np.zeros(cap, np.int32)
        e_words = np.zeros((cap, self.words), np.int32)
        batch = _Batch()
        for (s, t), ents in groups.items():
            for j, (e, idx, w) in enumerate(ents):
                pos = (s * S + t) * bcap + j
                # the landed block comes back receiver-major (identity for
                # shards == 1): the entry's return position swaps s and t
                e.slot = (batch, (t * S + s) * bcap + j, e.dst, idx)
                e_src[pos] = e.src
                e_dst[pos] = e.dst
                e_slot[pos] = idx
                e_keep[pos] = True
                e_kind[pos] = e.kind
                e_seq[pos] = e.ticket & 0x7FFFFFFF
                e_words[pos] = w
                self.c["mailbox_bytes_staged"] += len(e.payload)
        self._launched = batch
        return (self.arena, self.meta, e_src, e_dst, e_slot, e_keep,
                e_kind, e_seq, e_words, self.part)

    def adopt(self, outs) -> None:
        """Take the routing stage's outputs for the batch staged by the
        matching stage_batch call: new arena/meta device state plus the
        landed gather the deliveries will verify against."""
        arena, meta, landed, landed_meta, land = outs
        self.arena = arena
        self.meta = meta
        if self._launched is not None:
            self._launched.outs = (landed, landed_meta, land)
            self._launched = None

    # -- delivery ------------------------------------------------------------
    def read_landed(self, entry) -> Optional[bytes]:
        """The device-routed copy of an entry's payload, or None when it
        never landed (partition mask, not yet launched) -- the caller then
        delivers the retained host bytes."""
        batch, pos, _dst, _idx = entry.slot
        if batch.host is None:
            if batch.outs is None:
                return None  # staged but its launch never adopted
            landed, landed_meta, land = batch.outs
            batch.host = (np.asarray(landed), np.asarray(landed_meta),
                          np.asarray(land))
            batch.outs = None
        words, meta, land = batch.host
        if not bool(land[pos]):
            return None
        if int(meta[pos, 0]) != entry.src or int(meta[pos, 1]) != entry.kind \
                or int(meta[pos, 2]) != (entry.ticket & 0x7FFFFFFF):
            return None
        w = words[pos]
        from accord_tpu.ops import fault_plane as _fp
        if _fp.ACTIVE is not None:
            w = np.array(w)  # corrupt a local copy, never the batch buffer
            if not _fp.ACTIVE.corrupt_mailbox(w):
                w = words[pos]
        return unpack_words(w)

    def release(self, slot) -> None:
        """Free a delivered entry's ring slot (LIFO reuse keeps allocation
        deterministic)."""
        _batch, _pos, dst, idx = slot
        self._free[dst].append(idx)

    def counters(self) -> Dict[str, int]:
        return dict(self.c)
