"""JAX kernels for the deps data plane.

Design notes (TPU-first):
  - The conflict test is a boolean matmul: bitmap[B,K] @ bitmap[A,K]^T on the
    MXU in bfloat16 with float32 accumulation. K (key buckets) is a multiple
    of 128 (lane width); B and A are padded to multiples of 8 (sublanes).
  - Kind filtering is a gather from the 6x6 witness table; timestamp
    comparison is lexicographic over two int32 lanes -- both VPU element-wise
    ops XLA fuses into the matmul epilogue.
  - Transitive closure is iterated boolean matmul (repeated squaring), the
    standard reachability-by-matmul formulation; log2(N) MXU rounds.
All functions are jit-compiled with static shapes; callers pad to bucket
sizes (see resolver.py) so compilation caches are hit.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from accord_tpu.ops.tiers import snap


def _lex_before(a, b):
    """a < b lexicographically over 3 int32 lanes; a: [..., 3], b: [..., 3]
    (broadcasting)."""
    return ((a[..., 0] < b[..., 0])
            | ((a[..., 0] == b[..., 0])
               & ((a[..., 1] < b[..., 1])
                  | ((a[..., 1] == b[..., 1]) & (a[..., 2] < b[..., 2])))))


@functools.partial(jax.jit, static_argnames=())
def deps_matrix(subj_bitmaps, subj_before, subj_kinds,
                act_bitmaps, act_ts, act_kinds, act_valid,
                witness_table):
    """Pairwise dependency matrix.

    subj_bitmaps: f32[B, K]   keys touched by each subject txn
    subj_before:  i32[B, 3]   'started before' bound per subject (usually the
                              witnessed executeAt; reference semantics of
                              mapReduceActive STARTED_BEFORE)
    subj_kinds:   i32[B]
    act_bitmaps:  f32[A, K]   active-set key bitmaps
    act_ts:       i32[A, 3]   active txn ids (3-lane window-relative encoding)
    act_kinds:    i32[A]
    act_valid:    bool[A]     false for padding / invalidated entries
    witness_table: i32[6, 6]

    -> bool[B, A] : dep[b, a] == True iff active txn a is a dependency of
                    subject b (keys overlap AND subject witnesses a's kind AND
                    a started before b's bound AND a != b).
    """
    overlap = jax.lax.dot_general(
        subj_bitmaps.astype(jnp.bfloat16), act_bitmaps.astype(jnp.bfloat16),
        (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32) > 0.5
    witness = witness_table[subj_kinds[:, None], act_kinds[None, :]] == 1
    before = _lex_before(act_ts[None, :, :], subj_before[:, None, :])
    return overlap & witness & before & act_valid[None, :]


@functools.partial(jax.jit, static_argnames=())
def max_conflict(subj_bitmaps, act_bitmaps, act_exec_ts, act_valid):
    """Max witnessed-conflict timestamp per subject (feeds the fast-path test
    txnId >= maxConflicts; reference: MaxConflicts + CommandStore.preaccept).
    Kind-agnostic, like the reference's MaxConflicts: ANY registered txn on a
    shared key raises the timestamp floor.

    act_exec_ts: i32[A, 3] -- max(executeAt, txnId) per active txn.
    -> (i32[B, 3] lexicographic max (INT32_MIN lanes where no conflict),
        i32[B] winning row (-1 where none)).
    """
    overlap = jax.lax.dot_general(
        subj_bitmaps.astype(jnp.bfloat16), act_bitmaps.astype(jnp.bfloat16),
        (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32) > 0.5
    mask = overlap & act_valid[None, :]
    neg = jnp.int32(np.iinfo(np.int32).min)
    # lexicographic max without int64: successive tie-narrowing per lane
    l0 = jnp.where(mask, act_exec_ts[None, :, 0], neg)
    m0 = jnp.max(l0, axis=1)
    tie0 = mask & (act_exec_ts[None, :, 0] == m0[:, None])
    l1 = jnp.where(tie0, act_exec_ts[None, :, 1], neg)
    m1 = jnp.max(l1, axis=1)
    tie1 = tie0 & (act_exec_ts[None, :, 1] == m1[:, None])
    l2 = jnp.where(tie1, act_exec_ts[None, :, 2], neg)
    m2 = jnp.max(l2, axis=1)
    tie2 = tie1 & (act_exec_ts[None, :, 2] == m2[:, None])
    # winning row per subject (first among ties); -1 when no conflict
    row = jnp.where(jnp.any(tie2, axis=1),
                    jnp.argmax(tie2, axis=1).astype(jnp.int32), -1)
    return jnp.stack([m0, m1, m2], axis=1), row


@functools.partial(jax.jit, static_argnames=("iterations",))
def transitive_closure(adj, iterations: int):
    """Reachability closure of a boolean adjacency matrix by repeated
    squaring: R_{i+1} = R_i | (R_i @ R_i). `iterations` >= ceil(log2(N)).
    (the execute-order closure kernel; BASELINE config 'Synthetic Execute
    DAG')."""

    def body(_, r):
        rf = r.astype(jnp.bfloat16)
        sq = jax.lax.dot_general(rf, rf, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32) > 0.5
        return r | sq

    return jax.lax.fori_loop(0, iterations, body, adj)


@functools.partial(jax.jit, static_argnames=("max_levels",))
def execution_wavefronts(adj, max_levels: int):
    """Topological execution levels of a dependency DAG: level[i] = longest
    dependency chain ending at i (the order the execution engine may release
    txns in parallel waves). adj[i, j] == True iff i depends on j.
    -> i32[N] levels (max_levels if a cycle prevents settling)."""
    n = adj.shape[0]

    def body(_, level):
        # level'_i = 1 + max_j adj[i,j] * level_j   (0 if no deps)
        dep_levels = jnp.where(adj, level[None, :] + 1, 0)
        return jnp.maximum(level, jnp.max(dep_levels, axis=1))

    return jax.lax.fori_loop(0, max_levels, body, jnp.zeros(n, jnp.int32))


def _lex_le(a, b):
    """a <= b lexicographically over 3 int32 lanes (broadcasting)."""
    return ~_lex_before(b, a)


def _frontier_ready(adj, exec_ts, applied, pending, awaits_all):
    """The release test shared by the per-store and fused frontier kernels:
    pending rows whose gates are all clear (dep applied, or dep decided to
    execute after us and we are not an awaits-all kind)."""
    dep_le = _lex_le(exec_ts[None, :, :], exec_ts[:, None, :])  # dep <= waiter
    gates = adj & (~applied)[None, :] & (dep_le | awaits_all[:, None])
    return pending & ~jnp.any(gates, axis=1)


@jax.jit
def execution_frontier(adj, exec_ts, applied, pending, awaits_all):
    """The device execution scheduler's release test (reference: the host
    WaitingOn bitsets + Commands.maybeExecute walk, local/Command.java:1224,
    local/Commands.java:960 -- recomputed in batch on device instead of
    per-edge on the host).

    adj:        bool[cap, cap] dep adjacency; adj[w, d] iff w holds a wait
                edge on arena row d. Kept UNPACKED on device: exec_scatter
                unpacks uploaded rows once, so the per-tick frontier never
                re-expands the whole matrix.
    exec_ts:    i32[cap, 3]  executeAt lanes (INT32_MIN while undecided --
                an undecided dep always gates, the commit-wait)
    applied:    bool[cap]    dep applied (or terminal: no longer gates)
    pending:    bool[cap]    row is STABLE/PRE_APPLIED awaiting release
    awaits_all: bool[cap]    row's kind waits for EVERY dep to apply
                (ExclusiveSyncPoint / EphemeralRead), regardless of
                executeAt order

    -> u32[cap/32] packed release frontier: pending rows whose gates are all
    clear (dep applied, or dep decided to execute after us and we are not an
    awaits-all kind).
    """
    cap = adj.shape[0]
    bits = jnp.arange(32, dtype=jnp.uint32)
    ready = _frontier_ready(adj, exec_ts, applied, pending, awaits_all)
    weights = jnp.uint32(1) << bits
    return jnp.sum(ready.reshape(cap // 32, 32).astype(jnp.uint32)
                   * weights[None, :], axis=-1, dtype=jnp.uint32)


@jax.jit
def fused_execution_frontier(planes):
    """Cross-store fused twin of execution_frontier: one device call answers
    every store's release frontier for a node tick. `planes` is a TUPLE of
    per-store lane tuples (adj, exec_ts, applied, pending, awaits_all) -- jit
    specializes on the tuple structure, so the participating-store count and
    each store's cap are warmable tiers exactly like the resolver's fused
    dispatch. Per-store packed frontiers concatenate along the word axis; the
    host slices them back out with per-store word spans.

    -> u32[sum(cap_s)/32] packed release frontier, store blocks in tuple order
    """
    outs = []
    bits = jnp.arange(32, dtype=jnp.uint32)
    weights = jnp.uint32(1) << bits
    for (adj, exec_ts, applied, pending, awaits_all) in planes:
        cap = adj.shape[0]
        ready = _frontier_ready(adj, exec_ts, applied, pending, awaits_all)
        outs.append(jnp.sum(ready.reshape(cap // 32, 32).astype(jnp.uint32)
                            * weights[None, :], axis=-1, dtype=jnp.uint32))
    return jnp.concatenate(outs)


@functools.partial(jax.jit, static_argnames=("max_levels",))
def dag_wavefronts_packed(adj_packed, max_levels: int):
    """Topological release levels of a dependency DAG at scale (the BASELINE
    'Synthetic Execute DAG' config: 100k nodes). Works entirely on packed
    words -- never materializes the N x N boolean matrix -- so memory is
    N^2/8 bytes and each round is N x N/32 u32 lanes on the VPU.

    adj_packed: u32[N, N/32]; bit d of row w set iff w depends on d.
    -> i32[N] level per node (-1 if not settled within max_levels).
    """
    n, words = adj_packed.shape
    bits = jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32)

    def body(i, state):
        applied_packed, level = state
        blocked = jnp.any(adj_packed & (~applied_packed)[None, :] != 0, axis=1)
        ready = ~blocked & (level < 0)
        level = jnp.where(ready, i, level)
        rp = jnp.sum(ready.reshape(words, 32).astype(jnp.uint32)
                     * bits[None, :], axis=-1, dtype=jnp.uint32)
        return applied_packed | rp, level

    state = (jnp.zeros(words, jnp.uint32), jnp.full(n, -1, jnp.int32))
    _, level = jax.lax.fori_loop(0, max_levels, body, state)
    return level


@jax.jit
def exec_scatter(adj, exec_ts, applied, pending, awaits_all,
                 rows, adj_rows_packed, ts_rows, applied_rows, pending_rows,
                 awaits_rows):
    """Scatter dirty rows into the execution arena. Adjacency rows arrive
    PACKED from the host (cap/8 bytes per row over the slow link) and are
    unpacked on device into the resident bool matrix."""
    cap = adj.shape[0]
    bits = jnp.arange(32, dtype=jnp.uint32)
    unpacked = (((adj_rows_packed[:, :, None] >> bits[None, None, :]) & 1) > 0) \
        .reshape(adj_rows_packed.shape[0], cap)
    return (adj.at[rows].set(unpacked),
            exec_ts.at[rows].set(ts_rows),
            applied.at[rows].set(applied_rows),
            pending.at[rows].set(pending_rows),
            awaits_all.at[rows].set(awaits_rows))


@jax.jit
def scatter_rows(dst, idx, rows):
    """dst[cap, ...] with dst[idx[i]] = rows[i] -- the incremental device
    active-set update (dirty rows only; jit caches per (cap, len(idx)) shape
    bucket)."""
    return dst.at[idx].set(rows)


@jax.jit
def kid_word_scatter(kid_rows, kid_idx, word_idx, words):
    """Incremental update of the per-key packed row-mask mirror
    (finalize_csr's kid_rows lane): write whole u32 WORDS at (kid, word)
    coordinates. The host dedupes coordinates and sources each word's full
    current value, so duplicate-index write hazards never arise; padding
    entries use kid_idx == KC (out of bounds, dropped)."""
    return kid_rows.at[kid_idx, word_idx].set(words, mode="drop")


def _pack_bits(m):
    """bool[B, A] -> u32[B, A/32] little-bit-first per lane (A % 32 == 0)."""
    b, a = m.shape
    weights = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))
    return jnp.sum(m.reshape(b, a // 32, 32).astype(jnp.uint32)
                   * weights[None, None, :], axis=-1, dtype=jnp.uint32)


@jax.jit
def deps_resolve(subj_of, subj_keys, subj_before, subj_kinds,
                 act_bitmaps, act_ts, act_kinds, act_valid,
                 witness_table):
    """The fused hot-path kernel: subject bitmaps built ON DEVICE from a
    variable-width CSR key list (uploading 2 x nnz int32 instead of B x K
    float bitmaps -- the host->device link is the bottleneck, see
    resolver.py). The CSR replaces the old fixed i32[B, MAXK] scatter:
    arbitrarily wide subjects stay on the device path instead of demoting to
    a host residual scan. The pairwise conflict matrix is BIT-PACKED on
    device for the readback: 32 arena rows per uint32 lane, so the transfer
    is B x cap/8 bytes regardless of how many dependencies each subject has
    (a top-k index list was tried first: its coverage/latency trade collapses
    under contention where counts reach hundreds).

    subj_of:     i32[nnz]      subject row per CSR entry (padding entries use
                               B -- out of bounds, dropped by the scatter)
    subj_keys:   i32[nnz]      key bucket indices (already % K)
    subj_before: i32[B, 3]     'started before' bound per subject (3-lane
                               encoding)
    subj_kinds:  i32[B]
    act_*:       the device arena (see resolver._StoreArena); cap % 32 == 0
    -> u32[B, cap/32] packed dependency bitmask, little-bit-first per lane
    """
    b = subj_before.shape[0]
    k = act_bitmaps.shape[1]
    subj_bm = jnp.zeros((b, k), jnp.float32) \
        .at[subj_of, subj_keys].max(1.0, mode="drop").astype(jnp.bfloat16)
    overlap = jax.lax.dot_general(
        subj_bm, act_bitmaps.astype(jnp.bfloat16),
        (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32) > 0.5
    witness = witness_table[subj_kinds[:, None], act_kinds[None, :]] == 1
    before = _lex_before(act_ts[None, :, :], subj_before[:, None, :])
    m = overlap & witness & before & act_valid[None, :]
    return _pack_bits(m)


@jax.jit
def fused_deps_resolve(subj_of, subj_keys, subj_store, subj_before,
                       subj_kinds, slots, arenas, witness_table):
    """Cross-store fused twin of deps_resolve: one device call answers every
    store's slice of a node tick. `arenas` is a TUPLE of per-store lane
    tuples (bitmaps, ts, kinds, valid) -- jit specializes on the tuple
    structure, so the participating-store count is a warmable tier exactly
    like the batch size. The subject bitmap is built ONCE from the CSR; each
    store's block masks by the store-id lane (subj_store == slots[s]) so a
    subject only sees its own store's rows, and the per-store packed blocks
    concatenate into one u32[B, sum(cap_s)/32] readback whose word offsets
    are the host-side row-offset table.

    subj_store: i32[B]   group slot per subject (padding rows use a slot no
                         entry of `slots` matches)
    slots:      i32[S]   the group slot each arena block answers (traced, so
                         slot assignment never recompiles)
    arenas:     tuple of S (bitmaps f32[cap_s, K], ts i32[cap_s, 3],
                kinds i32[cap_s], valid bool[cap_s])
    -> u32[B, sum(cap_s)/32] packed dependency bitmask, store blocks in
       `arenas` order
    """
    b = subj_before.shape[0]
    k = arenas[0][0].shape[1]
    subj_bm = jnp.zeros((b, k), jnp.float32) \
        .at[subj_of, subj_keys].max(1.0, mode="drop").astype(jnp.bfloat16)
    outs = []
    for s, (act_bm, act_ts, act_kinds, act_valid) in enumerate(arenas):
        overlap = jax.lax.dot_general(
            subj_bm, act_bm.astype(jnp.bfloat16),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) > 0.5
        witness = witness_table[subj_kinds[:, None], act_kinds[None, :]] == 1
        before = _lex_before(act_ts[None, :, :], subj_before[:, None, :])
        mine = (subj_store == slots[s])[:, None]
        outs.append(_pack_bits(
            overlap & witness & before & act_valid[None, :] & mine))
    return jnp.concatenate(outs, axis=1)


def covered_buckets(iv_of, iv_start, iv_end, b, k_local, base, k_total):
    """Per-subject covered-bucket mask from a CSR interval list under the
    modular bucket hash `key % k_total`: bucket `base + j` is covered by the
    half-open interval [s, e) iff some integer in [s, e) lands in it, i.e.
    `(base + j - s) mod k_total < e - s`. Intervals spanning >= k_total keys
    (and degenerate/padding widths <= 0) cover every bucket. Exact under
    int32 wraparound ONLY when k_total divides 2^32 -- callers assert
    power-of-two bucket counts. `base` may be traced (shard_map axis offset);
    single-device callers pass 0 with k_local == k_total.

    -> bf16[b, k_local] covered-bucket matrix (padding iv_of == b dropped)
    """
    j = base + jnp.arange(k_local, dtype=jnp.int32)
    width = iv_end - iv_start
    wide = (width <= 0) | (width >= k_total)
    covered = wide[:, None] | (
        jnp.mod(j[None, :] - iv_start[:, None], k_total) < width[:, None])
    return jnp.zeros((b, k_local), jnp.float32) \
        .at[iv_of].max(covered.astype(jnp.float32), mode="drop") \
        .astype(jnp.bfloat16)


@jax.jit
def fused_range_deps_resolve(iv_of, iv_start, iv_end, subj_store,
                             subj_before, subj_kinds, subj_is_range,
                             r_slots, rarenas, k_slots, karenas,
                             witness_table):
    """Cross-store fused twin of range_deps_resolve. `rarenas` holds the
    participating stores' RANGE-arena lanes (starts, ends, ts, kinds, valid),
    `karenas` the stores' key-arena lanes (bitmaps, ts, kinds, valid) tested
    by covered-bucket contraction (see range_deps_resolve); either tuple may
    be empty (that side returns a zero-width buffer). Store routing works
    like fused_deps_resolve: each block masks by its slot in the subj_store
    lane, and blocks concatenate along the packed word axis in tuple order.

    -> (u32[B, sum(rcap_s)/32], u32[B, sum(cap_s)/32])
    """
    b = subj_before.shape[0]
    routs = []
    for s, (r_start, r_end, r_ts, r_kinds, r_valid) in enumerate(rarenas):
        rcap = r_start.shape[0]
        hit_r = (iv_start[:, None] < r_end[None, :]) \
            & (r_start[None, :] < iv_end[:, None])
        any_r = jnp.zeros((b, rcap), jnp.int32) \
            .at[iv_of].max(hit_r.astype(jnp.int32), mode="drop") > 0
        witness_r = witness_table[subj_kinds[:, None], r_kinds[None, :]] == 1
        before_r = _lex_before(r_ts[None, :, :], subj_before[:, None, :])
        mine = (subj_store == r_slots[s])[:, None]
        routs.append(_pack_bits(
            any_r & witness_r & before_r & r_valid[None, :] & mine))
    kouts = []
    if karenas:
        k = karenas[0][0].shape[1]
        cov = covered_buckets(iv_of, iv_start, iv_end, b, k, 0, k)
    for s, (k_bm, k_ts, k_kinds, k_valid) in enumerate(karenas):
        any_k = jax.lax.dot_general(
            cov, k_bm.astype(jnp.bfloat16),
            (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32) > 0.5
        witness_k = witness_table[subj_kinds[:, None], k_kinds[None, :]] == 1
        before_k = _lex_before(k_ts[None, :, :], subj_before[:, None, :])
        mine = (subj_store == k_slots[s])[:, None] & subj_is_range[:, None]
        kouts.append(_pack_bits(
            any_k & witness_k & before_k & k_valid[None, :] & mine))
    rpacked = jnp.concatenate(routs, axis=1) if routs \
        else jnp.zeros((b, 0), jnp.uint32)
    kpacked = jnp.concatenate(kouts, axis=1) if kouts \
        else jnp.zeros((b, 0), jnp.uint32)
    return rpacked, kpacked


@jax.jit
def range_deps_resolve(iv_of, iv_start, iv_end, subj_before, subj_kinds,
                       subj_is_range,
                       r_start, r_end, r_ts, r_kinds, r_valid,
                       k_bm, k_ts, k_kinds, k_valid,
                       witness_table):
    """The fused RANGE-overlap kernel: every subject carries a CSR list of
    half-open int32 intervals (a key subject's keys become point intervals
    [k, k+1); a range subject's owned ranges upload as-is), tested against

      - the RANGE arena by branch-free interval overlap
        (iv_start < r_end & r_start < iv_end), which for a point interval
        degenerates to the stabbing test r_start <= key < r_end; and
      - the KEY arena by covered-bucket contraction: the subject's intervals
        expand to a covered-bucket mask (covered_buckets) contracted against
        the per-row key bitmaps on the MXU -- range subjects only (key
        subjects get exact key deps from deps_resolve); the host decode
        filters bucket-collision false positives per real key. This replaces
        the old per-row [kmin, kmax] hull span compare: a sparse row with a
        wide key spread no longer candidates every interval inside its hull,
        only intervals actually sharing a bucket.

    Sorted-endpoint broadcast compares beat an interval tree here: the tree's
    pointer-chasing descent is serial and branchy, while [nv, rcap] compares
    are pure VPU work XLA fuses with the witness/before masks.

    iv_of:         i32[nv]   subject row per interval (padding -> B, dropped)
    iv_start/end:  i32[nv]   half-open interval endpoints
    subj_before:   i32[B, 3] 'started before' bound per subject
    subj_kinds:    i32[B]
    subj_is_range: bool[B]   True for range-domain subjects (gates the
                             key-arena output)
    r_*:           the range arena (resolver._RangeArena); rcap % 32 == 0
    k_*:           the key arena lanes (k_bm f32[cap, K]); cap % 32 == 0,
                   K a power of two (covered_buckets wraparound)
    -> (u32[B, rcap/32], u32[B, cap/32]) packed candidate bitmasks, masked by
       witness/before/valid exactly like deps_resolve
    """
    b = subj_before.shape[0]
    rcap = r_start.shape[0]
    k = k_bm.shape[1]
    hit_r = (iv_start[:, None] < r_end[None, :]) \
        & (r_start[None, :] < iv_end[:, None])
    any_r = jnp.zeros((b, rcap), jnp.int32) \
        .at[iv_of].max(hit_r.astype(jnp.int32), mode="drop") > 0
    witness_r = witness_table[subj_kinds[:, None], r_kinds[None, :]] == 1
    before_r = _lex_before(r_ts[None, :, :], subj_before[:, None, :])
    m_r = any_r & witness_r & before_r & r_valid[None, :]
    cov = covered_buckets(iv_of, iv_start, iv_end, b, k, 0, k)
    any_k = jax.lax.dot_general(
        cov, k_bm.astype(jnp.bfloat16),
        (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32) > 0.5
    witness_k = witness_table[subj_kinds[:, None], k_kinds[None, :]] == 1
    before_k = _lex_before(k_ts[None, :, :], subj_before[:, None, :])
    m_k = any_k & witness_k & before_k & k_valid[None, :] \
        & subj_is_range[:, None]
    return _pack_bits(m_r), _pack_bits(m_k)


def _segment_compact(hits, out_cap: int):
    """Segment compaction: per-segment popcount -> exclusive prefix sum ->
    masked scatter. `hits` is i32[S, N] (0/1); returns (indptr i32[S+1],
    dep_rows i32[out_cap]) where dep_rows packs the hit COLUMN indices of all
    segments contiguously in (segment-major, column-ascending) order. Hits
    beyond out_cap are dropped by the scatter; callers detect overflow via
    indptr[-1] > out_cap and fall back."""
    s, n = hits.shape
    counts = jnp.sum(hits, axis=1, dtype=jnp.int32)
    indptr = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(counts, dtype=jnp.int32)])
    within = jnp.cumsum(hits, axis=1, dtype=jnp.int32) - hits
    pos = jnp.where(hits > 0, indptr[:-1][:, None] + within, out_cap)
    col = jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32)[None, :], (s, n))
    dep_rows = jnp.zeros(out_cap, jnp.int32) \
        .at[pos.reshape(-1)].set(col.reshape(-1), mode="drop")
    return indptr, dep_rows


def _popcount_u32(x):
    """Branch-free SWAR popcount per u32 lane (jnp.bitwise_count is not
    available across the supported jax versions)."""
    x = x - ((x >> 1) & jnp.uint32(0x55555555))
    x = (x & jnp.uint32(0x33333333)) + ((x >> 2) & jnp.uint32(0x33333333))
    x = (x + (x >> 4)) & jnp.uint32(0x0F0F0F0F)
    return ((x * jnp.uint32(0x01010101)) >> 24).astype(jnp.int32)


def _packed_segment_compact(m, out_cap: int):
    """_segment_compact over BIT-PACKED segments: `m` is u32[S, W] (each
    segment a packed row set, cap == W*32). Crucially never materializes the
    S x cap bit matrix -- popcounts and prefix sums run word-packed (S*W
    elements), only the <= out_cap NONZERO words expand to bit granularity
    (out_cap x 32). At dispatch shapes (S=2k segments, cap=16k rows) that is
    ~30x less intermediate traffic than the dense path, which dominated the
    kernel's wall time. Output contract matches _segment_compact: (indptr
    i32[S+1], dep_rows i32[out_cap]) in (segment-major, row-ascending)
    order; indptr[-1] > out_cap signals overflow (a nonzero word count can
    never exceed the bit count, so the word compaction cannot overflow
    without the bit total overflowing too)."""
    s, w = m.shape
    pop = _popcount_u32(m)                                    # i32[S, W]
    counts = jnp.sum(pop, axis=1, dtype=jnp.int32)
    indptr = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(counts, dtype=jnp.int32)])
    flat_pop = pop.reshape(-1)
    flat_val = m.reshape(-1)
    # global output offset of each word's first bit (word-major order ==
    # segment-major, row-ascending)
    bit_off = jnp.cumsum(flat_pop, dtype=jnp.int32) - flat_pop
    nz = flat_pop > 0
    slot = jnp.where(nz,
                     jnp.cumsum(nz.astype(jnp.int32), dtype=jnp.int32) - 1,
                     out_cap)
    # compact the nonzero words: ONE S*W-entry scatter of flat indices, then
    # out_cap-sized gathers for (value, bit offset, base row index) -- three
    # full-size scatters here tripled the kernel's wall time
    src = jnp.zeros(out_cap, jnp.int32) \
        .at[slot].set(jnp.arange(s * w, dtype=jnp.int32), mode="drop")
    live = jnp.arange(out_cap, dtype=jnp.int32) \
        < jnp.sum(nz.astype(jnp.int32))
    cw_val = jnp.where(live, flat_val[src], jnp.uint32(0))
    cw_off = bit_off[src]
    cw_row = (src % w) * 32
    # bit-expand only the compacted words
    bits = ((cw_val[:, None] >> jnp.arange(32, dtype=jnp.uint32)) & 1) \
        .astype(jnp.int32)                                    # [out_cap, 32]
    within = jnp.cumsum(bits, axis=1, dtype=jnp.int32) - bits
    pos = jnp.where((bits > 0) & live[:, None], cw_off[:, None] + within,
                    out_cap)
    rows = cw_row[:, None] + jnp.arange(32, dtype=jnp.int32)[None, :]
    dep_rows = jnp.zeros(out_cap, jnp.int32) \
        .at[pos.reshape(-1)].set(rows.reshape(-1), mode="drop")
    return indptr, dep_rows


def _csum_fold(x, seed: int):
    """Position-weighted fold of one CSR lane into a u32 word: bitcast to
    u32, mix the high half down, then a wrapping sum weighted by odd
    per-position multipliers (odd => invertible mod 2^32, so transposing
    or flipping any element changes the sum). Runs in-jit on device; the
    host twin is csr_checksum_host. Sums mod 2^32 are order-independent,
    so device reduction order cannot diverge from numpy's."""
    v = jax.lax.bitcast_convert_type(x.reshape(-1), jnp.uint32)
    v = v ^ (v >> jnp.uint32(16))
    idx = jnp.arange(v.shape[0], dtype=jnp.uint32)
    return jnp.sum(v * (jnp.uint32(2) * idx + jnp.uint32(seed)),
                   dtype=jnp.uint32)


def csr_checksum(indptr, dep_rows, dep_ts):
    """Device-side integrity word over a finalized CSR triple, fused into
    the finalize kernels' returns and re-derived from the host copies at
    harvest (resolver._csum_ok): a readback that arrives bit-flipped can
    never decode into wrong deps -- the mismatch routes the group to the
    legacy fallback, which re-reads the raw candidate buffers."""
    return (_csum_fold(indptr, 1) ^ _csum_fold(dep_rows, 5)
            ^ _csum_fold(dep_ts, 9))


def csr_checksum_host(indptr, dep_rows, dep_ts) -> int:
    """numpy twin of csr_checksum, computed over the fetched host copies.
    Must track the device fold bit for bit."""
    def fold(x, seed):
        v = np.ascontiguousarray(x).view(np.uint32).reshape(-1)
        v = v ^ (v >> np.uint32(16))
        idx = np.arange(v.shape[0], dtype=np.uint32)
        return (v * (np.uint32(2) * idx + np.uint32(seed))).sum(
            dtype=np.uint32)
    return int(fold(indptr, 1) ^ fold(dep_rows, 5) ^ fold(dep_ts, 9))


@functools.partial(jax.jit, static_argnames=("out_cap",))
def finalize_csr(packed, word_off, kid_rows, slot_subj, slot_kid,
                 subj_row, act_ts, out_cap: int):
    """Device-side dep FINALIZATION for the key domain: consume the packed
    conflict bitmask straight out of deps_resolve (or one store's word span
    of the fused/sharded output -- `word_off` is the traced span offset) and
    emit final, exact, already-translated dep lists in CSR form, so harvest
    becomes a contiguous readback instead of unpackbits + re-filtering.

    Exactness comes from the device mirror of the host's per-key row masks:
    `kid_rows[kid]` is the packed set of arena rows whose key set contains
    the real key with dense id `kid` (resolver._StoreArena.key_rows shipped
    as a lane). ANDing it against the subject's packed bucket-level result
    removes bucket-collision false positives ON DEVICE -- the per-(subject,
    key) slot list replaces the host KM gather stack.

    packed:    u32[B, W_total] deps_resolve / fused output
    word_off:  i32 scalar      word offset of this store's span (0 unfused)
    kid_rows:  u32[KC, W]      per-key packed row masks (W*32 == cap)
    slot_subj: i32[S]          subject row per (subject, key) slot; padding B
    slot_kid:  i32[S]          dense key id per slot; padding KC
    subj_row:  i32[B]          subject's own arena row (-1 if unregistered),
                               cleared from its slots (a txn never deps on
                               itself)
    act_ts:    i32[cap, 3]     the arena's txn-id lanes; gathered through the
                               compacted rows so RESULTS ARE TXN IDS
    -> (indptr i32[S+1], dep_rows i32[out_cap], dep_ts i32[out_cap, 3],
        bound i32 scalar, csum u32 scalar);
       dep order within a slot is ascending arena row; indptr[-1] > out_cap
       signals overflow. `bound` is the segmented reduction over the slots'
       kid-table row masks -- exactly the host popcount bound
       (sum of key_pop over the dispatch's slot keys) -- read back with the
       result so the NEXT dispatch's out_cap tier needs no host O(keys)
       pass (resolver's OutCapTiers policy). `csum` is the csr_checksum
       integrity word over the triple, verified at harvest.
    """
    b = packed.shape[0]
    kc, w = kid_rows.shape
    blk = jax.lax.dynamic_slice_in_dim(packed, word_off, w, axis=1)
    ok = (slot_subj >= 0) & (slot_subj < b) & (slot_kid >= 0) & (slot_kid < kc)
    kid_m = kid_rows[jnp.clip(slot_kid, 0, kc - 1)]
    bound = jnp.sum(jnp.where(
        ok, jnp.sum(_popcount_u32(kid_m), axis=1, dtype=jnp.int32), 0),
        dtype=jnp.int32)
    so = jnp.clip(slot_subj, 0, b - 1)
    m = jnp.where(ok[:, None], blk[so] & kid_m, jnp.uint32(0))
    r = subj_row[so]
    widx = jnp.arange(w, dtype=jnp.int32)
    selfbit = jnp.where(
        (r >= 0)[:, None] & (widx[None, :] == (r >> 5)[:, None]),
        (jnp.uint32(1) << (r & 31).astype(jnp.uint32))[:, None],
        jnp.uint32(0))
    m = m & ~selfbit
    indptr, dep_rows = _packed_segment_compact(m, out_cap)
    dep_ts = act_ts[dep_rows]
    return (indptr, dep_rows, dep_ts, bound,
            csr_checksum(indptr, dep_rows, dep_ts))


@functools.partial(jax.jit, static_argnames=("out_cap",))
def range_finalize_csr(iv_of, iv_start, iv_end, ent_ok,
                       subj_before, subj_kinds,
                       r_start, r_end, r_ts, r_kinds, r_valid,
                       witness_table, out_cap: int):
    """Device-side finalization of range-arena deps: stab the REAL interval
    endpoint lanes per CSR entry (no covered-bucket hull, no iv_of
    contraction), so each entry gets its own exact hit segment. Entries are
    either a key subject's point interval [k, k+1) (the key-subject
    range-deps lane) or ONE PIECE of a range subject's owned interval set
    (multi-piece subjects contribute one segment lane per piece; the host
    attribution walk unions the per-piece hits, which is idempotent) -- so
    the host re-filter against store.range_txns retires for BOTH subject
    kinds. The witness/before/valid masks gather through iv_of, matching
    range_deps_resolve; `ent_ok` gates which entries finalize (entries of
    the targeted store).

    -> (indptr i32[NV+1], dep_rows i32[out_cap], dep_ts i32[out_cap, 3],
        csum u32 scalar -- the csr_checksum integrity word, verified at
        harvest); dep_ts carries the range arena's txn-id lanes so results
       are txn ids.
    """
    b = subj_before.shape[0]
    o = jnp.clip(iv_of, 0, b - 1)
    inb = (iv_of >= 0) & (iv_of < b) & ent_ok
    hit = (iv_start[:, None] < r_end[None, :]) \
        & (r_start[None, :] < iv_end[:, None])
    witness = witness_table[subj_kinds[o][:, None], r_kinds[None, :]] == 1
    before = _lex_before(r_ts[None, :, :], subj_before[o][:, None, :])
    m = hit & witness & before & r_valid[None, :] & inb[:, None]
    indptr, dep_rows = _segment_compact(m.astype(jnp.int32), out_cap)
    dep_ts = r_ts[dep_rows]
    return (indptr, dep_rows, dep_ts,
            csr_checksum(indptr, dep_rows, dep_ts))


@jax.jit
def arena_scatter(bitmaps, ts, exec_ts, kinds, valid,
                  rows, key_rows, key_mods, ts_rows, exec_rows, kind_rows,
                  valid_rows):
    """Scatter dirty rows into the device arena. Bitmap rows are rebuilt on
    device from a CSR key list (key_rows i32[nnz] holds ABSOLUTE arena row
    indices; padding entries use cap -- out of bounds, dropped): each dirty
    row's bitmap is zeroed, then its current buckets scatter-set, so rows
    whose key sets shrank lose their stale bits. Row-padding duplicates
    row[0] with identical lane data -- harmless double write."""
    cleared = bitmaps.at[rows].set(0.0)
    return (cleared.at[key_rows, key_mods].max(1.0, mode="drop"),
            ts.at[rows].set(ts_rows),
            exec_ts.at[rows].set(exec_rows),
            kinds.at[rows].set(kind_rows),
            valid.at[rows].set(valid_rows))


@jax.jit
def arena_scatter_keys(bitmaps, rows, key_rows, key_mods):
    """Field-granular scatter for KEY-SET-ONLY row changes (key widening,
    prune/truncate shrinks): rebuild the dirty rows' bitmaps from the CSR
    without shipping the ts/exec/kind/valid lanes the change didn't touch.
    Same clear-then-max CSR contract as arena_scatter. (The [kmin, kmax]
    hull lanes this used to refresh are retired -- the range kernel now
    contracts over the same bitmaps.)"""
    cleared = bitmaps.at[rows].set(0.0)
    return cleared.at[key_rows, key_mods].max(1.0, mode="drop")


@jax.jit
def range_scatter(starts, ends, ts, kinds, valid,
                  rows, start_rows, end_rows, ts_rows, kind_rows, valid_rows):
    """Scatter dirty rows into the range arena (tiny flat lanes -- one
    interval per row). Padding duplicates row[0]; harmless double write."""
    return (starts.at[rows].set(start_rows),
            ends.at[rows].set(end_rows),
            ts.at[rows].set(ts_rows),
            kinds.at[rows].set(kind_rows),
            valid.at[rows].set(valid_rows))


@functools.partial(jax.jit, static_argnames=("new_cap",))
def arena_grow(bitmaps, ts, exec_ts, kinds, valid, new_cap: int):
    """Double the arena capacity ON DEVICE (zero/neg padding) -- re-uploading
    a full [cap, K] bitmap over the host link would cost seconds."""
    neg = jnp.int32(np.iinfo(np.int32).min)
    grow = new_cap - bitmaps.shape[0]

    def pad(a, value=0):
        widths = [(0, grow)] + [(0, 0)] * (a.ndim - 1)
        return jnp.pad(a, widths, constant_values=value)

    return (pad(bitmaps), pad(ts), pad(exec_ts, neg), pad(kinds),
            pad(valid, False))


def pad_to(x: np.ndarray, size: int, axis: int = 0) -> np.ndarray:
    """Pad axis up to `size` with zeros (bucketed static shapes for jit)."""
    if x.shape[axis] == size:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, size - x.shape[axis])
    return np.pad(x, pad)


def bucket_size(n: int, minimum: int = 8) -> int:
    """Next power-of-two bucket >= n (>= minimum), so jit caches stay warm."""
    return snap(n, (), minimum)


# The deps-resolver subject-batch padding ladder. Deliberately few named
# tiers so the jit cache stays tiny and warmup() can cover every shape the
# async pipeline dispatches: {8, 64, 128} handle the common batch-window
# coalescing sizes (128 is the default MAX_DISPATCH), and anything larger
# falls onto power-of-two buckets from 256 up (the bench's deep-dispatch
# configurations warm their own tier explicitly).
SUBJECT_TIERS = (8, 64, 128)


def subject_tier(n: int) -> int:
    """Padded subject-batch size for a dispatch of n subjects."""
    return snap(n, SUBJECT_TIERS, 256)


# CSR flat-entry padding ladders. The subject CSR (one entry per owned key /
# owned interval) pads to NNZ_TIERS; the dirty-row scatter CSR packs rows
# greedily under SCATTER_NNZ_TIERS[-1] entries per chunk so both the row tier
# ({8, 64}) and the nnz tier stay warmable. Oversized singles fall onto
# power-of-two buckets.
NNZ_TIERS = (32, 256, 2048)
SCATTER_NNZ_TIERS = (64, 512)


def nnz_tier(n: int) -> int:
    """Padded CSR entry count for a dispatch carrying n subject entries."""
    return snap(n, NNZ_TIERS, 4096)


def scatter_nnz_tier(n: int) -> int:
    """Padded CSR entry count for an arena-scatter chunk of n key entries."""
    return snap(n, SCATTER_NNZ_TIERS, 1024)


# Finalized-CSR output padding ladder. The compaction kernels' out_cap tier
# is PINNED by the resolver's OutCapTiers hysteresis policy (ops.tiers),
# fed by the device-computed bound each finalize call reads back -- grow
# immediately, shrink only after several consecutive quiet dispatches -- so
# the picked tier is not data-dependent dispatch to dispatch and the bench's
# zero-recompile assertion covers the finalize kernels without exemption.
# (With device_out_bound disabled the resolver sizes from the exact host
# popcount bound instead: the differential baseline.)
OUT_TIERS = (256, 2048, 16384)
OUT_TIER_FLOOR = 32768


def out_tier(n: int) -> int:
    """Padded finalized-CSR entry count for a dispatch with n bound hits."""
    return snap(n, OUT_TIERS, OUT_TIER_FLOOR)


def jit_cache_sizes() -> dict:
    """Compiled-variant counts of the warmable hot-path kernels: the bench
    snapshots this around its timed windows to assert warmup() covered every
    jit tier the pipeline dispatches (0 recompiles while timing)."""
    return {
        "deps_resolve": deps_resolve._cache_size(),
        "range_deps_resolve": range_deps_resolve._cache_size(),
        "fused_deps_resolve": fused_deps_resolve._cache_size(),
        "fused_range_deps_resolve": fused_range_deps_resolve._cache_size(),
        "arena_scatter": arena_scatter._cache_size(),
        "arena_scatter_keys": arena_scatter_keys._cache_size(),
        "scatter_rows": scatter_rows._cache_size(),
        "range_scatter": range_scatter._cache_size(),
        "finalize_csr": finalize_csr._cache_size(),
        "range_finalize_csr": range_finalize_csr._cache_size(),
        "kid_word_scatter": kid_word_scatter._cache_size(),
        "fused_execution_frontier": fused_execution_frontier._cache_size(),
    }
