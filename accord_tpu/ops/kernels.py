"""JAX kernels for the deps data plane.

Design notes (TPU-first):
  - The conflict test is a boolean matmul: bitmap[B,K] @ bitmap[A,K]^T on the
    MXU in bfloat16 with float32 accumulation. K (key buckets) is a multiple
    of 128 (lane width); B and A are padded to multiples of 8 (sublanes).
  - Kind filtering is a gather from the 6x6 witness table; timestamp
    comparison is lexicographic over two int32 lanes -- both VPU element-wise
    ops XLA fuses into the matmul epilogue.
  - Transitive closure is iterated boolean matmul (repeated squaring), the
    standard reachability-by-matmul formulation; log2(N) MXU rounds.
All functions are jit-compiled with static shapes; callers pad to bucket
sizes (see resolver.py) so compilation caches are hit.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def _lex_before(a, b):
    """a < b lexicographically over 3 int32 lanes; a: [..., 3], b: [..., 3]
    (broadcasting)."""
    return ((a[..., 0] < b[..., 0])
            | ((a[..., 0] == b[..., 0])
               & ((a[..., 1] < b[..., 1])
                  | ((a[..., 1] == b[..., 1]) & (a[..., 2] < b[..., 2])))))


@functools.partial(jax.jit, static_argnames=())
def deps_matrix(subj_bitmaps, subj_before, subj_kinds,
                act_bitmaps, act_ts, act_kinds, act_valid,
                witness_table):
    """Pairwise dependency matrix.

    subj_bitmaps: f32[B, K]   keys touched by each subject txn
    subj_before:  i32[B, 3]   'started before' bound per subject (usually the
                              witnessed executeAt; reference semantics of
                              mapReduceActive STARTED_BEFORE)
    subj_kinds:   i32[B]
    act_bitmaps:  f32[A, K]   active-set key bitmaps
    act_ts:       i32[A, 3]   active txn ids (3-lane window-relative encoding)
    act_kinds:    i32[A]
    act_valid:    bool[A]     false for padding / invalidated entries
    witness_table: i32[6, 6]

    -> bool[B, A] : dep[b, a] == True iff active txn a is a dependency of
                    subject b (keys overlap AND subject witnesses a's kind AND
                    a started before b's bound AND a != b).
    """
    overlap = jax.lax.dot_general(
        subj_bitmaps.astype(jnp.bfloat16), act_bitmaps.astype(jnp.bfloat16),
        (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32) > 0.5
    witness = witness_table[subj_kinds[:, None], act_kinds[None, :]] == 1
    before = _lex_before(act_ts[None, :, :], subj_before[:, None, :])
    return overlap & witness & before & act_valid[None, :]


@functools.partial(jax.jit, static_argnames=())
def max_conflict(subj_bitmaps, act_bitmaps, act_exec_ts, act_valid):
    """Max witnessed-conflict timestamp per subject (feeds the fast-path test
    txnId >= maxConflicts; reference: MaxConflicts + CommandStore.preaccept).
    Kind-agnostic, like the reference's MaxConflicts: ANY registered txn on a
    shared key raises the timestamp floor.

    act_exec_ts: i32[A, 3] -- max(executeAt, txnId) per active txn.
    -> (i32[B, 3] lexicographic max (INT32_MIN lanes where no conflict),
        i32[B] winning row (-1 where none)).
    """
    overlap = jax.lax.dot_general(
        subj_bitmaps.astype(jnp.bfloat16), act_bitmaps.astype(jnp.bfloat16),
        (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32) > 0.5
    mask = overlap & act_valid[None, :]
    neg = jnp.int32(np.iinfo(np.int32).min)
    # lexicographic max without int64: successive tie-narrowing per lane
    l0 = jnp.where(mask, act_exec_ts[None, :, 0], neg)
    m0 = jnp.max(l0, axis=1)
    tie0 = mask & (act_exec_ts[None, :, 0] == m0[:, None])
    l1 = jnp.where(tie0, act_exec_ts[None, :, 1], neg)
    m1 = jnp.max(l1, axis=1)
    tie1 = tie0 & (act_exec_ts[None, :, 1] == m1[:, None])
    l2 = jnp.where(tie1, act_exec_ts[None, :, 2], neg)
    m2 = jnp.max(l2, axis=1)
    tie2 = tie1 & (act_exec_ts[None, :, 2] == m2[:, None])
    # winning row per subject (first among ties); -1 when no conflict
    row = jnp.where(jnp.any(tie2, axis=1),
                    jnp.argmax(tie2, axis=1).astype(jnp.int32), -1)
    return jnp.stack([m0, m1, m2], axis=1), row


@functools.partial(jax.jit, static_argnames=("iterations",))
def transitive_closure(adj, iterations: int):
    """Reachability closure of a boolean adjacency matrix by repeated
    squaring: R_{i+1} = R_i | (R_i @ R_i). `iterations` >= ceil(log2(N)).
    (the execute-order closure kernel; BASELINE config 'Synthetic Execute
    DAG')."""

    def body(_, r):
        rf = r.astype(jnp.bfloat16)
        sq = jax.lax.dot_general(rf, rf, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32) > 0.5
        return r | sq

    return jax.lax.fori_loop(0, iterations, body, adj)


@functools.partial(jax.jit, static_argnames=("max_levels",))
def execution_wavefronts(adj, max_levels: int):
    """Topological execution levels of a dependency DAG: level[i] = longest
    dependency chain ending at i (the order the execution engine may release
    txns in parallel waves). adj[i, j] == True iff i depends on j.
    -> i32[N] levels (max_levels if a cycle prevents settling)."""
    n = adj.shape[0]

    def body(_, level):
        # level'_i = 1 + max_j adj[i,j] * level_j   (0 if no deps)
        dep_levels = jnp.where(adj, level[None, :] + 1, 0)
        return jnp.maximum(level, jnp.max(dep_levels, axis=1))

    return jax.lax.fori_loop(0, max_levels, body, jnp.zeros(n, jnp.int32))


def _lex_le(a, b):
    """a <= b lexicographically over 3 int32 lanes (broadcasting)."""
    return ~_lex_before(b, a)


@jax.jit
def execution_frontier(adj, exec_ts, applied, pending, awaits_all):
    """The device execution scheduler's release test (reference: the host
    WaitingOn bitsets + Commands.maybeExecute walk, local/Command.java:1224,
    local/Commands.java:960 -- recomputed in batch on device instead of
    per-edge on the host).

    adj:        bool[cap, cap] dep adjacency; adj[w, d] iff w holds a wait
                edge on arena row d. Kept UNPACKED on device: exec_scatter
                unpacks uploaded rows once, so the per-tick frontier never
                re-expands the whole matrix.
    exec_ts:    i32[cap, 3]  executeAt lanes (INT32_MIN while undecided --
                an undecided dep always gates, the commit-wait)
    applied:    bool[cap]    dep applied (or terminal: no longer gates)
    pending:    bool[cap]    row is STABLE/PRE_APPLIED awaiting release
    awaits_all: bool[cap]    row's kind waits for EVERY dep to apply
                (ExclusiveSyncPoint / EphemeralRead), regardless of
                executeAt order

    -> u32[cap/32] packed release frontier: pending rows whose gates are all
    clear (dep applied, or dep decided to execute after us and we are not an
    awaits-all kind).
    """
    cap = adj.shape[0]
    bits = jnp.arange(32, dtype=jnp.uint32)
    dep_le = _lex_le(exec_ts[None, :, :], exec_ts[:, None, :])  # dep <= waiter
    gates = adj & (~applied)[None, :] & (dep_le | awaits_all[:, None])
    ready = pending & ~jnp.any(gates, axis=1)
    weights = jnp.uint32(1) << bits
    return jnp.sum(ready.reshape(cap // 32, 32).astype(jnp.uint32)
                   * weights[None, :], axis=-1, dtype=jnp.uint32)


@functools.partial(jax.jit, static_argnames=("max_levels",))
def dag_wavefronts_packed(adj_packed, max_levels: int):
    """Topological release levels of a dependency DAG at scale (the BASELINE
    'Synthetic Execute DAG' config: 100k nodes). Works entirely on packed
    words -- never materializes the N x N boolean matrix -- so memory is
    N^2/8 bytes and each round is N x N/32 u32 lanes on the VPU.

    adj_packed: u32[N, N/32]; bit d of row w set iff w depends on d.
    -> i32[N] level per node (-1 if not settled within max_levels).
    """
    n, words = adj_packed.shape
    bits = jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32)

    def body(i, state):
        applied_packed, level = state
        blocked = jnp.any(adj_packed & (~applied_packed)[None, :] != 0, axis=1)
        ready = ~blocked & (level < 0)
        level = jnp.where(ready, i, level)
        rp = jnp.sum(ready.reshape(words, 32).astype(jnp.uint32)
                     * bits[None, :], axis=-1, dtype=jnp.uint32)
        return applied_packed | rp, level

    state = (jnp.zeros(words, jnp.uint32), jnp.full(n, -1, jnp.int32))
    _, level = jax.lax.fori_loop(0, max_levels, body, state)
    return level


@jax.jit
def exec_scatter(adj, exec_ts, applied, pending, awaits_all,
                 rows, adj_rows_packed, ts_rows, applied_rows, pending_rows,
                 awaits_rows):
    """Scatter dirty rows into the execution arena. Adjacency rows arrive
    PACKED from the host (cap/8 bytes per row over the slow link) and are
    unpacked on device into the resident bool matrix."""
    cap = adj.shape[0]
    bits = jnp.arange(32, dtype=jnp.uint32)
    unpacked = (((adj_rows_packed[:, :, None] >> bits[None, None, :]) & 1) > 0) \
        .reshape(adj_rows_packed.shape[0], cap)
    return (adj.at[rows].set(unpacked),
            exec_ts.at[rows].set(ts_rows),
            applied.at[rows].set(applied_rows),
            pending.at[rows].set(pending_rows),
            awaits_all.at[rows].set(awaits_rows))


@jax.jit
def scatter_rows(dst, idx, rows):
    """dst[cap, ...] with dst[idx[i]] = rows[i] -- the incremental device
    active-set update (dirty rows only; jit caches per (cap, len(idx)) shape
    bucket)."""
    return dst.at[idx].set(rows)


@jax.jit
def deps_resolve(subj_keys, subj_before, subj_kinds,
                 act_bitmaps, act_ts, act_kinds, act_valid,
                 witness_table):
    """The fused hot-path kernel: subject bitmaps built ON DEVICE from key
    indices (uploading B x MAXK int32 instead of B x K float bitmaps -- the
    host->device link is the bottleneck, see resolver.py), then the pairwise
    conflict matrix, BIT-PACKED on device for the readback: 32 arena rows per
    uint32 lane, so the transfer is B x cap/8 bytes regardless of how many
    dependencies each subject has (a top-k index list was tried first: its
    coverage/latency trade collapses under contention where counts reach
    hundreds).

    subj_keys:   i32[B, MAXK]  key bucket indices (already % K; -1 padding)
    subj_before: i32[B, 3]     'started before' bound (3-lane encoding)
    subj_kinds:  i32[B]
    act_*:       the device arena (see resolver._NodeArena); cap % 32 == 0
    -> u32[B, cap/32] packed dependency bitmask, little-bit-first per lane
    """
    onehot = (subj_keys[:, :, None]
              == jnp.arange(act_bitmaps.shape[1], dtype=jnp.int32)[None, None, :]) \
        & (subj_keys >= 0)[:, :, None]
    subj_bm = onehot.any(axis=1).astype(jnp.bfloat16)
    overlap = jax.lax.dot_general(
        subj_bm, act_bitmaps.astype(jnp.bfloat16),
        (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32) > 0.5
    witness = witness_table[subj_kinds[:, None], act_kinds[None, :]] == 1
    before = _lex_before(act_ts[None, :, :], subj_before[:, None, :])
    m = overlap & witness & before & act_valid[None, :]
    b, a = m.shape
    weights = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))
    return jnp.sum(m.reshape(b, a // 32, 32).astype(jnp.uint32)
                   * weights[None, None, :], axis=-1, dtype=jnp.uint32)


@jax.jit
def arena_scatter(bitmaps, ts, exec_ts, kinds, valid,
                  rows, keys_mod, ts_rows, exec_rows, kind_rows, valid_rows):
    """Scatter dirty rows into the device arena. Bitmap rows are rebuilt on
    device from key indices (i32[n, MAXK], -1 padded) so the upload is tiny.
    Padding duplicates row[0] with identical data -- harmless double write."""
    onehot = (keys_mod[:, :, None]
              == jnp.arange(bitmaps.shape[1], dtype=jnp.int32)[None, None, :]) \
        & (keys_mod >= 0)[:, :, None]
    bm_rows = onehot.any(axis=1).astype(bitmaps.dtype)
    return (bitmaps.at[rows].set(bm_rows),
            ts.at[rows].set(ts_rows),
            exec_ts.at[rows].set(exec_rows),
            kinds.at[rows].set(kind_rows),
            valid.at[rows].set(valid_rows))


@functools.partial(jax.jit, static_argnames=("new_cap",))
def arena_grow(bitmaps, ts, exec_ts, kinds, valid, new_cap: int):
    """Double the arena capacity ON DEVICE (zero/neg padding) -- re-uploading
    a full [cap, K] bitmap over the host link would cost seconds."""
    neg = jnp.int32(np.iinfo(np.int32).min)
    grow = new_cap - bitmaps.shape[0]

    def pad(a, value=0):
        widths = [(0, grow)] + [(0, 0)] * (a.ndim - 1)
        return jnp.pad(a, widths, constant_values=value)

    return (pad(bitmaps), pad(ts), pad(exec_ts, neg), pad(kinds),
            pad(valid, False))


def pad_to(x: np.ndarray, size: int, axis: int = 0) -> np.ndarray:
    """Pad axis up to `size` with zeros (bucketed static shapes for jit)."""
    if x.shape[axis] == size:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, size - x.shape[axis])
    return np.pad(x, pad)


def bucket_size(n: int, minimum: int = 8) -> int:
    """Next power-of-two bucket >= n (>= minimum), so jit caches stay warm."""
    size = minimum
    while size < n:
        size *= 2
    return size


# The deps-resolver subject-batch padding ladder. Deliberately few named
# tiers so the jit cache stays tiny and warmup() can cover every shape the
# async pipeline dispatches: {8, 64, 128} handle the common batch-window
# coalescing sizes (128 is the default MAX_DISPATCH), and anything larger
# falls onto power-of-two buckets from 256 up (the bench's deep-dispatch
# configurations warm their own tier explicitly).
SUBJECT_TIERS = (8, 64, 128)


def subject_tier(n: int) -> int:
    """Padded subject-batch size for a dispatch of n subject chunks."""
    for tier in SUBJECT_TIERS:
        if n <= tier:
            return tier
    return bucket_size(n, 256)
