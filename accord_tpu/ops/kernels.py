"""JAX kernels for the deps data plane.

Design notes (TPU-first):
  - The conflict test is a boolean matmul: bitmap[B,K] @ bitmap[A,K]^T on the
    MXU in bfloat16 with float32 accumulation. K (key buckets) is a multiple
    of 128 (lane width); B and A are padded to multiples of 8 (sublanes).
  - Kind filtering is a gather from the 6x6 witness table; timestamp
    comparison is lexicographic over two int32 lanes -- both VPU element-wise
    ops XLA fuses into the matmul epilogue.
  - Transitive closure is iterated boolean matmul (repeated squaring), the
    standard reachability-by-matmul formulation; log2(N) MXU rounds.
All functions are jit-compiled with static shapes; callers pad to bucket
sizes (see resolver.py) so compilation caches are hit.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from accord_tpu.ops.tiers import snap


def _lex_before(a, b):
    """a < b lexicographically over 3 int32 lanes; a: [..., 3], b: [..., 3]
    (broadcasting)."""
    return ((a[..., 0] < b[..., 0])
            | ((a[..., 0] == b[..., 0])
               & ((a[..., 1] < b[..., 1])
                  | ((a[..., 1] == b[..., 1]) & (a[..., 2] < b[..., 2])))))


@functools.partial(jax.jit, static_argnames=())
def deps_matrix(subj_bitmaps, subj_before, subj_kinds,
                act_bitmaps, act_ts, act_kinds, act_valid,
                witness_table):
    """Pairwise dependency matrix.

    subj_bitmaps: f32[B, K]   keys touched by each subject txn
    subj_before:  i32[B, 3]   'started before' bound per subject (usually the
                              witnessed executeAt; reference semantics of
                              mapReduceActive STARTED_BEFORE)
    subj_kinds:   i32[B]
    act_bitmaps:  f32[A, K]   active-set key bitmaps
    act_ts:       i32[A, 3]   active txn ids (3-lane window-relative encoding)
    act_kinds:    i32[A]
    act_valid:    bool[A]     false for padding / invalidated entries
    witness_table: i32[6, 6]

    -> bool[B, A] : dep[b, a] == True iff active txn a is a dependency of
                    subject b (keys overlap AND subject witnesses a's kind AND
                    a started before b's bound AND a != b).
    """
    overlap = jax.lax.dot_general(
        subj_bitmaps.astype(jnp.bfloat16), act_bitmaps.astype(jnp.bfloat16),
        (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32) > 0.5
    witness = witness_table[subj_kinds[:, None], act_kinds[None, :]] == 1
    before = _lex_before(act_ts[None, :, :], subj_before[:, None, :])
    return overlap & witness & before & act_valid[None, :]


@functools.partial(jax.jit, static_argnames=())
def max_conflict(subj_bitmaps, act_bitmaps, act_exec_ts, act_valid):
    """Max witnessed-conflict timestamp per subject (feeds the fast-path test
    txnId >= maxConflicts; reference: MaxConflicts + CommandStore.preaccept).
    Kind-agnostic, like the reference's MaxConflicts: ANY registered txn on a
    shared key raises the timestamp floor.

    act_exec_ts: i32[A, 3] -- max(executeAt, txnId) per active txn.
    -> (i32[B, 3] lexicographic max (INT32_MIN lanes where no conflict),
        i32[B] winning row (-1 where none)).
    """
    overlap = jax.lax.dot_general(
        subj_bitmaps.astype(jnp.bfloat16), act_bitmaps.astype(jnp.bfloat16),
        (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32) > 0.5
    mask = overlap & act_valid[None, :]
    neg = jnp.int32(np.iinfo(np.int32).min)
    # lexicographic max without int64: successive tie-narrowing per lane
    l0 = jnp.where(mask, act_exec_ts[None, :, 0], neg)
    m0 = jnp.max(l0, axis=1)
    tie0 = mask & (act_exec_ts[None, :, 0] == m0[:, None])
    l1 = jnp.where(tie0, act_exec_ts[None, :, 1], neg)
    m1 = jnp.max(l1, axis=1)
    tie1 = tie0 & (act_exec_ts[None, :, 1] == m1[:, None])
    l2 = jnp.where(tie1, act_exec_ts[None, :, 2], neg)
    m2 = jnp.max(l2, axis=1)
    tie2 = tie1 & (act_exec_ts[None, :, 2] == m2[:, None])
    # winning row per subject (first among ties); -1 when no conflict
    row = jnp.where(jnp.any(tie2, axis=1),
                    jnp.argmax(tie2, axis=1).astype(jnp.int32), -1)
    return jnp.stack([m0, m1, m2], axis=1), row


@functools.partial(jax.jit, static_argnames=("iterations",))
def transitive_closure(adj, iterations: int):
    """Reachability closure of a boolean adjacency matrix by repeated
    squaring: R_{i+1} = R_i | (R_i @ R_i). `iterations` >= ceil(log2(N)).
    (the execute-order closure kernel; BASELINE config 'Synthetic Execute
    DAG')."""

    def body(_, r):
        rf = r.astype(jnp.bfloat16)
        sq = jax.lax.dot_general(rf, rf, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32) > 0.5
        return r | sq

    return jax.lax.fori_loop(0, iterations, body, adj)


@functools.partial(jax.jit, static_argnames=("max_levels",))
def execution_wavefronts(adj, max_levels: int):
    """Topological execution levels of a dependency DAG: level[i] = longest
    dependency chain ending at i (the order the execution engine may release
    txns in parallel waves). adj[i, j] == True iff i depends on j.
    -> i32[N] levels (max_levels if a cycle prevents settling)."""
    n = adj.shape[0]

    def body(_, level):
        # level'_i = 1 + max_j adj[i,j] * level_j   (0 if no deps)
        dep_levels = jnp.where(adj, level[None, :] + 1, 0)
        return jnp.maximum(level, jnp.max(dep_levels, axis=1))

    return jax.lax.fori_loop(0, max_levels, body, jnp.zeros(n, jnp.int32))


def _lex_le(a, b):
    """a <= b lexicographically over 3 int32 lanes (broadcasting)."""
    return ~_lex_before(b, a)


def _frontier_ready(adj, exec_ts, applied, pending, awaits_all):
    """The release test shared by the per-store and fused frontier kernels:
    pending rows whose gates are all clear (dep applied, or dep decided to
    execute after us and we are not an awaits-all kind)."""
    dep_le = _lex_le(exec_ts[None, :, :], exec_ts[:, None, :])  # dep <= waiter
    gates = adj & (~applied)[None, :] & (dep_le | awaits_all[:, None])
    return pending & ~jnp.any(gates, axis=1)


@jax.jit
def execution_frontier(adj, exec_ts, applied, pending, awaits_all):
    """The device execution scheduler's release test (reference: the host
    WaitingOn bitsets + Commands.maybeExecute walk, local/Command.java:1224,
    local/Commands.java:960 -- recomputed in batch on device instead of
    per-edge on the host).

    adj:        bool[cap, cap] dep adjacency; adj[w, d] iff w holds a wait
                edge on arena row d. Kept UNPACKED on device: exec_scatter
                unpacks uploaded rows once, so the per-tick frontier never
                re-expands the whole matrix.
    exec_ts:    i32[cap, 3]  executeAt lanes (INT32_MIN while undecided --
                an undecided dep always gates, the commit-wait)
    applied:    bool[cap]    dep applied (or terminal: no longer gates)
    pending:    bool[cap]    row is STABLE/PRE_APPLIED awaiting release
    awaits_all: bool[cap]    row's kind waits for EVERY dep to apply
                (ExclusiveSyncPoint / EphemeralRead), regardless of
                executeAt order

    -> u32[cap/32] packed release frontier: pending rows whose gates are all
    clear (dep applied, or dep decided to execute after us and we are not an
    awaits-all kind).
    """
    cap = adj.shape[0]
    bits = jnp.arange(32, dtype=jnp.uint32)
    ready = _frontier_ready(adj, exec_ts, applied, pending, awaits_all)
    weights = jnp.uint32(1) << bits
    return jnp.sum(ready.reshape(cap // 32, 32).astype(jnp.uint32)
                   * weights[None, :], axis=-1, dtype=jnp.uint32)


@jax.jit
def fused_execution_frontier(planes):
    """Cross-store fused twin of execution_frontier: one device call answers
    every store's release frontier for a node tick. `planes` is a TUPLE of
    per-store lane tuples (adj, exec_ts, applied, pending, awaits_all) -- jit
    specializes on the tuple structure, so the participating-store count and
    each store's cap are warmable tiers exactly like the resolver's fused
    dispatch. Per-store packed frontiers concatenate along the word axis; the
    host slices them back out with per-store word spans.

    -> u32[sum(cap_s)/32] packed release frontier, store blocks in tuple order
    """
    outs = []
    bits = jnp.arange(32, dtype=jnp.uint32)
    weights = jnp.uint32(1) << bits
    for (adj, exec_ts, applied, pending, awaits_all) in planes:
        cap = adj.shape[0]
        ready = _frontier_ready(adj, exec_ts, applied, pending, awaits_all)
        outs.append(jnp.sum(ready.reshape(cap // 32, 32).astype(jnp.uint32)
                            * weights[None, :], axis=-1, dtype=jnp.uint32))
    return jnp.concatenate(outs)


@functools.partial(jax.jit, static_argnames=("max_levels",))
def dag_wavefronts_packed(adj_packed, max_levels: int):
    """Topological release levels of a dependency DAG at scale (the BASELINE
    'Synthetic Execute DAG' config: 100k nodes). Works entirely on packed
    words -- never materializes the N x N boolean matrix -- so memory is
    N^2/8 bytes and each round is N x N/32 u32 lanes on the VPU.

    adj_packed: u32[N, N/32]; bit d of row w set iff w depends on d.
    -> i32[N] level per node (-1 if not settled within max_levels).
    """
    n, words = adj_packed.shape
    bits = jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32)

    def body(i, state):
        applied_packed, level = state
        blocked = jnp.any(adj_packed & (~applied_packed)[None, :] != 0, axis=1)
        ready = ~blocked & (level < 0)
        level = jnp.where(ready, i, level)
        rp = jnp.sum(ready.reshape(words, 32).astype(jnp.uint32)
                     * bits[None, :], axis=-1, dtype=jnp.uint32)
        return applied_packed | rp, level

    state = (jnp.zeros(words, jnp.uint32), jnp.full(n, -1, jnp.int32))
    _, level = jax.lax.fori_loop(0, max_levels, body, state)
    return level


@jax.jit
def exec_scatter(adj, exec_ts, applied, pending, awaits_all,
                 rows, adj_rows_packed, ts_rows, applied_rows, pending_rows,
                 awaits_rows):
    """Scatter dirty rows into the execution arena. Adjacency rows arrive
    PACKED from the host (cap/8 bytes per row over the slow link) and are
    unpacked on device into the resident bool matrix."""
    cap = adj.shape[0]
    bits = jnp.arange(32, dtype=jnp.uint32)
    unpacked = (((adj_rows_packed[:, :, None] >> bits[None, None, :]) & 1) > 0) \
        .reshape(adj_rows_packed.shape[0], cap)
    return (adj.at[rows].set(unpacked),
            exec_ts.at[rows].set(ts_rows),
            applied.at[rows].set(applied_rows),
            pending.at[rows].set(pending_rows),
            awaits_all.at[rows].set(awaits_rows))


@jax.jit
def scatter_rows(dst, idx, rows):
    """dst[cap, ...] with dst[idx[i]] = rows[i] -- the incremental device
    active-set update (dirty rows only; jit caches per (cap, len(idx)) shape
    bucket)."""
    return dst.at[idx].set(rows)


@jax.jit
def kid_word_scatter(kid_rows, kid_idx, word_idx, words):
    """Incremental update of the per-key packed row-mask mirror
    (finalize_csr's kid_rows lane): write whole u32 WORDS at (kid, word)
    coordinates. The host dedupes coordinates and sources each word's full
    current value, so duplicate-index write hazards never arise; padding
    entries use kid_idx == KC (out of bounds, dropped)."""
    return kid_rows.at[kid_idx, word_idx].set(words, mode="drop")


def _pack_bits(m):
    """bool[B, A] -> u32[B, A/32] little-bit-first per lane (A % 32 == 0)."""
    b, a = m.shape
    weights = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))
    return jnp.sum(m.reshape(b, a // 32, 32).astype(jnp.uint32)
                   * weights[None, None, :], axis=-1, dtype=jnp.uint32)


@jax.jit
def deps_resolve(subj_of, subj_keys, subj_before, subj_kinds,
                 act_bitmaps, act_ts, act_kinds, act_valid,
                 witness_table):
    """The fused hot-path kernel: subject bitmaps built ON DEVICE from a
    variable-width CSR key list (uploading 2 x nnz int32 instead of B x K
    float bitmaps -- the host->device link is the bottleneck, see
    resolver.py). The CSR replaces the old fixed i32[B, MAXK] scatter:
    arbitrarily wide subjects stay on the device path instead of demoting to
    a host residual scan. The pairwise conflict matrix is BIT-PACKED on
    device for the readback: 32 arena rows per uint32 lane, so the transfer
    is B x cap/8 bytes regardless of how many dependencies each subject has
    (a top-k index list was tried first: its coverage/latency trade collapses
    under contention where counts reach hundreds).

    subj_of:     i32[nnz]      subject row per CSR entry (padding entries use
                               B -- out of bounds, dropped by the scatter)
    subj_keys:   i32[nnz]      key bucket indices (already % K)
    subj_before: i32[B, 3]     'started before' bound per subject (3-lane
                               encoding)
    subj_kinds:  i32[B]
    act_*:       the device arena (see resolver._StoreArena); cap % 32 == 0
    -> u32[B, cap/32] packed dependency bitmask, little-bit-first per lane
    """
    b = subj_before.shape[0]
    k = act_bitmaps.shape[1]
    subj_bm = jnp.zeros((b, k), jnp.float32) \
        .at[subj_of, subj_keys].max(1.0, mode="drop").astype(jnp.bfloat16)
    overlap = jax.lax.dot_general(
        subj_bm, act_bitmaps.astype(jnp.bfloat16),
        (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32) > 0.5
    witness = witness_table[subj_kinds[:, None], act_kinds[None, :]] == 1
    before = _lex_before(act_ts[None, :, :], subj_before[:, None, :])
    m = overlap & witness & before & act_valid[None, :]
    return _pack_bits(m)


@jax.jit
def fused_deps_resolve(subj_of, subj_keys, subj_store, subj_before,
                       subj_kinds, slots, arenas, witness_table):
    """Cross-store fused twin of deps_resolve: one device call answers every
    store's slice of a node tick. `arenas` is a TUPLE of per-store lane
    tuples (bitmaps, ts, kinds, valid) -- jit specializes on the tuple
    structure, so the participating-store count is a warmable tier exactly
    like the batch size. The subject bitmap is built ONCE from the CSR; each
    store's block masks by the store-id lane (subj_store == slots[s]) so a
    subject only sees its own store's rows, and the per-store packed blocks
    concatenate into one u32[B, sum(cap_s)/32] readback whose word offsets
    are the host-side row-offset table.

    subj_store: i32[B]   group slot per subject (padding rows use a slot no
                         entry of `slots` matches)
    slots:      i32[S]   the group slot each arena block answers (traced, so
                         slot assignment never recompiles)
    arenas:     tuple of S (bitmaps f32[cap_s, K], ts i32[cap_s, 3],
                kinds i32[cap_s], valid bool[cap_s])
    -> u32[B, sum(cap_s)/32] packed dependency bitmask, store blocks in
       `arenas` order
    """
    b = subj_before.shape[0]
    k = arenas[0][0].shape[1]
    subj_bm = jnp.zeros((b, k), jnp.float32) \
        .at[subj_of, subj_keys].max(1.0, mode="drop").astype(jnp.bfloat16)
    outs = []
    for s, (act_bm, act_ts, act_kinds, act_valid) in enumerate(arenas):
        overlap = jax.lax.dot_general(
            subj_bm, act_bm.astype(jnp.bfloat16),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) > 0.5
        witness = witness_table[subj_kinds[:, None], act_kinds[None, :]] == 1
        before = _lex_before(act_ts[None, :, :], subj_before[:, None, :])
        mine = (subj_store == slots[s])[:, None]
        outs.append(_pack_bits(
            overlap & witness & before & act_valid[None, :] & mine))
    return jnp.concatenate(outs, axis=1)


def covered_buckets(iv_of, iv_start, iv_end, b, k_local, base, k_total):
    """Per-subject covered-bucket mask from a CSR interval list under the
    modular bucket hash `key % k_total`: bucket `base + j` is covered by the
    half-open interval [s, e) iff some integer in [s, e) lands in it, i.e.
    `(base + j - s) mod k_total < e - s`. Intervals spanning >= k_total keys
    (and degenerate/padding widths <= 0) cover every bucket. Exact under
    int32 wraparound ONLY when k_total divides 2^32 -- callers assert
    power-of-two bucket counts. `base` may be traced (shard_map axis offset);
    single-device callers pass 0 with k_local == k_total.

    -> bf16[b, k_local] covered-bucket matrix (padding iv_of == b dropped)
    """
    j = base + jnp.arange(k_local, dtype=jnp.int32)
    width = iv_end - iv_start
    wide = (width <= 0) | (width >= k_total)
    covered = wide[:, None] | (
        jnp.mod(j[None, :] - iv_start[:, None], k_total) < width[:, None])
    return jnp.zeros((b, k_local), jnp.float32) \
        .at[iv_of].max(covered.astype(jnp.float32), mode="drop") \
        .astype(jnp.bfloat16)


@jax.jit
def fused_range_deps_resolve(iv_of, iv_start, iv_end, subj_store,
                             subj_before, subj_kinds, subj_is_range,
                             r_slots, rarenas, k_slots, karenas,
                             witness_table):
    """Cross-store fused twin of range_deps_resolve. `rarenas` holds the
    participating stores' RANGE-arena lanes (starts, ends, ts, kinds, valid),
    `karenas` the stores' key-arena lanes (bitmaps, ts, kinds, valid) tested
    by covered-bucket contraction (see range_deps_resolve); either tuple may
    be empty (that side returns a zero-width buffer). Store routing works
    like fused_deps_resolve: each block masks by its slot in the subj_store
    lane, and blocks concatenate along the packed word axis in tuple order.

    -> (u32[B, sum(rcap_s)/32], u32[B, sum(cap_s)/32])
    """
    b = subj_before.shape[0]
    routs = []
    for s, (r_start, r_end, r_ts, r_kinds, r_valid) in enumerate(rarenas):
        rcap = r_start.shape[0]
        hit_r = (iv_start[:, None] < r_end[None, :]) \
            & (r_start[None, :] < iv_end[:, None])
        any_r = jnp.zeros((b, rcap), jnp.int32) \
            .at[iv_of].max(hit_r.astype(jnp.int32), mode="drop") > 0
        witness_r = witness_table[subj_kinds[:, None], r_kinds[None, :]] == 1
        before_r = _lex_before(r_ts[None, :, :], subj_before[:, None, :])
        mine = (subj_store == r_slots[s])[:, None]
        routs.append(_pack_bits(
            any_r & witness_r & before_r & r_valid[None, :] & mine))
    kouts = []
    if karenas:
        k = karenas[0][0].shape[1]
        cov = covered_buckets(iv_of, iv_start, iv_end, b, k, 0, k)
    for s, (k_bm, k_ts, k_kinds, k_valid) in enumerate(karenas):
        any_k = jax.lax.dot_general(
            cov, k_bm.astype(jnp.bfloat16),
            (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32) > 0.5
        witness_k = witness_table[subj_kinds[:, None], k_kinds[None, :]] == 1
        before_k = _lex_before(k_ts[None, :, :], subj_before[:, None, :])
        mine = (subj_store == k_slots[s])[:, None] & subj_is_range[:, None]
        kouts.append(_pack_bits(
            any_k & witness_k & before_k & k_valid[None, :] & mine))
    rpacked = jnp.concatenate(routs, axis=1) if routs \
        else jnp.zeros((b, 0), jnp.uint32)
    kpacked = jnp.concatenate(kouts, axis=1) if kouts \
        else jnp.zeros((b, 0), jnp.uint32)
    return rpacked, kpacked


@jax.jit
def range_deps_resolve(iv_of, iv_start, iv_end, subj_before, subj_kinds,
                       subj_is_range,
                       r_start, r_end, r_ts, r_kinds, r_valid,
                       k_bm, k_ts, k_kinds, k_valid,
                       witness_table):
    """The fused RANGE-overlap kernel: every subject carries a CSR list of
    half-open int32 intervals (a key subject's keys become point intervals
    [k, k+1); a range subject's owned ranges upload as-is), tested against

      - the RANGE arena by branch-free interval overlap
        (iv_start < r_end & r_start < iv_end), which for a point interval
        degenerates to the stabbing test r_start <= key < r_end; and
      - the KEY arena by covered-bucket contraction: the subject's intervals
        expand to a covered-bucket mask (covered_buckets) contracted against
        the per-row key bitmaps on the MXU -- range subjects only (key
        subjects get exact key deps from deps_resolve); the host decode
        filters bucket-collision false positives per real key. This replaces
        the old per-row [kmin, kmax] hull span compare: a sparse row with a
        wide key spread no longer candidates every interval inside its hull,
        only intervals actually sharing a bucket.

    Sorted-endpoint broadcast compares beat an interval tree here: the tree's
    pointer-chasing descent is serial and branchy, while [nv, rcap] compares
    are pure VPU work XLA fuses with the witness/before masks.

    iv_of:         i32[nv]   subject row per interval (padding -> B, dropped)
    iv_start/end:  i32[nv]   half-open interval endpoints
    subj_before:   i32[B, 3] 'started before' bound per subject
    subj_kinds:    i32[B]
    subj_is_range: bool[B]   True for range-domain subjects (gates the
                             key-arena output)
    r_*:           the range arena (resolver._RangeArena); rcap % 32 == 0
    k_*:           the key arena lanes (k_bm f32[cap, K]); cap % 32 == 0,
                   K a power of two (covered_buckets wraparound)
    -> (u32[B, rcap/32], u32[B, cap/32]) packed candidate bitmasks, masked by
       witness/before/valid exactly like deps_resolve
    """
    b = subj_before.shape[0]
    rcap = r_start.shape[0]
    k = k_bm.shape[1]
    hit_r = (iv_start[:, None] < r_end[None, :]) \
        & (r_start[None, :] < iv_end[:, None])
    any_r = jnp.zeros((b, rcap), jnp.int32) \
        .at[iv_of].max(hit_r.astype(jnp.int32), mode="drop") > 0
    witness_r = witness_table[subj_kinds[:, None], r_kinds[None, :]] == 1
    before_r = _lex_before(r_ts[None, :, :], subj_before[:, None, :])
    m_r = any_r & witness_r & before_r & r_valid[None, :]
    cov = covered_buckets(iv_of, iv_start, iv_end, b, k, 0, k)
    any_k = jax.lax.dot_general(
        cov, k_bm.astype(jnp.bfloat16),
        (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32) > 0.5
    witness_k = witness_table[subj_kinds[:, None], k_kinds[None, :]] == 1
    before_k = _lex_before(k_ts[None, :, :], subj_before[:, None, :])
    m_k = any_k & witness_k & before_k & k_valid[None, :] \
        & subj_is_range[:, None]
    return _pack_bits(m_r), _pack_bits(m_k)


def _segment_compact(hits, out_cap: int):
    """Segment compaction: per-segment popcount -> exclusive prefix sum ->
    masked scatter. `hits` is i32[S, N] (0/1); returns (indptr i32[S+1],
    dep_rows i32[out_cap]) where dep_rows packs the hit COLUMN indices of all
    segments contiguously in (segment-major, column-ascending) order. Hits
    beyond out_cap are dropped by the scatter; callers detect overflow via
    indptr[-1] > out_cap and fall back."""
    s, n = hits.shape
    counts = jnp.sum(hits, axis=1, dtype=jnp.int32)
    indptr = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(counts, dtype=jnp.int32)])
    within = jnp.cumsum(hits, axis=1, dtype=jnp.int32) - hits
    pos = jnp.where(hits > 0, indptr[:-1][:, None] + within, out_cap)
    col = jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32)[None, :], (s, n))
    dep_rows = jnp.zeros(out_cap, jnp.int32) \
        .at[pos.reshape(-1)].set(col.reshape(-1), mode="drop")
    return indptr, dep_rows


def _popcount_u32(x):
    """Branch-free SWAR popcount per u32 lane (jnp.bitwise_count is not
    available across the supported jax versions)."""
    x = x - ((x >> 1) & jnp.uint32(0x55555555))
    x = (x & jnp.uint32(0x33333333)) + ((x >> 2) & jnp.uint32(0x33333333))
    x = (x + (x >> 4)) & jnp.uint32(0x0F0F0F0F)
    return ((x * jnp.uint32(0x01010101)) >> 24).astype(jnp.int32)


def _packed_segment_compact(m, out_cap: int):
    """_segment_compact over BIT-PACKED segments: `m` is u32[S, W] (each
    segment a packed row set, cap == W*32). Crucially never materializes the
    S x cap bit matrix -- popcounts and prefix sums run word-packed (S*W
    elements), only the <= out_cap NONZERO words expand to bit granularity
    (out_cap x 32). At dispatch shapes (S=2k segments, cap=16k rows) that is
    ~30x less intermediate traffic than the dense path, which dominated the
    kernel's wall time. Output contract matches _segment_compact: (indptr
    i32[S+1], dep_rows i32[out_cap]) in (segment-major, row-ascending)
    order; indptr[-1] > out_cap signals overflow (a nonzero word count can
    never exceed the bit count, so the word compaction cannot overflow
    without the bit total overflowing too)."""
    s, w = m.shape
    pop = _popcount_u32(m)                                    # i32[S, W]
    counts = jnp.sum(pop, axis=1, dtype=jnp.int32)
    indptr = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(counts, dtype=jnp.int32)])
    flat_pop = pop.reshape(-1)
    flat_val = m.reshape(-1)
    # global output offset of each word's first bit (word-major order ==
    # segment-major, row-ascending)
    bit_off = jnp.cumsum(flat_pop, dtype=jnp.int32) - flat_pop
    nz = flat_pop > 0
    slot = jnp.where(nz,
                     jnp.cumsum(nz.astype(jnp.int32), dtype=jnp.int32) - 1,
                     out_cap)
    # compact the nonzero words: ONE S*W-entry scatter of flat indices, then
    # out_cap-sized gathers for (value, bit offset, base row index) -- three
    # full-size scatters here tripled the kernel's wall time
    src = jnp.zeros(out_cap, jnp.int32) \
        .at[slot].set(jnp.arange(s * w, dtype=jnp.int32), mode="drop")
    live = jnp.arange(out_cap, dtype=jnp.int32) \
        < jnp.sum(nz.astype(jnp.int32))
    cw_val = jnp.where(live, flat_val[src], jnp.uint32(0))
    cw_off = bit_off[src]
    cw_row = (src % w) * 32
    # bit-expand only the compacted words
    bits = ((cw_val[:, None] >> jnp.arange(32, dtype=jnp.uint32)) & 1) \
        .astype(jnp.int32)                                    # [out_cap, 32]
    within = jnp.cumsum(bits, axis=1, dtype=jnp.int32) - bits
    pos = jnp.where((bits > 0) & live[:, None], cw_off[:, None] + within,
                    out_cap)
    rows = cw_row[:, None] + jnp.arange(32, dtype=jnp.int32)[None, :]
    dep_rows = jnp.zeros(out_cap, jnp.int32) \
        .at[pos.reshape(-1)].set(rows.reshape(-1), mode="drop")
    return indptr, dep_rows


def _csum_fold(x, seed: int):
    """Position-weighted fold of one CSR lane into a u32 word: bitcast to
    u32, mix the high half down, then a wrapping sum weighted by odd
    per-position multipliers (odd => invertible mod 2^32, so transposing
    or flipping any element changes the sum). Runs in-jit on device; the
    host twin is csr_checksum_host. Sums mod 2^32 are order-independent,
    so device reduction order cannot diverge from numpy's."""
    v = jax.lax.bitcast_convert_type(x.reshape(-1), jnp.uint32)
    v = v ^ (v >> jnp.uint32(16))
    idx = jnp.arange(v.shape[0], dtype=jnp.uint32)
    return jnp.sum(v * (jnp.uint32(2) * idx + jnp.uint32(seed)),
                   dtype=jnp.uint32)


def csr_checksum(indptr, dep_rows, dep_ts):
    """Device-side integrity word over a finalized CSR triple, fused into
    the finalize kernels' returns and re-derived from the host copies at
    harvest (resolver._csum_ok): a readback that arrives bit-flipped can
    never decode into wrong deps -- the mismatch routes the group to the
    legacy fallback, which re-reads the raw candidate buffers."""
    return (_csum_fold(indptr, 1) ^ _csum_fold(dep_rows, 5)
            ^ _csum_fold(dep_ts, 9))


def csr_checksum_host(indptr, dep_rows, dep_ts) -> int:
    """numpy twin of csr_checksum, computed over the fetched host copies.
    Must track the device fold bit for bit."""
    def fold(x, seed):
        v = np.ascontiguousarray(x).view(np.uint32).reshape(-1)
        v = v ^ (v >> np.uint32(16))
        idx = np.arange(v.shape[0], dtype=np.uint32)
        return (v * (np.uint32(2) * idx + np.uint32(seed))).sum(
            dtype=np.uint32)
    return int(fold(indptr, 1) ^ fold(dep_rows, 5) ^ fold(dep_ts, 9))


# --------------------------------------------------------------------------
# Execution-frontier compaction + recovery scans: the finalized-CSR twins
# for the exec/recovery planes. The frontier kernel emits the EXACT
# released-row index list (segment per store), a count bound (indptr), and
# a u32 integrity word, so harvest readback is O(released) instead of
# O(arena rows) and the host decode is a direct slice. The recovery scan
# answers "which cmd-arena rows are live and stalled" the same way, so
# progress-engine candidate selection at 10k in-flight is one device query.

FRONTIER_OUT_TIERS = (32, 256, 2048)
RECOVERY_OUT_TIERS = (32, 256, 2048)


def frontier_checksum(indptr, rows):
    """Device integrity word over a compacted frontier (indptr + row list):
    the exec plane's twin of csr_checksum. Fresh fold seeds so a frontier
    word can never alias a finalize word; a readback that arrives
    bit-flipped routes the harvest to the legacy bitmask decode (counted)
    instead of releasing wrong rows."""
    return _csum_fold(indptr, 13) ^ _csum_fold(rows, 17)


def frontier_checksum_host(indptr, rows) -> int:
    """numpy twin of frontier_checksum, computed over the fetched host
    copies. Must track the device fold bit for bit."""
    def fold(x, seed):
        v = np.ascontiguousarray(x).view(np.uint32).reshape(-1)
        v = v ^ (v >> np.uint32(16))
        idx = np.arange(v.shape[0], dtype=np.uint32)
        return (v * (np.uint32(2) * idx + np.uint32(seed))).sum(
            dtype=np.uint32)
    return int(fold(indptr, 13) ^ fold(rows, 17))


def _frontier_compact_body(planes, out_cap: int):
    """Unjitted body shared by frontier_compact and the protocol_tick exec
    block (one source of truth -> fused and standalone paths bit-identical).
    `planes` is a tuple of per-store lane tuples exactly as
    fused_execution_frontier takes them; each store is one compaction
    SEGMENT, so indptr demuxes per-store released runs and each row value
    is a GLOBAL bit index (32 * store word offset + arena row) that the
    host converts back with its word span."""
    packs = []
    for (adj, exec_ts, applied, pending, awaits_all) in planes:
        cap = adj.shape[0]
        ready = _frontier_ready(adj, exec_ts, applied, pending, awaits_all)
        packs.append(_pack_bits(ready.reshape(1, cap))[0])
    w_tot = sum(int(p.shape[0]) for p in packs)
    rows_m, off = [], 0
    for p in packs:
        w = int(p.shape[0])
        segs = []
        if off:
            segs.append(jnp.zeros(off, jnp.uint32))
        segs.append(p)
        if w_tot - off - w:
            segs.append(jnp.zeros(w_tot - off - w, jnp.uint32))
        rows_m.append(jnp.concatenate(segs) if len(segs) > 1 else segs[0])
        off += w
    m = jnp.stack(rows_m)
    indptr, rows = _packed_segment_compact(m, out_cap)
    return (indptr, rows, frontier_checksum(indptr, rows),
            jnp.concatenate(packs))


@functools.partial(jax.jit, static_argnames=("out_cap",))
def frontier_compact(planes, out_cap: int):
    """Compacted execution frontier for a tuple of store planes: ONE device
    call answering every store's release list for a node tick.

    -> (indptr i32[S+1], rows i32[out_cap], csum u32, packed u32[sum(W_s)])

    rows holds released GLOBAL bit indices in (store-major, row-ascending)
    order; store s's run is rows[indptr[s]:indptr[s+1]] - 32 * w_lo_s.
    indptr is exact regardless of out_cap: indptr[-1] > out_cap signals
    overflow AND gives the true needed size for the tier bump. `packed` is
    the legacy full bitmask, RETAINED ON DEVICE -- the harvest fetches only
    the compacted lanes (O(released) bytes) and touches packed solely on
    the counted checksum-mismatch / overflow fallback paths."""
    return _frontier_compact_body(planes, out_cap)


def _recovery_scan_body(status, touched_ms, now_ms, stall_ms, out_cap: int):
    """Unjitted recovery-candidate scan over cmd-arena SoA columns: a row
    is a candidate iff its status sits in the live band (PRE_ACCEPTED ..
    < APPLIED, which also excludes the INVALIDATED/TRUNCATED terminals
    above it) and its last arena touch is at least stall_ms old. The host
    twin is CmdPlane.recovery_scan_host -- bit for bit the same predicate
    over the numpy shadows."""
    live = (status >= CMD_ST_PRE_ACCEPTED) & (status < CMD_ST_APPLIED)
    stalled = live & ((now_ms - touched_ms) >= stall_ms)
    m = _pack_bits(stalled.reshape(1, -1))
    indptr, rows = _packed_segment_compact(m, out_cap)
    return indptr, rows, frontier_checksum(indptr, rows)


@functools.partial(jax.jit, static_argnames=("out_cap",))
def recovery_scan(status, touched_ms, now_ms, stall_ms, out_cap: int):
    """One-query recovery candidate selection: which cmd-arena rows need a
    MaybeRecover/BeginRecovery probe (reference: the ProgressLog shards'
    pendingTimers walk, impl/progress/*.java -- batch work for the cmd
    plane instead of a host walk over every live waiter).

    status/touched_ms: i32[cap] arena columns; now_ms/stall_ms: i32
    scalars (traced -- value churn mints no recompiles).
    -> (indptr i32[2], rows i32[out_cap], csum u32); same overflow and
    checksum contract as frontier_compact."""
    return _recovery_scan_body(status, touched_ms, now_ms, stall_ms,
                               out_cap)


@functools.partial(jax.jit, static_argnames=("out_cap",))
def finalize_csr(packed, word_off, kid_rows, slot_subj, slot_kid,
                 subj_row, act_ts, out_cap: int):
    """Device-side dep FINALIZATION for the key domain: consume the packed
    conflict bitmask straight out of deps_resolve (or one store's word span
    of the fused/sharded output -- `word_off` is the traced span offset) and
    emit final, exact, already-translated dep lists in CSR form, so harvest
    becomes a contiguous readback instead of unpackbits + re-filtering.

    Exactness comes from the device mirror of the host's per-key row masks:
    `kid_rows[kid]` is the packed set of arena rows whose key set contains
    the real key with dense id `kid` (resolver._StoreArena.key_rows shipped
    as a lane). ANDing it against the subject's packed bucket-level result
    removes bucket-collision false positives ON DEVICE -- the per-(subject,
    key) slot list replaces the host KM gather stack.

    packed:    u32[B, W_total] deps_resolve / fused output
    word_off:  i32 scalar      word offset of this store's span (0 unfused)
    kid_rows:  u32[KC, W]      per-key packed row masks (W*32 == cap)
    slot_subj: i32[S]          subject row per (subject, key) slot; padding B
    slot_kid:  i32[S]          dense key id per slot; padding KC
    subj_row:  i32[B]          subject's own arena row (-1 if unregistered),
                               cleared from its slots (a txn never deps on
                               itself)
    act_ts:    i32[cap, 3]     the arena's txn-id lanes; gathered through the
                               compacted rows so RESULTS ARE TXN IDS
    -> (indptr i32[S+1], dep_rows i32[out_cap], dep_ts i32[out_cap, 3],
        bound i32 scalar, csum u32 scalar);
       dep order within a slot is ascending arena row; indptr[-1] > out_cap
       signals overflow. `bound` is the segmented reduction over the slots'
       kid-table row masks -- exactly the host popcount bound
       (sum of key_pop over the dispatch's slot keys) -- read back with the
       result so the NEXT dispatch's out_cap tier needs no host O(keys)
       pass (resolver's OutCapTiers policy). `csum` is the csr_checksum
       integrity word over the triple, verified at harvest.
    """
    return _finalize_csr_body(packed, word_off, kid_rows, slot_subj,
                              slot_kid, subj_row, act_ts, out_cap)


def _finalize_csr_body(packed, word_off, kid_rows, slot_subj, slot_kid,
                       subj_row, act_ts, out_cap: int):
    """finalize_csr's trace body, unjitted so protocol_tick can inline the
    same compaction inside the fused cluster-tick program (the standalone
    jit wrapper above delegates here -- one source of truth, bit-identical
    either way)."""
    b = packed.shape[0]
    kc, w = kid_rows.shape
    blk = jax.lax.dynamic_slice_in_dim(packed, word_off, w, axis=1)
    ok = (slot_subj >= 0) & (slot_subj < b) & (slot_kid >= 0) & (slot_kid < kc)
    kid_m = kid_rows[jnp.clip(slot_kid, 0, kc - 1)]
    bound = jnp.sum(jnp.where(
        ok, jnp.sum(_popcount_u32(kid_m), axis=1, dtype=jnp.int32), 0),
        dtype=jnp.int32)
    so = jnp.clip(slot_subj, 0, b - 1)
    m = jnp.where(ok[:, None], blk[so] & kid_m, jnp.uint32(0))
    r = subj_row[so]
    widx = jnp.arange(w, dtype=jnp.int32)
    selfbit = jnp.where(
        (r >= 0)[:, None] & (widx[None, :] == (r >> 5)[:, None]),
        (jnp.uint32(1) << (r & 31).astype(jnp.uint32))[:, None],
        jnp.uint32(0))
    m = m & ~selfbit
    indptr, dep_rows = _packed_segment_compact(m, out_cap)
    dep_ts = act_ts[dep_rows]
    return (indptr, dep_rows, dep_ts, bound,
            csr_checksum(indptr, dep_rows, dep_ts))


@functools.partial(jax.jit, static_argnames=("out_cap",))
def range_finalize_csr(iv_of, iv_start, iv_end, ent_ok,
                       subj_before, subj_kinds,
                       r_start, r_end, r_ts, r_kinds, r_valid,
                       witness_table, out_cap: int):
    """Device-side finalization of range-arena deps: stab the REAL interval
    endpoint lanes per CSR entry (no covered-bucket hull, no iv_of
    contraction), so each entry gets its own exact hit segment. Entries are
    either a key subject's point interval [k, k+1) (the key-subject
    range-deps lane) or ONE PIECE of a range subject's owned interval set
    (multi-piece subjects contribute one segment lane per piece; the host
    attribution walk unions the per-piece hits, which is idempotent) -- so
    the host re-filter against store.range_txns retires for BOTH subject
    kinds. The witness/before/valid masks gather through iv_of, matching
    range_deps_resolve; `ent_ok` gates which entries finalize (entries of
    the targeted store).

    -> (indptr i32[NV+1], dep_rows i32[out_cap], dep_ts i32[out_cap, 3],
        bound i32 scalar, csum u32 scalar); dep_ts carries the range
       arena's txn-id lanes so results are txn ids. `bound` is the
       segmented STAB COUNT -- the number of (entry, valid-range) interval
       overlaps before the witness/before narrowing -- an exact upper
       bound on indptr[-1] read back with the result so the NEXT
       dispatch's out_cap tier needs no host entries*nvalid product
       (resolver's OutCapTiers policy, mirroring finalize_csr's key-lane
       bound from PR 8). `csum` is the csr_checksum integrity word,
       verified at harvest.
    """
    return _range_finalize_csr_body(iv_of, iv_start, iv_end, ent_ok,
                                    subj_before, subj_kinds,
                                    r_start, r_end, r_ts, r_kinds, r_valid,
                                    witness_table, out_cap)


def _range_finalize_csr_body(iv_of, iv_start, iv_end, ent_ok,
                             subj_before, subj_kinds,
                             r_start, r_end, r_ts, r_kinds, r_valid,
                             witness_table, out_cap: int):
    """range_finalize_csr's trace body, unjitted for protocol_tick (see
    _finalize_csr_body)."""
    b = subj_before.shape[0]
    o = jnp.clip(iv_of, 0, b - 1)
    inb = (iv_of >= 0) & (iv_of < b) & ent_ok
    hit = (iv_start[:, None] < r_end[None, :]) \
        & (r_start[None, :] < iv_end[:, None])
    stab = hit & r_valid[None, :] & inb[:, None]
    bound = jnp.sum(stab.astype(jnp.int32), dtype=jnp.int32)
    witness = witness_table[subj_kinds[o][:, None], r_kinds[None, :]] == 1
    before = _lex_before(r_ts[None, :, :], subj_before[o][:, None, :])
    m = stab & witness & before
    indptr, dep_rows = _segment_compact(m.astype(jnp.int32), out_cap)
    dep_ts = r_ts[dep_rows]
    return (indptr, dep_rows, dep_ts, bound,
            csr_checksum(indptr, dep_rows, dep_ts))


@jax.jit
def arena_scatter(bitmaps, ts, exec_ts, kinds, valid,
                  rows, key_rows, key_mods, ts_rows, exec_rows, kind_rows,
                  valid_rows):
    """Scatter dirty rows into the device arena. Bitmap rows are rebuilt on
    device from a CSR key list (key_rows i32[nnz] holds ABSOLUTE arena row
    indices; padding entries use cap -- out of bounds, dropped): each dirty
    row's bitmap is zeroed, then its current buckets scatter-set, so rows
    whose key sets shrank lose their stale bits. Row-padding duplicates
    row[0] with identical lane data -- harmless double write."""
    cleared = bitmaps.at[rows].set(0.0)
    return (cleared.at[key_rows, key_mods].max(1.0, mode="drop"),
            ts.at[rows].set(ts_rows),
            exec_ts.at[rows].set(exec_rows),
            kinds.at[rows].set(kind_rows),
            valid.at[rows].set(valid_rows))


@jax.jit
def arena_scatter_keys(bitmaps, rows, key_rows, key_mods):
    """Field-granular scatter for KEY-SET-ONLY row changes (key widening,
    prune/truncate shrinks): rebuild the dirty rows' bitmaps from the CSR
    without shipping the ts/exec/kind/valid lanes the change didn't touch.
    Same clear-then-max CSR contract as arena_scatter. (The [kmin, kmax]
    hull lanes this used to refresh are retired -- the range kernel now
    contracts over the same bitmaps.)"""
    cleared = bitmaps.at[rows].set(0.0)
    return cleared.at[key_rows, key_mods].max(1.0, mode="drop")


@jax.jit
def range_scatter(starts, ends, ts, kinds, valid,
                  rows, start_rows, end_rows, ts_rows, kind_rows, valid_rows):
    """Scatter dirty rows into the range arena (tiny flat lanes -- one
    interval per row). Padding duplicates row[0]; harmless double write."""
    return (starts.at[rows].set(start_rows),
            ends.at[rows].set(end_rows),
            ts.at[rows].set(ts_rows),
            kinds.at[rows].set(kind_rows),
            valid.at[rows].set(valid_rows))


@functools.partial(jax.jit, static_argnames=("new_cap",))
def arena_grow(bitmaps, ts, exec_ts, kinds, valid, new_cap: int):
    """Double the arena capacity ON DEVICE (zero/neg padding) -- re-uploading
    a full [cap, K] bitmap over the host link would cost seconds."""
    neg = jnp.int32(np.iinfo(np.int32).min)
    grow = new_cap - bitmaps.shape[0]

    def pad(a, value=0):
        widths = [(0, grow)] + [(0, 0)] * (a.ndim - 1)
        return jnp.pad(a, widths, constant_values=value)

    return (pad(bitmaps), pad(ts), pad(exec_ts, neg), pad(kinds),
            pad(valid, False))


def pad_to(x: np.ndarray, size: int, axis: int = 0) -> np.ndarray:
    """Pad axis up to `size` with zeros (bucketed static shapes for jit)."""
    if x.shape[axis] == size:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, size - x.shape[axis])
    return np.pad(x, pad)


def bucket_size(n: int, minimum: int = 8) -> int:
    """Next power-of-two bucket >= n (>= minimum), so jit caches stay warm."""
    return snap(n, (), minimum)


# The deps-resolver subject-batch padding ladder. Deliberately few named
# tiers so the jit cache stays tiny and warmup() can cover every shape the
# async pipeline dispatches: {8, 64, 128} handle the common batch-window
# coalescing sizes (128 is the default MAX_DISPATCH), and anything larger
# falls onto power-of-two buckets from 256 up (the bench's deep-dispatch
# configurations warm their own tier explicitly).
SUBJECT_TIERS = (8, 64, 128)


def subject_tier(n: int) -> int:
    """Padded subject-batch size for a dispatch of n subjects."""
    return snap(n, SUBJECT_TIERS, 256)


# CSR flat-entry padding ladders. The subject CSR (one entry per owned key /
# owned interval) pads to NNZ_TIERS; the dirty-row scatter CSR packs rows
# greedily under SCATTER_NNZ_TIERS[-1] entries per chunk so both the row tier
# ({8, 64}) and the nnz tier stay warmable. Oversized singles fall onto
# power-of-two buckets.
NNZ_TIERS = (32, 256, 2048)
SCATTER_NNZ_TIERS = (64, 512)


def nnz_tier(n: int) -> int:
    """Padded CSR entry count for a dispatch carrying n subject entries."""
    return snap(n, NNZ_TIERS, 4096)


def scatter_nnz_tier(n: int) -> int:
    """Padded CSR entry count for an arena-scatter chunk of n key entries."""
    return snap(n, SCATTER_NNZ_TIERS, 1024)


# Finalized-CSR output padding ladder. The compaction kernels' out_cap tier
# is PINNED by the resolver's OutCapTiers hysteresis policy (ops.tiers),
# fed by the device-computed bound each finalize call reads back -- grow
# immediately, shrink only after several consecutive quiet dispatches -- so
# the picked tier is not data-dependent dispatch to dispatch and the bench's
# zero-recompile assertion covers the finalize kernels without exemption.
# (With device_out_bound disabled the resolver sizes from the exact host
# popcount bound instead: the differential baseline.)
OUT_TIERS = (256, 2048, 16384)
OUT_TIER_FLOOR = 32768


def out_tier(n: int) -> int:
    """Padded finalized-CSR entry count for a dispatch with n bound hits."""
    return snap(n, OUT_TIERS, OUT_TIER_FLOOR)


# ---------------------------------------------------------------------------
# Device command plane (ops/cmd_plane.py): batched txn state machines
# ---------------------------------------------------------------------------

# Status ladder constants mirrored from local.status.Status. ops/cmd_plane.py
# asserts these against the enum at import, so the mirrors cannot drift.
CMD_ST_PRE_ACCEPTED = 1
CMD_ST_ACCEPTED = 3
CMD_ST_COMMITTED = 5
CMD_ST_STABLE = 6
CMD_ST_READY = 7
CMD_ST_PRE_APPLIED = 8
CMD_ST_APPLIED = 9
CMD_ST_INVALIDATED = 10
CMD_ST_TRUNCATED = 11

# outcome codes in the low 3 bits of out_code (cmd_plane maps them back to
# AcceptOutcome / CommitOutcome); high bits carry side-channel facts the
# host residuals need
CMD_OUT_SUCCESS = 0
CMD_OUT_REDUNDANT = 1
CMD_OUT_REJECTED_BALLOT = 2
CMD_OUT_TRUNCATED = 3
CMD_OUT_INSUFFICIENT = 4
CMD_OUT_INCONSISTENT_BIT = 8    # redundant commit/apply with executeAt drift
CMD_OUT_WAS_STABLE_BIT = 16     # apply arrived on an already-stable command

# op kinds in op_kind
CMD_OP_PREACCEPT = 0
CMD_OP_ACCEPT = 1
CMD_OP_COMMIT = 2
CMD_OP_APPLY = 3

# op_flags bits (host admission encodes these per op)
CMD_F_PERMIT_FAST = 1    # ballot == Ballot.ZERO
CMD_F_EPOCH_OK = 2       # txn_id.epoch >= node.epoch at encode time
CMD_F_EXPIRED = 4        # preaccept expiry fired (precomputed at encode: a
                         # pure function of txn hlc + now + agent timeout, so
                         # the host float compare stays exactly authoritative)
CMD_F_MSG_HAS_TXN = 8    # the commit/apply message carries a txn body
CMD_F_VALID = 16         # real op (padding rows leave this clear)
CMD_F_DEPS_EMPTY = 32    # commit/apply deps empty (promote-eligible)

# batched-op padding ladder for cmd_tick dispatches
CMD_OP_TIERS = (8, 64, 512)


def cmd_op_tier(n: int) -> int:
    """Padded op count for a cmd_tick dispatch carrying n ops."""
    return snap(n, CMD_OP_TIERS, 4096)


def _lex_max_masked(rows, valid):
    """Lexicographic max over rows[i] where valid[i]. rows: i32[K, 3],
    valid: bool[K] -> (i32[3] max lanes, bool any_valid); lanes are INT32_MIN
    when nothing is valid."""
    neg = jnp.int32(np.iinfo(np.int32).min)
    l0 = jnp.where(valid, rows[:, 0], neg)
    m0 = jnp.max(l0)
    t0 = valid & (rows[:, 0] == m0)
    l1 = jnp.where(t0, rows[:, 1], neg)
    m1 = jnp.max(l1)
    t1 = t0 & (rows[:, 1] == m1)
    m2 = jnp.max(jnp.where(t1, rows[:, 2], neg))
    return jnp.stack([m0, m1, m2]), jnp.any(valid)


def cmd_checksum(out_code, out_status, out_ts, clock):
    """Device integrity word over a cmd_tick result block (the PR 11 harvest
    checksum discipline extended to the command plane): recomputed from the
    host copies at harvest; a bit-flipped readback falls back to the host
    handlers instead of applying corrupt transitions."""
    return (_csum_fold(out_code, 3) ^ _csum_fold(out_status, 7)
            ^ _csum_fold(out_ts, 11)
            ^ _csum_fold(clock.reshape(1), 13))


def cmd_checksum_host(out_code, out_status, out_ts, clock) -> int:
    """numpy twin of cmd_checksum; must track the device fold bit for bit."""
    def fold(x, seed):
        v = np.ascontiguousarray(x, dtype=np.int32).view(np.uint32).reshape(-1)
        v = v ^ (v >> np.uint32(16))
        idx = np.arange(v.shape[0], dtype=np.uint32)
        return (v * (np.uint32(2) * idx + np.uint32(seed))).sum(
            dtype=np.uint32)
    return int(fold(out_code, 3) ^ fold(out_status, 7) ^ fold(out_ts, 11)
               ^ fold(np.asarray([clock], dtype=np.int32), 13))


@functools.partial(jax.jit, static_argnames=("promote",))
def cmd_tick(status, flags, promised, accepted, execute_at, durability,
             kmax, kmax_valid, clock,
             op_kind, op_row, op_txn, op_ballot, op_exec, op_keys, op_flags,
             op_now, op_prev, op_rlast, op_kprev, op_klast,
             node_epoch, lane2_clean, lane2_rej,
             dur_local, promote: bool = False):
    """One device dispatch evaluating a batch of protocol transitions IN
    ORDER over the SoA command arena: PreAccept witness (fast-path test +
    unique_now twin + expiry), Accept ballot checks, Commit/Apply status
    promotions -- the per-txn Python state machines of local/commands.py
    re-expressed as one fori_loop over op slots.

    Arena columns (authoritative device state between dispatches):
      status:     i32[cap]      Status ladder value
      flags:      i32[cap]      bit0 = definition recorded (cmd.txn != None)
      promised:   i32[cap, 3]   promised ballot lanes
      accepted:   i32[cap, 3]   accepted ballot lanes
      execute_at: i32[cap, 3]   executeAt lanes (INT32_MIN lanes == None)
      durability: i32[cap]      Durability ladder value
      kmax:       i32[kcap, 3]  per-key max-conflict lanes (MaxConflicts)
      kmax_valid: bool[kcap]    false == no conflict witnessed for the key
      clock:      i32 scalar    node HLC register (node._last_hlc)

    Ops (padded to CMD_OP_TIERS; lanes are ABSOLUTE base-(0,0) encodings:
    lane0 epoch, lane1 hlc, lane2 (flags << 16 | node) - 2^31):
      op_kind:  i32[n]     CMD_OP_*
      op_row:   i32[n]     arena row of the op's txn
      op_txn:   i32[n, 3]  TxnId lanes (flags carry kind/domain, so this IS
                           txn_id.as_timestamp() too)
      op_ballot: i32[n, 3] ballot lanes
      op_exec:  i32[n, 3]  proposed/decided executeAt lanes (accept/commit/
                           apply)
      op_keys:  i32[n, KPAD] dense kid-table slots of the op's owned keys
                           (-1 padding)
      op_flags: i32[n]     CMD_F_* bits
      op_now:   i32[n]     now_micros at the op's scheduler instant
      op_prev:  i32[n]     index of the previous op in this batch on the
                           same row (-1 = none): intra-batch row chains
      op_rlast: bool[n]    this op is its row's LAST writer in the batch
      op_kprev: i32[n,KPAD] previous writer of this kid slot, encoded
                           p * KPAD + s (-1 = none)
      op_klast: bool[n,KPAD] this (op, slot) is the kid's last writer
    Scalars: node_epoch; lane2_clean/lane2_rej = the node's lane2 value with
    flags 0 / REJECTED (precomputed host-side: (flags << 16 | node) - 2^31);
    dur_local = Durability.LOCAL.

    The loop carries only op-tier-sized state: each op's view of the arena
    is gathered up front, intra-batch dependencies resolve through the
    prev-writer links, and the final chain values scatter back ONCE after
    the loop (last-writer wins). Carrying the cap-sized columns through the
    fori_loop instead makes XLA's copy insertion duplicate them every
    iteration -- ~17ms per 512-op dispatch at cap 16384 vs ~1ms this way.

    `promote` (static): additionally run the empty-deps maybe_execute
    promotion on device (STABLE -> READY_TO_EXECUTE, PRE_APPLIED ->
    APPLIED + durability merge) -- the arena-only bench mode. With host
    residuals (apply_to_store) the promotion runs host-side instead.

    -> (updated columns..., out_code i32[n], out_ts i32[n, 3] (witnessed /
        echoed executeAt), out_status i32[n], csum u32)
    """
    return _cmd_tick_body(status, flags, promised, accepted, execute_at,
                          durability, kmax, kmax_valid, clock,
                          op_kind, op_row, op_txn, op_ballot, op_exec,
                          op_keys, op_flags, op_now, op_prev, op_rlast,
                          op_kprev, op_klast, node_epoch, lane2_clean,
                          lane2_rej, dur_local, promote)


def _cmd_tick_body(status, flags, promised, accepted, execute_at, durability,
                   kmax, kmax_valid, clock,
                   op_kind, op_row, op_txn, op_ballot, op_exec, op_keys,
                   op_flags, op_now, op_prev, op_rlast, op_kprev, op_klast,
                   node_epoch, lane2_clean, lane2_rej,
                   dur_local, promote: bool = False):
    """cmd_tick's trace body, unjitted so protocol_tick can run the same
    batched transitions inside the fused cluster-tick program. Keeps the
    op-tier-sized fori_loop carry (see the docstring above: a cap-sized
    carry makes XLA duplicate the columns every iteration)."""
    cap = status.shape[0]
    kcap = kmax.shape[0]
    n, kpad = op_keys.shape
    neg = jnp.int32(np.iinfo(np.int32).min)

    # per-op arena views before the batch (padding slots clip to row/kid 0
    # and are masked out of every write by op_rlast/op_klast)
    rowc = jnp.clip(op_row, 0, cap - 1)
    st_0 = status[rowc]
    fl_0 = flags[rowc]
    pr_0 = promised[rowc]
    ab_0 = accepted[rowc]
    ea_0 = execute_at[rowc]
    du_0 = durability[rowc]
    kid_0 = jnp.clip(op_keys, 0, kcap - 1)
    km_0 = kmax[kid_0]          # (n, kpad, 3)
    kv_0 = kmax_valid[kid_0]    # (n, kpad)

    def body(i, c):
        (r_st, r_fl, r_pr, r_ab, r_ea, r_du, k_km, k_kv,
         clock, out_code, out_ts, out_status) = c
        kind = op_kind[i]
        valid = (op_flags[i] & CMD_F_VALID) != 0
        prev = op_prev[i]
        use_prev = prev >= 0
        pc = jnp.where(use_prev, prev, 0)
        st = jnp.where(use_prev, r_st[pc], st_0[i])
        fl = jnp.where(use_prev, r_fl[pc], fl_0[i])
        pr = jnp.where(use_prev, r_pr[pc], pr_0[i])
        ab = jnp.where(use_prev, r_ab[pc], ab_0[i])
        ea = jnp.where(use_prev, r_ea[pc], ea_0[i])
        du = jnp.where(use_prev, r_du[pc], du_0[i])
        txn = op_txn[i]
        bal = op_ballot[i]
        oex = op_exec[i]
        kids = op_keys[i]
        permit_fast = (op_flags[i] & CMD_F_PERMIT_FAST) != 0
        epoch_ok = (op_flags[i] & CMD_F_EPOCH_OK) != 0
        expired = (op_flags[i] & CMD_F_EXPIRED) != 0
        msg_has_txn = (op_flags[i] & CMD_F_MSG_HAS_TXN) != 0
        deps_empty = (op_flags[i] & CMD_F_DEPS_EMPTY) != 0
        now = op_now[i]

        has_txn = (fl & 1) != 0
        ea_set = ea[0] != neg
        terminal = (st == CMD_ST_INVALIDATED) | (st == CMD_ST_TRUNCATED)
        pr_gt_bal = _lex_before(bal, pr)
        pr_max_bal = jnp.where(_lex_before(pr, bal), bal, pr)
        term_code = jnp.where(st == CMD_ST_INVALIDATED,
                              CMD_OUT_REJECTED_BALLOT, CMD_OUT_TRUNCATED)

        # kid-table chain: each slot reads its previous in-batch writer's
        # post-value, else the pre-batch gather
        links = op_kprev[i]
        lv = links >= 0
        lc = jnp.where(lv, links, 0)
        lp, ls = lc // kpad, lc % kpad
        kv_raw = jnp.where(lv, k_kv[lp, ls], kv_0[i])
        kv = kv_raw & (kids >= 0)
        km = jnp.where(lv[:, None], k_km[lp, ls], km_0[i])
        mc, mc_any = _lex_max_masked(km, kv)

        # unique_now twin (local/Node.unique_now): hlc = max(now, clock + 1),
        # bumped past at_least.hlc; epoch = max(node epoch, at_least.epoch)
        def unow(al_ep, al_hlc, lane2):
            h = jnp.maximum(now, clock + 1)
            h = jnp.where(al_hlc >= h, al_hlc + 1, h)
            return jnp.stack([jnp.maximum(node_epoch, al_ep), h, lane2]), h

        # -- PreAccept (commands.preaccept) -----------------------------------
        rej_w, rej_h = unow(txn[0], txn[1], lane2_rej)
        al = jnp.where(mc_any, mc, txn)
        slow_w, slow_h = unow(al[0], al[1], lane2_clean)
        fast = permit_fast & (~mc_any | ~_lex_before(txn, mc)) & epoch_ok
        witness = jnp.where(expired, rej_w, jnp.where(fast, txn, slow_w))
        wit_clock = jnp.where(expired, rej_h,
                              jnp.where(fast, clock, slow_h))
        pa_blocked = terminal | pr_gt_bal
        pa_code = jnp.where(
            terminal, term_code,
            jnp.where(pr_gt_bal, CMD_OUT_REJECTED_BALLOT,
                      jnp.where(has_txn & permit_fast, CMD_OUT_REDUNDANT,
                                CMD_OUT_SUCCESS)))
        pa_wit = ~pa_blocked & ~has_txn & ~ea_set
        pa_st = jnp.where(
            pa_blocked | has_txn, st,
            jnp.where(ea_set, jnp.maximum(st, CMD_ST_PRE_ACCEPTED),
                      CMD_ST_PRE_ACCEPTED))
        pa_fl = jnp.where(pa_blocked, fl, fl | 1)
        pa_pr = jnp.where(pa_blocked, pr, pr_max_bal)
        pa_ea = jnp.where(pa_wit, witness, ea)
        pa_out_ts = jnp.where(pa_wit, witness, ea)

        # -- Accept (commands.accept; the reject_before gate is an admission
        # precondition, so is_rejected_if_not_preaccepted is always false) ---
        committed = st >= CMD_ST_COMMITTED
        ac_code = jnp.where(
            terminal, term_code,
            jnp.where(pr_gt_bal | committed,
                      jnp.where(committed, CMD_OUT_REDUNDANT,
                                CMD_OUT_REJECTED_BALLOT),
                      CMD_OUT_SUCCESS))
        ac_ok = ~terminal & ~pr_gt_bal & ~committed
        ac_st = jnp.where(ac_ok, CMD_ST_ACCEPTED, st)
        ac_pr = jnp.where(ac_ok, bal, pr)
        ac_ab = jnp.where(ac_ok, bal, ab)
        ac_ea = jnp.where(ac_ok, oex, ea)

        # -- Commit -> STABLE (commands.commit) -------------------------------
        ea_eq = jnp.all(ea == oex)
        stable = st >= CMD_ST_STABLE
        cm_incons = stable & ~terminal & ~ea_eq
        cm_insuf = ~stable & ~has_txn & ~msg_has_txn
        cm_ok = ~stable & ~cm_insuf
        cm_code = jnp.where(
            stable,
            CMD_OUT_REDUNDANT + jnp.where(cm_incons,
                                          CMD_OUT_INCONSISTENT_BIT, 0),
            jnp.where(cm_insuf, CMD_OUT_INSUFFICIENT, CMD_OUT_SUCCESS))
        cm_new_st = jnp.int32(CMD_ST_STABLE)
        if promote:
            cm_new_st = jnp.where(deps_empty, CMD_ST_READY, CMD_ST_STABLE)
        cm_st = jnp.where(cm_ok, cm_new_st, st)
        cm_fl = jnp.where(cm_ok & msg_has_txn, fl | 1, fl)
        cm_ea = jnp.where(cm_ok, oex, ea)
        # register at max(executeAt, txnId.as_timestamp()) -- TxnId lanes
        # carry the flags, so the lane compare IS the host compare
        cm_regval = jnp.where(_lex_before(oex, txn), txn, oex)

        # -- Apply -> PRE_APPLIED (commands.apply) ----------------------------
        preapplied = st >= CMD_ST_PRE_APPLIED
        was_stable = st >= CMD_ST_STABLE
        ap_incons = preapplied & ~terminal & ~ea_eq
        ap_insuf = ~preapplied & ~has_txn & ~msg_has_txn
        ap_ok = ~preapplied & ~ap_insuf
        ap_code = jnp.where(
            preapplied,
            CMD_OUT_REDUNDANT + jnp.where(ap_incons,
                                          CMD_OUT_INCONSISTENT_BIT, 0),
            jnp.where(ap_insuf, CMD_OUT_INSUFFICIENT,
                      CMD_OUT_SUCCESS + jnp.where(
                          was_stable, CMD_OUT_WAS_STABLE_BIT, 0)))
        ap_new_st = jnp.int32(CMD_ST_PRE_APPLIED)
        ap_du = du
        if promote:
            ap_new_st = jnp.where(deps_empty, CMD_ST_APPLIED,
                                  CMD_ST_PRE_APPLIED)
            ap_du = jnp.where(ap_ok & deps_empty,
                              jnp.maximum(du, dur_local), du)
        ap_st = jnp.where(ap_ok, ap_new_st, st)
        ap_fl = jnp.where(ap_ok & msg_has_txn, fl | 1, fl)
        ap_ea = jnp.where(ap_ok, oex, ea)

        # -- select per kind, gate on valid, scatter back ---------------------
        is_pa = kind == CMD_OP_PREACCEPT
        is_ac = kind == CMD_OP_ACCEPT
        is_cm = kind == CMD_OP_COMMIT

        def pick(a, b, c_, d):
            return jnp.where(is_pa, a,
                             jnp.where(is_ac, b, jnp.where(is_cm, c_, d)))

        new_st = jnp.where(valid, pick(pa_st, ac_st, cm_st, ap_st), st)
        new_fl = jnp.where(valid, pick(pa_fl, fl, cm_fl, ap_fl), fl)
        new_pr = jnp.where(valid, pick(pa_pr, ac_pr, pr, pr), pr)
        new_ab = jnp.where(valid, pick(ab, ac_ab, ab, ab), ab)
        new_ea = jnp.where(valid, pick(pa_ea, ac_ea, cm_ea, ap_ea), ea)
        new_du = jnp.where(valid, pick(du, du, du, ap_du), du)
        code = pick(pa_code, ac_code, cm_code, ap_code)
        ts_out = pick(pa_out_ts, ac_ea, cm_ea, ap_ea)
        do_reg = valid & pick(pa_wit, ac_ok, cm_ok, ap_ok)
        regval = pick(witness, oex, cm_regval, cm_regval)

        r_st = r_st.at[i].set(new_st)
        r_fl = r_fl.at[i].set(new_fl)
        r_pr = r_pr.at[i].set(new_pr)
        r_ab = r_ab.at[i].set(new_ab)
        r_ea = r_ea.at[i].set(new_ea)
        r_du = r_du.at[i].set(new_du)

        better = ~kv | _lex_before(km, regval[None, :])
        take = do_reg & better & (kids >= 0)
        nkm = jnp.where(take[:, None], regval[None, :], km)
        k_km = k_km.at[i].set(nkm)
        k_kv = k_kv.at[i].set(kv_raw | do_reg)

        clock = jnp.where(valid & is_pa & pa_wit, wit_clock, clock)
        out_code = out_code.at[i].set(jnp.where(valid, code, -1))
        out_ts = out_ts.at[i].set(ts_out)
        out_status = out_status.at[i].set(new_st)
        return (r_st, r_fl, r_pr, r_ab, r_ea, r_du, k_km, k_kv,
                clock, out_code, out_ts, out_status)

    init = (st_0, fl_0, pr_0, ab_0, ea_0, du_0, km_0, kv_0,
            jnp.asarray(clock, jnp.int32),
            jnp.full(n, -1, jnp.int32), jnp.full((n, 3), neg, jnp.int32),
            jnp.full(n, -1, jnp.int32))
    (r_st, r_fl, r_pr, r_ab, r_ea, r_du, k_km, k_kv,
     clock, out_code, out_ts, out_status) = \
        jax.lax.fori_loop(0, n, body, init)

    # single writeback: each row's / kid's last in-batch writer carries the
    # chain's final value (padding and non-last writes drop)
    wrow = jnp.where(op_rlast, op_row, cap)
    status = status.at[wrow].set(r_st, mode="drop")
    flags = flags.at[wrow].set(r_fl, mode="drop")
    promised = promised.at[wrow].set(r_pr, mode="drop")
    accepted = accepted.at[wrow].set(r_ab, mode="drop")
    execute_at = execute_at.at[wrow].set(r_ea, mode="drop")
    durability = durability.at[wrow].set(r_du, mode="drop")
    wkid = jnp.where(op_klast, op_keys, kcap).reshape(-1)
    kmax = kmax.at[wkid].set(k_km.reshape(-1, 3), mode="drop")
    kmax_valid = kmax_valid.at[wkid].set(k_kv.reshape(-1), mode="drop")
    return (status, flags, promised, accepted, execute_at, durability,
            kmax, kmax_valid, clock, out_code, out_ts, out_status,
            cmd_checksum(out_code, out_status, out_ts, clock))


# -- the protocol megakernel --------------------------------------------------
#
# One jitted program per cluster tick: the node-lane resolve (key + range),
# every plan's finalize-CSR compaction demuxed IN-KERNEL at its merge span,
# optional cmd_tick blocks, and the fast-path electorate-quorum count over
# the tick's PreAccept lanes. Each stage is the SAME trace body the
# standalone kernels run (_finalize_csr_body / _range_finalize_csr_body /
# _cmd_tick_body and the node_lane resolve bodies), so fused outputs are
# bit-identical to the unfused ≤2-dispatch path by construction.
#
# Programs are cached per static signature (which stages are present, each
# finalize's slice shape + out_cap, each cmd block's promote flag, the
# quorum size); every shape in the signature rides an existing tier ladder,
# so warm burns re-land on compiled entries.

_PROTOCOL_TICK_FNS: dict = {}


def _cmd_repair_body(status, flags, promised, accepted, execute_at,
                     durability, kmax, kvalid, rows_idx, st_v, fl_v, pr_v,
                     ab_v, ea_v, du_v, kid_idx, km_v, kv_v):
    """One CmdPlane's deferred-twin repair: scatter the host shadows'
    current values over the dirty rows/kids INSIDE the fused program, so
    the device-messages path retires the twin's flush debt without a
    standalone flush_lane dispatch. Idempotent by construction (a repair
    writes exactly what a flush would), so staleness is impossible;
    padding indices point one past the cap and drop."""
    status = status.at[rows_idx].set(st_v, mode="drop")
    flags = flags.at[rows_idx].set(fl_v, mode="drop")
    promised = promised.at[rows_idx].set(pr_v, mode="drop")
    accepted = accepted.at[rows_idx].set(ab_v, mode="drop")
    execute_at = execute_at.at[rows_idx].set(ea_v, mode="drop")
    durability = durability.at[rows_idx].set(du_v, mode="drop")
    kmax = kmax.at[kid_idx].set(km_v, mode="drop")
    kvalid = kvalid.at[kid_idx].set(kv_v, mode="drop")
    return (status, flags, promised, accepted, execute_at, durability,
            kmax, kvalid)


def _protocol_tick_fn(statics):
    fn = _PROTOCOL_TICK_FNS.get(statics)
    if fn is not None:
        return fn
    has_key, has_rng, fin_statics, cmd_promotes, qsize, has_mail, \
        n_repairs, exec_statics = statics
    # node_lane imports from this module -- resolve lazily (first call
    # always happens after the engine imported it)
    from accord_tpu.ops import node_lane as _nl
    from accord_tpu.ops.mailbox import _mailbox_route_body

    def run(witness_table, key_in, rng_in, fin_in, cmd_in, q_in,
            mail_in, rep_in, exec_in):
        packed = ()
        rng_out = ()
        if has_key:
            packed = _nl._key_resolve_body(*key_in, witness_table)
        if has_rng:
            rng_out = _nl._range_resolve_body(*rng_in, witness_table)
        fin_outs = []
        for spec, args in zip(fin_statics, fin_in):
            kind = spec[0]
            if kind == "range":
                (iv_of, iv_s, iv_e, ent_ok, f_sb, f_sknd,
                 (r_start, r_end, r_ts, r_kinds, r_valid)) = args
                fin_outs.append(_range_finalize_csr_body(
                    iv_of, iv_s, iv_e, ent_ok, f_sb, f_sknd,
                    r_start, r_end, r_ts, r_kinds, r_valid,
                    witness_table, spec[1]))
            else:
                _k, rows, words, out_cap = spec
                (r0, w_lo, word_off, kid_rows, slot_subj, slot_kid,
                 subj_row, act_ts) = args
                src = packed if kind == "key" else rng_out[1]
                blk = jax.lax.dynamic_slice(src, (r0, w_lo), (rows, words))
                fin_outs.append(_finalize_csr_body(
                    blk, word_off, kid_rows, slot_subj, slot_kid,
                    subj_row, act_ts, out_cap))
        cmd_outs = []
        for promote, args in zip(cmd_promotes, cmd_in):
            cmd_outs.append(_cmd_tick_body(*args, promote=promote))
        q_out = ()
        if qsize is not None:
            q_txn, q_ts, q_code, q_valid = q_in
            # a lane is a fast-path PreAccept witness iff it SUCCEEDED and
            # echoed the txn id unchanged (the host fastpath test)
            fast = q_valid & ((q_code & 7) == CMD_OUT_SUCCESS) \
                & jnp.all(q_ts == q_txn, axis=1)
            same = jnp.all(q_txn[:, None, :] == q_txn[None, :, :], axis=2)
            votes = jnp.sum(same & fast[None, :], axis=1, dtype=jnp.int32)
            q_out = (fast, votes, fast & (votes >= qsize))
        mail_out = ()
        if has_mail:
            mail_out = _mailbox_route_body(*mail_in)
        rep_outs = tuple(_cmd_repair_body(*rep_in[i])
                         for i in range(n_repairs))
        exec_outs = tuple(_frontier_compact_body(exec_in[i], oc)
                          for i, oc in enumerate(exec_statics))
        return (packed, rng_out, tuple(fin_outs), tuple(cmd_outs), q_out,
                mail_out, rep_outs, exec_outs)

    fn = jax.jit(run)
    _PROTOCOL_TICK_FNS[statics] = fn
    return fn


def protocol_tick(witness_table, key_in=None, rng_in=None, fins=(),
                  cmds=(), quorum=None, quorum_size=1, mailbox=None,
                  cmd_repairs=(), execs=()):
    """Launch the fused cluster-tick program: ONE device dispatch covering
    deps resolve, finalize compaction, cmd transitions, the fast-path
    quorum count, the device-message mailbox routing stage, and any
    CmdPlane repair scatters.

    key_in:  node_fused_deps_resolve's args minus witness_table, or None
    rng_in:  node_fused_range_deps_resolve's args minus witness_table
    fins:    finalize specs, one per (plan, group), in harvest order:
               ("key",  row_off, w_lo, rows, words, word_off, kid_rows,
                slot_subj, slot_kid, subj_row, act_ts, out_cap)
               ("rkey", ... same lanes, sliced from the k-side range output)
               ("range", iv_of, iv_s, iv_e, ent_ok, sb, sknd,
                rsnap 5-tuple, out_cap)
             key/rkey specs dynamic-slice their plan's [rows x words] span
             out of the merged packed result in-kernel, then run the exact
             finalize_csr body with the group's word offset -- slot_subj is
             plan-local, so recorded finalize lanes work unchanged.
    cmds:    cmd_tick arg tuples (every positional arg, promote last); the
             promote flag is static, everything else traced.
    quorum:  (txn i32[t,3], ts i32[t,3], code i32[t], valid bool[t]) lanes
             from the tick's PreAccept spans, padded to a MEGA_LANE_TIERS
             tier; quorum_size the electorate majority (static).
    mailbox: ops/mailbox.MailboxPlane.stage_batch's input tuple (arena,
             meta, emit lanes, partition mask) for the fused routing
             stage, or None.
    cmd_repairs: CmdPlane.collect_repair blocks (18 arrays each, see
             _cmd_repair_body) retiring deferred-twin flush debt in-kernel.
    execs:   execution-frontier compaction blocks, one per ExecCoordinator
             staging this tick: (planes, out_cap) where planes is the
             fused_execution_frontier lane-tuple tuple and out_cap the
             compaction tier (static). Outputs follow frontier_compact's
             contract (indptr, rows, csum, packed).
    -> (packed, (rpacked, kpacked), fin_outs, cmd_outs,
        (fast, votes, met), mail_out, rep_outs, exec_outs); absent stages
        return ().
    """
    fin_statics, fin_traced, order = _fin_split(fins)
    cmd_statics = tuple(bool(c[-1]) for c in cmds)
    cmd_traced = tuple(tuple(c[:-1]) for c in cmds)
    exec_statics = tuple(int(oc) for (_pl, oc) in execs)
    exec_traced = tuple(tuple(tuple(p) for p in pl) for (pl, _oc) in execs)
    statics = (key_in is not None, rng_in is not None, tuple(fin_statics),
               cmd_statics, int(quorum_size) if quorum is not None else None,
               mailbox is not None, len(cmd_repairs), exec_statics)
    fn = _protocol_tick_fn(statics)
    (packed, rng_out, fin_outs, cmd_outs, q_out, mail_out, rep_outs,
     exec_outs) = fn(
        witness_table,
        tuple(key_in) if key_in is not None else (),
        tuple(rng_in) if rng_in is not None else (),
        tuple(fin_traced), cmd_traced,
        tuple(quorum) if quorum is not None else (),
        tuple(mailbox) if mailbox is not None else (),
        tuple(tuple(r) for r in cmd_repairs),
        exec_traced)
    return (packed, rng_out, _fin_unsort(fin_outs, order), cmd_outs,
            q_out, mail_out, rep_outs, exec_outs)


def _fin_split(fins):
    """Split finalize specs into (static signature, traced args) and
    canonically stable-sort them by static signature, so the compiled
    program key depends on the tick's signature MULTISET, not the arrival
    order of plans -- order jitter across ticks would otherwise mint a
    fresh multi-second compile per permutation. Shared by protocol_tick
    and parallel.mesh.sharded_protocol_tick (same cache-key discipline on
    both paths). Returns (fin_statics, fin_traced, order); undo the sort
    on the outputs with _fin_unsort(fin_outs, order)."""
    fin_statics, fin_traced = [], []
    for f in fins:
        if f[0] == "range":
            fin_statics.append(("range", f[8]))
            fin_traced.append(tuple(f[1:8]))
        else:
            fin_statics.append((f[0], f[3], f[4], f[11]))
            fin_traced.append((f[1], f[2]) + tuple(f[5:11]))
    order = sorted(range(len(fin_statics)), key=lambda i: fin_statics[i])
    return ([fin_statics[i] for i in order],
            [fin_traced[i] for i in order], order)


def _fin_unsort(fin_outs, order):
    """Undo _fin_split's canonical sort: callers demux fin_outs
    positionally against the fins they passed in."""
    if order == list(range(len(order))):
        return tuple(fin_outs)
    back = [0] * len(order)
    for pos, i in enumerate(order):
        back[i] = pos
    return tuple(fin_outs[back[i]] for i in range(len(order)))


def protocol_tick_cache_sizes() -> int:
    """Total compiled protocol_tick variants across every static signature
    (the megakernel's entry in jit_cache_sizes)."""
    return sum(f._cache_size() for f in _PROTOCOL_TICK_FNS.values())


def jit_cache_sizes() -> dict:
    """Compiled-variant counts of the warmable hot-path kernels: the bench
    snapshots this around its timed windows to assert warmup() covered every
    jit tier the pipeline dispatches (0 recompiles while timing)."""
    return {
        "deps_resolve": deps_resolve._cache_size(),
        "range_deps_resolve": range_deps_resolve._cache_size(),
        "fused_deps_resolve": fused_deps_resolve._cache_size(),
        "fused_range_deps_resolve": fused_range_deps_resolve._cache_size(),
        "arena_scatter": arena_scatter._cache_size(),
        "arena_scatter_keys": arena_scatter_keys._cache_size(),
        "scatter_rows": scatter_rows._cache_size(),
        "range_scatter": range_scatter._cache_size(),
        "finalize_csr": finalize_csr._cache_size(),
        "range_finalize_csr": range_finalize_csr._cache_size(),
        "kid_word_scatter": kid_word_scatter._cache_size(),
        "fused_execution_frontier": fused_execution_frontier._cache_size(),
        "frontier_compact": frontier_compact._cache_size(),
        "recovery_scan": recovery_scan._cache_size(),
        "cmd_tick": cmd_tick._cache_size(),
        "protocol_tick": protocol_tick_cache_sizes(),
        # node-lane (cluster-on-mesh burn) kernels live in ops/node_lane,
        # which imports from this module -- resolve lazily to avoid a cycle
        **_node_lane_cache_sizes(),
        # likewise the sharded megakernel lives in parallel/mesh
        **_mesh_cache_sizes(),
    }


def _node_lane_cache_sizes() -> dict:
    import sys
    mod = sys.modules.get("accord_tpu.ops.node_lane")
    if mod is None:
        # not imported -> nothing compiled -> all zero (reported anyway so
        # bench deltas stay keyed consistently)
        return {"node_fused_deps_resolve": 0,
                "node_fused_range_deps_resolve": 0,
                "lane_slice": 0}
    return mod.node_lane_cache_sizes()


def _mesh_cache_sizes() -> dict:
    import sys
    mod = sys.modules.get("accord_tpu.parallel.mesh")
    if mod is None:
        return {"sharded_protocol_tick": 0}
    return {"sharded_protocol_tick": mod.sharded_protocol_tick_cache_sizes()}
