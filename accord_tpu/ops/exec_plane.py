"""The device execution scheduler: batched release of the execute-order DAG.

The host engine tracks, per command, the set of undecided/unapplied deps
gating its execution (WaitingOn; reference local/Command.java:1224) and walks
waiter lists on every dep transition (Commands.NotifyWaitingOn,
local/Commands.java:960). That walk is the hottest protocol loop. This plane
re-expresses the release test as a batched device computation: a per-store
arena holds each live txn's packed dep-adjacency row plus executeAt /
applied / pending / awaits-all lanes, and one `execution_frontier` kernel
call per tick returns the packed set of commands whose gates are all clear.

Modes:
  - primary: the plane is LOAD-BEARING -- the host wait-graph is still
    maintained (it is the differential oracle: every release asserts
    wo.is_done(), so a premature device release trips immediately under
    paranoia), but release scheduling comes exclusively from harvested
    frontiers. notify_listeners suppresses its own maybe_execute scheduling.
  - off (store.exec_plane is None): host walk schedules releases as before.

Determinism: ticks and harvests are scheduler events; dirty-row uploads and
frontier decodes are pure functions of store state at the tick; release
order is ascending row index. The async dispatch/harvest split mirrors
ops/resolver.py's pipeline (enqueue + copy_to_host_async at dispatch; the
blocking read happens `device_latency_ms` of simulated time later).
"""
from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional

import numpy as np

from accord_tpu.local.status import Status
from accord_tpu.obs.metrics import (CounterDict, MetricsRegistry, RegCounter,
                                    RegTimer)
from accord_tpu.obs.trace import REC, node_pid, node_ts
from accord_tpu.ops.encoding import TimestampEncoder
from accord_tpu.primitives.timestamp import Timestamp, TxnId
from accord_tpu.utils.invariants import Invariants

_NEG = np.iinfo(np.int32).min


class ExecPlane:
    """One per CommandStore (the wait graph is per-store state)."""

    GROW = 2

    # bench/diagnostic counters -- registry-backed descriptors (obs/metrics):
    # legacy attribute reads/writes proxy onto self.metrics unchanged
    dispatches = RegCounter("exec.dispatches")
    releases = RegCounter("exec.releases")
    harvest_stall_s = RegTimer("exec.harvest_stall_s")
    prefetched = RegCounter("exec.prefetched")
    upload_bytes = RegCounter("exec.upload_bytes")
    upload_bytes_full_equiv = RegCounter("exec.upload_bytes_full_equiv")
    # frontiers dropped on the gen mismatch (compaction raced an in-flight
    # readback) -- previously swallowed silently
    dropped_frontiers = RegCounter("exec.dropped_frontiers")
    # compacted-harvest accounting: bytes the harvest actually fetched vs
    # what the full-bitmask readback would have cost for the same dispatch
    # (the PR 4 upload-accounting pattern, readback side)
    readback_bytes = RegCounter("exec.readback_bytes")
    readback_full_equiv = RegCounter("exec.readback_full_equiv")
    compact_fallbacks = RegCounter("exec.compact_fallbacks")
    compact_overflows = RegCounter("exec.compact_overflows")

    def __init__(self, store, initial_cap: int = 1024,
                 tick_ms: float = 2.0, device_latency_ms: float = 4.0,
                 compact: bool = False):
        self.metrics = MetricsRegistry()
        self.store = store
        self.cap = initial_cap
        self.count = 0
        self.tick_ms = tick_ms
        self.device_latency_ms = device_latency_ms
        # compacted harvests: the dispatch runs frontier_compact and the
        # harvest fetches only (indptr, rows, csum) -- O(released) bytes --
        # with the full bitmask retained on device for the counted
        # checksum-mismatch / overflow fallbacks
        self.compact = bool(compact)
        self._out_tiers = None   # OutCapTiers, built lazily on first pick
        # per-node fused dispatch (ExecCoordinator.register sets this):
        # ticks route to the coordinator, which answers every store's
        # frontier with ONE device call per node tick
        self.coordinator: Optional["ExecCoordinator"] = None
        self.row_of: Dict[TxnId, int] = {}
        self.txn_ids: List[TxnId] = []
        self.encoder: Optional[TimestampEncoder] = None
        # host shadows (authoritative until scattered)
        self.adj = np.zeros((self.cap, self.cap // 32), dtype=np.uint32)
        self.exec_ts = np.full((self.cap, 3), _NEG, dtype=np.int32)
        self.applied = np.zeros(self.cap, dtype=bool)
        self.pending = np.zeros(self.cap, dtype=bool)
        self.awaits_all = np.zeros(self.cap, dtype=bool)
        # per-field dirty sets (same scheme as the resolver arenas): `full`
        # rows re-ship every lane (new rows, stable ingests, edge rewrites);
        # ts/flags rows ship just that lane group via the shared flush_lane
        # helper -- an executeAt bump no longer re-uploads a cap/8-byte
        # adjacency row
        self._dirty_full: set = set()
        self._dirty_ts: set = set()
        self._dirty_flags: set = set()
        self._device = None
        self._ticking = False
        self._gen = 0   # bumped by compaction: retires in-flight frontiers
                        # whose row indices refer to the old mapping
        self._compacting = False
        self._released: set = set()   # rows released (guard double release)
        # in-order queue of in-flight frontier readbacks: [frontier,
        # host copy or None, gen]; each dispatch schedules one harvest,
        # which pops the head (mirrors ops/resolver.py's pipeline)
        self._inflight: deque = deque()
        self._poll_armed = False
        # field-granular accounting, mirroring the resolver arenas:
        # upload_bytes == sum of the by-field buckets; full_equiv is what
        # the retired whole-row scheme would have shipped for the same
        # dirty sets (the baseline proving the granular deltas' win)
        self.upload_bytes_by_field = CounterDict(
            self.metrics, "exec.upload_bytes", ("full", "ts", "flags"))

    def snapshot(self) -> dict:
        return self.metrics.snapshot()

    # -- row management ------------------------------------------------------
    def _row(self, txn_id: TxnId) -> int:
        row = self.row_of.get(txn_id)
        if row is not None:
            return row
        if self.encoder is None:
            self.encoder = TimestampEncoder(0, txn_id.hlc)
        if self.count == self.cap:
            self._grow()
        row = self.count
        self.count += 1
        self.row_of[txn_id] = row
        self.txn_ids.append(txn_id)
        self._dirty_full.add(row)
        return row

    def _ensure_capacity(self, n: int) -> None:
        """Make room for `n` new rows BEFORE an ingestion allocates them:
        compaction remaps (and may drop) existing rows, so it must never run
        between an ingestion's allocations and its writes. Prefers
        reclaiming dead history (rows stay live only while pending or
        referenced by a pending wait set) over growing."""
        if self.cap - self.count >= n:
            return
        if self._compacting or not self._compact():
            while self.cap - self.count < n:
                self._grow()

    def _live_set(self) -> List[TxnId]:
        """Pending commands plus every dep their wait sets still reference
        (everything else is settled history that can never gate again)."""
        store = self.store
        live: List[TxnId] = []
        seen = set()
        for row in np.nonzero(self.pending[:self.count])[0].tolist():
            tid = self.txn_ids[row]
            cmd = store.command_if_present(tid)
            if cmd is None:
                continue
            if tid not in seen:
                seen.add(tid)
                live.append(tid)
            wo = cmd.waiting_on
            if wo is not None:
                for dep in wo.commit | wo.apply:
                    if dep not in seen:
                        seen.add(dep)
                        live.append(dep)
        return live

    def _compact(self) -> bool:
        """Rebuild the arena keeping only live rows. Returns False when
        compaction would not reclaim at least half the capacity -- the
        caller grows instead. Rebuilding from the host wait-graph (the
        oracle) is exact: edges, lanes and flags are re-derived from
        current command state."""
        self._compacting = True
        live = self._live_set()
        if len(live) > self.cap // 2:
            self._compacting = False
            return False
        self._rebuild(live)
        self._compacting = False
        return True

    def _ensure_window(self, ts) -> None:
        """Guard before encode(): executeAt hlc drifts past the encoder's
        int32 window (~2^31 us, ~35 simulated minutes) on long-running
        stores; re-base via a forced rebuild rather than raising inside
        on_stable/on_status (the resolver guards this case the same way)."""
        if ts is None or self.encoder is None or self.encoder.in_window(ts):
            return
        if self._compacting:
            return  # the in-progress rebuild already re-bases
        self._compacting = True
        self._rebuild(self._live_set(), extra_base=ts)
        self._compacting = False

    def _encode(self, ts):
        """All hook-path encodes go through here: a compaction triggered by
        _ensure_capacity can re-base the encoder AFTER _ensure_window ran
        (the incoming command is not yet in the live set), so the window is
        re-verified at the encode itself. A live-set spread exceeding the
        int32 window (~35 simulated minutes between the oldest wedged
        executeAt and this one) cannot be encoded at any base: fail with a
        diagnostic rather than an opaque ValueError."""
        Invariants.check_state(
            self.encoder is not None and self.encoder.in_window(ts),
            "exec plane live window exceeds encoder range at %s "
            "(oldest live executeAt is >2^31us behind; a dep is wedged)", ts)
        return self.encoder.encode([ts])[0]

    def _rebuild(self, live: List[TxnId], extra_base=None) -> None:
        """Reset and re-ingest `live`; always re-bases the encoder to the
        minimum live executeAt (encodings are base-relative and the live
        window drifts forward over the store's lifetime)."""
        store = self.store
        base = extra_base
        for tid in live:
            cmd = store.command_if_present(tid)
            ts = cmd.execute_at if cmd is not None else None
            for cand in (ts, tid.as_timestamp()):
                if cand is not None and (base is None or cand < base):
                    base = cand
        if base is not None:
            self.encoder = TimestampEncoder(base.epoch, base.hlc)
        self.count = 0
        self.row_of = {}
        self.txn_ids = []
        self.adj[:] = 0
        self.exec_ts[:] = _NEG
        self.applied[:] = False
        self.pending[:] = False
        self.awaits_all[:] = False
        self._released = set()
        self._device = None
        self._dirty_full = set()
        self._dirty_ts = set()
        self._dirty_flags = set()
        self._gen += 1
        for tid in live:
            row = self._row(tid)
            cmd = store.command_if_present(tid)
            if cmd is None or cmd.has_been(Status.APPLIED) \
                    or cmd.status.is_terminal:
                self.applied[row] = True
                continue
            if cmd.known_execute_at and cmd.execute_at is not None:
                self.exec_ts[row] = self._encode(cmd.execute_at)
        for tid in live:
            cmd = store.command_if_present(tid)
            if cmd is not None and cmd.has_been(Status.STABLE) \
                    and not cmd.status.is_terminal \
                    and not cmd.has_been(Status.APPLIED):
                self.on_stable(cmd)

    def _grow(self) -> None:
        old_cap = self.cap
        self.cap *= self.GROW
        self.adj = np.pad(self.adj, ((0, self.cap - old_cap),
                                     (0, (self.cap - old_cap) // 32)))
        self.exec_ts = np.pad(self.exec_ts, ((0, self.cap - old_cap), (0, 0)),
                              constant_values=_NEG)
        self.applied = np.pad(self.applied, (0, self.cap - old_cap))
        self.pending = np.pad(self.pending, (0, self.cap - old_cap))
        self.awaits_all = np.pad(self.awaits_all, (0, self.cap - old_cap))
        # column width changed: the device copy must be rebuilt wholesale
        self._device = None

    # -- hooks from the engine (commands.py) ---------------------------------
    def on_stable(self, cmd) -> None:
        """A command became STABLE: ingest its wait edges and pending flag.
        Called after _init_waiting_on built the (floor-elided) edge set.

        All rows are allocated BEFORE any write: _row can trigger a
        compaction that remaps every index, so an index held across an
        allocation would be stale."""
        self._ensure_window(cmd.execute_at)
        wo = cmd.waiting_on
        dep_ids = tuple(wo.commit | wo.apply) if wo is not None else ()
        self._ensure_capacity(1 + len(dep_ids))
        self._row(cmd.txn_id)
        for dep_id in dep_ids:
            self._row(dep_id)
        row = self.row_of[cmd.txn_id]
        self.awaits_all[row] = cmd.txn_id.kind.awaits_only_deps
        if cmd.execute_at is not None:
            self.exec_ts[row] = self._encode(cmd.execute_at)
        self.adj[row] = 0
        for dep_id in dep_ids:
            d = self.row_of[dep_id]
            self.adj[row, d >> 5] |= np.uint32(1 << (d & 31))
        self.pending[row] = True
        self._released.discard(row)
        self._dirty_full.add(row)
        self._schedule_tick()

    def on_status(self, cmd) -> None:
        """A command's status advanced (it may gate others): refresh its
        dep-side lanes. Delta-aware: a hook that changes no lane (repeated
        status bumps between ticks are common) dirties nothing, so the next
        dispatch uploads only genuinely-changed rows."""
        if cmd.known_execute_at:
            self._ensure_window(cmd.execute_at)
        row = self.row_of.get(cmd.txn_id)
        if row is None:
            return
        changed = False
        if cmd.known_execute_at and cmd.execute_at is not None:
            enc = self._encode(cmd.execute_at)
            if not np.array_equal(self.exec_ts[row], enc):
                self.exec_ts[row] = enc
                self._dirty_ts.add(row)
                changed = True
        if cmd.has_been(Status.APPLIED) or cmd.status.is_terminal:
            if not self.applied[row] or self.pending[row]:
                self.applied[row] = True
                self.pending[row] = False
                self._dirty_flags.add(row)
                changed = True
        if changed:
            self._schedule_tick()

    def on_edges_changed(self, cmd) -> None:
        """Floor/ownership elision rewrote the wait set: resync the row.
        (Rows allocated before writes -- see on_stable.)"""
        if cmd.txn_id not in self.row_of:
            return
        wo = cmd.waiting_on
        dep_ids = ()
        if wo is not None and not wo.is_done():
            dep_ids = tuple(wo.commit | wo.apply)
            self._ensure_capacity(len(dep_ids))
            for dep_id in dep_ids:
                self._row(dep_id)
        row = self.row_of.get(cmd.txn_id)
        if row is None:
            return  # compaction dropped it (no longer pending/referenced)
        new_adj = np.zeros_like(self.adj[row])
        for dep_id in dep_ids:
            d = self.row_of[dep_id]
            new_adj[d >> 5] |= np.uint32(1 << (d & 31))
        if np.array_equal(new_adj, self.adj[row]):
            return  # elision rewrote to the same edges: nothing to upload
        self.adj[row] = new_adj
        self._dirty_full.add(row)
        self._schedule_tick()

    def on_erased(self, txn_id: TxnId) -> None:
        row = self.row_of.get(txn_id)
        if row is None or (self.applied[row] and not self.pending[row]):
            return
        self.applied[row] = True   # an erased record gates nothing
        self.pending[row] = False
        self._dirty_flags.add(row)
        self._schedule_tick()

    # -- the tick/harvest pipeline -------------------------------------------
    def _schedule_tick(self) -> None:
        if self.coordinator is not None:
            self.coordinator.schedule()
            return
        if self._ticking:
            return
        self._ticking = True
        self.store.node.scheduler.once(self.tick_ms, self._tick)

    def _needs_dispatch(self) -> bool:
        """The tick's launch gate: something pending AND either dirty state
        to sync or no device copy yet (an unchanged arena's frontier was
        already harvested; the next on_* hook re-arms the tick)."""
        if not self.pending.any():
            return False
        return bool(self._dirty_full or self._dirty_ts or self._dirty_flags) \
            or self._device is None

    def _tick(self) -> None:
        self._ticking = False
        if not self._needs_dispatch():
            return
        self._dispatch()   # appends its own in-flight entry
        self.store.node.scheduler.once(self.device_latency_ms, self._harvest)
        self._ensure_poll()

    def _pick_out_cap(self) -> int:
        """Pin the compaction tier for this dispatch: hysteresis over the
        device-observed release counts, seeded with the pending-row count
        (an exact upper bound) while cold."""
        if self._out_tiers is None:
            from accord_tpu.ops.kernels import FRONTIER_OUT_TIERS
            from accord_tpu.ops.tiers import OutCapTiers
            self._out_tiers = OutCapTiers(FRONTIER_OUT_TIERS,
                                          FRONTIER_OUT_TIERS[-1] * 2)
        pend = int(self.pending.sum())
        est = self._out_tiers.estimate(1)
        return self._out_tiers.pick(est if est is not None else max(1, pend))

    def _observe_bound(self, total: int) -> None:
        if self._out_tiers is not None:
            self._out_tiers.observe(total, 1)

    def _ensure_poll(self) -> None:
        """Between dispatch and harvest, a cheap deterministic poll drains
        finished async readbacks via the non-blocking is_ready() probe; it
        only fills the in-flight entries' host-copy slot (invisible to
        simulated state), so determinism is untouched -- see
        sim/scheduler.py poll()."""
        scheduler = self.store.node.scheduler
        poll = getattr(scheduler, "poll", None)
        # opt-in via node.device_poll_ms, as in resolver._ensure_poll
        interval = getattr(self.store.node, "device_poll_ms", None)
        if poll is None or interval is None or self._poll_armed:
            return
        self._poll_armed = True
        q = self._inflight

        def prefetch() -> bool:
            _poll_prefetch(q)
            if q:
                return True
            self._poll_armed = False
            return False

        poll(interval, prefetch)

    def _full_row_bytes(self, m: int) -> int:
        """Bytes one whole-row exec_scatter chunk of tier m ships: row index
        + packed adjacency + exec_ts + applied/pending/awaits flags."""
        return m * (4 + self.cap // 8 + 12 + 3)

    def _dispatch(self) -> None:
        """Solo (uncoordinated) launch: sync dirty rows, fire the frontier
        kernel (compacted or legacy bitmask), enqueue its async readback."""
        from accord_tpu.ops.kernels import (execution_frontier,
                                            frontier_compact)
        devs = self._sync_device()
        if self.compact:
            out_cap = self._pick_out_cap()
            res = frontier_compact((tuple(devs),), out_cap=out_cap)
            for lane in res[:3]:
                lane.copy_to_host_async()
            self._inflight.append([res, None, self._gen, out_cap])
        else:
            out = execution_frontier(*devs)
            out.copy_to_host_async()
            self._inflight.append([out, None, self._gen])
        self.dispatches += 1
        if REC.enabled:
            node = self.store.node
            REC.instant(node_pid(node), "exec", "frontier_dispatch",
                        node_ts(node), args={"rows": self.count,
                                             "compact": self.compact})

    def _sync_device(self):
        """Flush the dirty sets into the device arena and return its lane
        tuple (adj, exec_ts, applied, pending, awaits_all) -- the shared
        front half of the solo dispatch and the coordinator's fused one."""
        import jax.numpy as jnp
        from accord_tpu.ops.deltas import (LANE_ROW_TIERS, flush_lane,
                                           lane_row_tier)
        from accord_tpu.ops.kernels import exec_scatter
        if self._device is None:
            # the device adjacency lives UNPACKED (bool[cap, cap]); build it
            # by scattering every populated row's PACKED form -- the upload
            # stays cap/8 bytes per row and the device does the expansion
            self._device = (
                jnp.zeros((self.cap, self.cap), bool),
                jnp.full((self.cap, 3), _NEG, jnp.int32),
                jnp.zeros(self.cap, bool), jnp.zeros(self.cap, bool),
                jnp.zeros(self.cap, bool))
            self._dirty_full = set(range(self.count))
            self._dirty_ts.clear()
            self._dirty_flags.clear()
        if self._dirty_full:
            # the full upload carries every lane: granular marks on the
            # same rows are satisfied by it
            self._dirty_ts -= self._dirty_full
            self._dirty_flags -= self._dirty_full
            full = sorted(self._dirty_full)
            step = LANE_ROW_TIERS[-1]
            for lo in range(0, len(full), step):
                chunk = full[lo:lo + step]
                # pad to the shared 8/64 row tiers by repeating the first
                # row (duplicate scatter indexes write identical data), so
                # dirty-count drift never mints a new compiled shape
                m = lane_row_tier(len(chunk))
                rows = np.full(m, chunk[0], dtype=np.int32)
                rows[:len(chunk)] = chunk
                # fancy-indexed selections below COPY, so the async
                # computation never aliases the live host shadows (zero-copy
                # aliasing on the CPU backend raced host mutations and broke
                # determinism)
                uploads = (rows, self.adj[rows], self.exec_ts[rows],
                           self.applied[rows], self.pending[rows],
                           self.awaits_all[rows])
                nb = sum(u.nbytes for u in uploads)
                self.upload_bytes += nb
                self.upload_bytes_by_field["full"] += nb
                self.upload_bytes_full_equiv += nb
                self._device = exec_scatter(
                    *self._device, *(jnp.asarray(u) for u in uploads))
            self._dirty_full.clear()
        if self._dirty_ts or self._dirty_flags:
            # all-lanes baseline FIRST, over the union of granular rows
            # chunked exactly like the whole-row scheme would have
            union = sorted(self._dirty_ts | self._dirty_flags)
            step = LANE_ROW_TIERS[-1]
            for lo in range(0, len(union), step):
                self.upload_bytes_full_equiv += self._full_row_bytes(
                    lane_row_tier(len(union[lo:lo + step])))
            d = list(self._device)

            def acct(field):
                def on_chunk(nbytes: int, _m: int) -> None:
                    self.upload_bytes += nbytes
                    self.upload_bytes_by_field[field] += nbytes
                return on_chunk

            d[1] = flush_lane(d[1], sorted(self._dirty_ts), self.exec_ts,
                              acct("ts"))
            self._dirty_ts.clear()
            flags = sorted(self._dirty_flags)
            d[2] = flush_lane(d[2], flags, self.applied, acct("flags"))
            d[3] = flush_lane(d[3], flags, self.pending, acct("flags"))
            self._dirty_flags.clear()
            self._device = tuple(d)
        return self._device

    def _harvest(self) -> None:
        import time as _time
        if not self._inflight:
            return  # defensive: every dispatch schedules exactly one harvest
        entry = self._inflight.popleft()
        if len(entry) == 4:   # compacted dispatch
            res, host, gen, out_cap = entry
            if host is None:
                t0 = _time.perf_counter()
                host = tuple(np.asarray(lane) for lane in res[:3])
                self.harvest_stall_s += _time.perf_counter() - t0
            else:
                self.prefetched += 1
            w = int(res[3].shape[0])
            _consume_compact(self, res, host, [(self, (0, w), gen)], out_cap)
            return
        frontier, packed, gen = entry
        if packed is None:
            t0 = _time.perf_counter()
            packed = np.asarray(frontier)
            self.harvest_stall_s += _time.perf_counter() - t0
        else:
            self.prefetched += 1
        self.readback_bytes += packed.nbytes
        self.readback_full_equiv += packed.nbytes
        self._apply_frontier(packed, gen)

    def _drop_frontier(self, gen: int, rows: int) -> None:
        """The gen-mismatch drop path: compaction remapped rows while this
        frontier was in flight; its indices address the old arena -- drop
        it (the rebuild re-ingested every pending row, so a fresh tick
        re-covers them). Counted + recorded so compaction races are
        visible instead of silently swallowed."""
        self.dropped_frontiers += 1
        if REC.enabled:
            node = self.store.node
            REC.instant(node_pid(node), "exec", "dropped_frontier",
                        node_ts(node),
                        args={"gen": gen, "live_gen": self._gen,
                              "rows": rows})
        self._schedule_tick()

    def _apply_frontier(self, packed: np.ndarray, gen: int) -> None:
        """Legacy bitmask decode (the back half of the harvest, shared with
        the coordinator, which hands each plane its word span of the fused
        readback): unpack + nonzero walk, then the shared release loop."""
        if gen != self._gen:
            self._drop_frontier(gen, -1)
            return
        rows = np.nonzero(
            np.unpackbits(packed.view(np.uint8), bitorder="little"))[0]
        self._apply_rows(rows.tolist(), gen)

    def _apply_rows(self, rows, gen: int) -> None:
        """Release every listed arena row against current host state.
        `rows` arrive ascending -- the exact order the bitmask decode
        produced -- so compacted and legacy harvests release identically."""
        from accord_tpu.local import commands as _commands
        if gen != self._gen:
            self._drop_frontier(gen, len(rows))
            return
        store = self.store
        for row in rows:
            if row >= self.count or row in self._released \
                    or not self.pending[row]:
                continue
            cmd = store.command_if_present(self.txn_ids[row])
            if cmd is None:
                continue
            # differential oracle: the host wait-graph must agree that this
            # command is releasable -- a premature device release is a bug
            Invariants.check_state(
                cmd.waiting_on is None or cmd.waiting_on.is_done(),
                "device frontier released %s before host WaitingOn drained: %s",
                cmd.txn_id, cmd.waiting_on)
            self._released.add(row)
            self.releases += 1
            _commands.maybe_execute(store, cmd)
        if self.pending.any():
            self._schedule_tick()


class ExecTicket:
    """A staged exec block awaiting the engine's next fused protocol_tick.
    The coordinator holds one in place of a launched frontier_compact
    result; the cluster engine fulfills `.result` with the block's
    (indptr, rows, csum, packed) output at its next megakernel launch, or
    at an exec-only flush tick if the coordinator's harvest comes due
    first. Purely a host-side rendezvous -- the device computation is the
    same _frontier_compact_body either way, so fused and standalone
    harvests release bit-identically."""

    __slots__ = ("planes", "out_cap", "result")

    def __init__(self, planes, out_cap: int):
        self.planes = planes
        self.out_cap = out_cap
        self.result = None


def _fetch_compact(res):
    """Fetch a compacted result's (indptr, rows, csum) host copies; the
    retained packed bitmask (res[3]) stays on device."""
    return tuple(np.asarray(lane) for lane in res[:3])


def _poll_prefetch(q) -> None:
    """Drain finished async readbacks into the in-flight entries' host-copy
    slots via the non-blocking is_ready() probe (shared by the plane and
    coordinator poll loops). Compact entries fetch only their three
    compacted lanes; engine tickets wait until the fused launch fulfilled
    them."""
    for entry in q:
        if entry[1] is not None:
            continue
        obj = entry[0]
        if isinstance(obj, ExecTicket):
            obj = obj.result
            if obj is None:
                break   # awaiting the engine's next fused launch
        if isinstance(obj, tuple):
            if not all(lane.is_ready() for lane in obj[:3]):
                break   # single device stream: later calls finish later
            entry[1] = _fetch_compact(obj)
            continue
        if not obj.is_ready():
            break  # single device stream: later calls finish later
        entry[1] = np.asarray(obj)


def _consume_compact(owner, res, host, entries, out_cap: int) -> None:
    """Decode one compacted frontier readback and release per plane.
    `owner` carries the readback counters and out-cap policy (the plane
    itself on the solo path, the coordinator on the fused one); `entries`
    is [(plane, (w_lo, w_hi), gen)] with per-plane word spans into the
    retained packed bitmask, one compaction segment per plane in order."""
    from accord_tpu.ops.kernels import frontier_checksum_host
    indptr, rows, csum = host
    total = int(indptr[-1])
    full_w = sum(hi - lo for _p, (lo, hi), _g in entries)
    owner.readback_full_equiv += full_w * 4
    owner.readback_bytes += indptr.nbytes + rows.nbytes + 4
    bad = frontier_checksum_host(indptr, rows) != int(csum)
    if bad or total > out_cap:
        # a corrupt readback, or more releases than the pinned tier holds
        # (indptr is exact either way: the overflow bumps straight to a
        # fitting rung) -- fall back to the legacy decode of the retained
        # device bitmask. The release set is identical, so chaos and
        # --reconcile stay bit-identical through the degradation.
        if bad:
            owner.compact_fallbacks += 1
        else:
            owner.compact_overflows += 1
            owner._observe_bound(total)
            if owner._out_tiers is not None:
                owner._out_tiers.overflowed()
        packed = np.asarray(res[3])
        owner.readback_bytes += packed.nbytes
        for plane, (lo, hi), gen in entries:
            plane._apply_frontier(packed[lo:hi], gen)
        return
    owner._observe_bound(total)
    for i, (plane, (lo, hi), gen) in enumerate(entries):
        seg = rows[indptr[i]:indptr[i + 1]] - 32 * lo
        plane._apply_rows(seg.tolist(), gen)


class ExecCoordinator:
    """Per-NODE fusion of the exec planes' frontier calls, mirroring the
    resolver's cross-store fused dispatch: each node tick collects every
    registered plane with work, syncs their dirty rows, and answers all of
    them with ONE device call -- the plain kernel for a single participant
    (byte-identical to the solo path), `fused_execution_frontier` with
    per-store word spans otherwise. Cuts per-tick launch count on
    many-store nodes from stores-with-work to one."""

    # registry-backed counters (see ExecPlane's descriptor block)
    dispatches = RegCounter("exec_coord.dispatches")
    fused_dispatches = RegCounter("exec_coord.fused_dispatches")
    harvest_stall_s = RegTimer("exec_coord.harvest_stall_s")
    prefetched = RegCounter("exec_coord.prefetched")
    staged_blocks = RegCounter("exec_coord.staged_blocks")
    readback_bytes = RegCounter("exec_coord.readback_bytes")
    readback_full_equiv = RegCounter("exec_coord.readback_full_equiv")
    compact_fallbacks = RegCounter("exec_coord.compact_fallbacks")
    compact_overflows = RegCounter("exec_coord.compact_overflows")

    def __init__(self, node, tick_ms: float = 2.0,
                 device_latency_ms: float = 4.0, compact: bool = False):
        self.metrics = MetricsRegistry()
        self.node = node
        self.tick_ms = tick_ms
        self.device_latency_ms = device_latency_ms
        self.compact = bool(compact)
        self._out_tiers = None
        self.planes: List[ExecPlane] = []
        self._ticking = False
        # [fused frontier | compact result | ExecTicket, host copy or None,
        #  [(plane, (lo, hi), gen)], out_cap (compact entries only)]
        self._inflight: deque = deque()
        self._poll_armed = False

    def snapshot(self) -> dict:
        return self.metrics.snapshot()

    def register(self, plane: ExecPlane) -> None:
        plane.coordinator = self
        self.planes.append(plane)

    def _engine(self):
        """The cluster tick engine, when this node rides a megakernel burn
        with exec fusion enabled: the compact block then STAGES into the
        engine's next protocol_tick instead of launching standalone, so
        exec traffic shares the cluster tick's single device call. Resolved
        lazily per tick -- the engine adopts resolvers after node wiring."""
        if not self.compact:
            return None
        res = getattr(self.node, "_deps_resolver", None)
        eng = getattr(res, "tick_driver", None) if res is not None else None
        return eng if getattr(eng, "exec_in_megakernel", False) else None

    def _observe_bound(self, total: int) -> None:
        if self._out_tiers is not None:
            self._out_tiers.observe(total, 1)

    def _pick_out_cap(self, parts) -> int:
        if self._out_tiers is None:
            from accord_tpu.ops.kernels import FRONTIER_OUT_TIERS
            from accord_tpu.ops.tiers import OutCapTiers
            self._out_tiers = OutCapTiers(FRONTIER_OUT_TIERS,
                                          FRONTIER_OUT_TIERS[-1] * 2)
        pend = sum(int(p.pending.sum()) for p in parts)
        est = self._out_tiers.estimate(1)
        return self._out_tiers.pick(est if est is not None else max(1, pend))

    def schedule(self) -> None:
        if self._ticking:
            return
        self._ticking = True
        self.node.scheduler.once(self.tick_ms, self._tick)

    def _tick(self) -> None:
        from accord_tpu.ops.kernels import (execution_frontier,
                                            frontier_compact,
                                            fused_execution_frontier)
        self._ticking = False
        parts = [p for p in self.planes if p._needs_dispatch()]
        if not parts:
            return
        devs = [p._sync_device() for p in parts]
        spans, off = [], 0
        for p in parts:
            spans.append((off, off + p.cap // 32))
            off += p.cap // 32
        if self.compact:
            out_cap = self._pick_out_cap(parts)
            planes_in = tuple(tuple(d) for d in devs)
            engine = self._engine()
            if engine is not None:
                # ride the cluster tick's single launch: the engine folds
                # this block into its next fused protocol_tick (or an
                # exec-only flush tick if our harvest comes due first)
                out = engine.stage_exec(planes_in, out_cap, self.node)
                self.staged_blocks += 1
            else:
                out = frontier_compact(planes_in, out_cap=out_cap)
                for lane in out[:3]:
                    lane.copy_to_host_async()
            entry = [out, None,
                     [(p, s, p._gen) for p, s in zip(parts, spans)],
                     out_cap]
        else:
            if len(parts) == 1:
                out = execution_frontier(*devs[0])
            else:
                out = fused_execution_frontier(tuple(devs))
            out.copy_to_host_async()
            entry = [out, None,
                     [(p, s, p._gen) for p, s in zip(parts, spans)]]
        if len(parts) > 1:
            self.fused_dispatches += 1
        self.dispatches += 1
        for p in parts:
            p.dispatches += 1
        if REC.enabled:
            REC.instant(node_pid(self.node), "exec", "frontier_dispatch",
                        node_ts(self.node),
                        args={"stores": len(parts),
                              "fused": len(parts) > 1,
                              "compact": self.compact})
        self._inflight.append(entry)
        self.node.scheduler.once(self.device_latency_ms, self._harvest)
        self._ensure_poll()

    def _ensure_poll(self) -> None:
        scheduler = self.node.scheduler
        poll = getattr(scheduler, "poll", None)
        interval = getattr(self.node, "device_poll_ms", None)
        if poll is None or interval is None or self._poll_armed:
            return
        self._poll_armed = True
        q = self._inflight

        def prefetch() -> bool:
            _poll_prefetch(q)
            if q:
                return True
            self._poll_armed = False
            return False

        poll(interval, prefetch)

    def _harvest(self) -> None:
        import time as _time
        if not self._inflight:
            return  # defensive: every dispatch schedules exactly one harvest
        entry = self._inflight.popleft()
        if len(entry) == 4:   # compacted dispatch (standalone or staged)
            obj, host, entries, out_cap = entry
            res = obj
            if isinstance(obj, ExecTicket):
                if obj.result is None:
                    # no cluster tick fired between our dispatch and this
                    # harvest: the engine flushes the queued blocks as one
                    # exec-only fused tick (its launch ledger keeps
                    # launches_per_tick == 1.0 by construction)
                    self._engine_flush()
                res = obj.result
                if res is None:
                    # defensive: the engine vanished mid-flight -- run the
                    # identical block standalone (same body, same result)
                    from accord_tpu.ops.kernels import frontier_compact
                    res = obj.result = frontier_compact(
                        obj.planes, out_cap=out_cap)
            if host is None:
                t0 = _time.perf_counter()
                host = _fetch_compact(res)
                self.harvest_stall_s += _time.perf_counter() - t0
            else:
                self.prefetched += 1
            _consume_compact(self, res, host, entries, out_cap)
            return
        frontier, packed, entries = entry
        if packed is None:
            t0 = _time.perf_counter()
            packed = np.asarray(frontier)
            self.harvest_stall_s += _time.perf_counter() - t0
        else:
            self.prefetched += 1
        self.readback_bytes += packed.nbytes
        self.readback_full_equiv += packed.nbytes
        for plane, (lo, hi), gen in entries:
            plane._apply_frontier(packed[lo:hi], gen)

    def _engine_flush(self) -> None:
        res = getattr(self.node, "_deps_resolver", None)
        eng = getattr(res, "tick_driver", None) if res is not None else None
        if eng is not None:
            eng.flush_exec()
