"""Shared field-granular delta-upload helper.

Every device mirror in this codebase (the resolver's key/range arenas and
the exec plane's wait-graph arena) keeps authoritative host shadows and
ships only dirty rows to the device. For single-lane deltas (an exec-ts
bump, a valid flip, an applied/pending flag change) they all follow the
same shape discipline: sort the dirty rows, chunk them to the 8/64 row
tiers the generic `scatter_rows` kernel is warmed for, pad a short chunk
by repeating its first row (duplicate scatter indexes write identical
data, so double writes are harmless), and account the shipped bytes.

This module is that discipline, written once -- so the arena and the exec
plane cannot drift apart on chunking, padding, or accounting, and new jit
tiers cannot appear inside a bench's timed window because one caller chose
a different chunk bound.
"""
from __future__ import annotations

from typing import Callable, Sequence

import numpy as np

from accord_tpu.obs.trace import REC

# the warmable row tiers every lane delta chunks to (see kernels.scatter_rows
# and resolver.warmup)
LANE_ROW_TIERS = (8, 64)


def lane_row_tier(n: int) -> int:
    """Smallest warmed row tier holding `n` rows (n <= 64 by chunking)."""
    from accord_tpu.ops.tiers import snap
    return snap(n, LANE_ROW_TIERS, LANE_ROW_TIERS[-1])


def flush_lane(lane, rows: Sequence[int], src: np.ndarray,
               on_chunk: Callable[[int, int], None]):
    """Scatter `src[rows]` into the device array `lane` row-wise and return
    the updated lane. `rows` must be sorted dirty row indices; `src` is the
    host shadow the rows are gathered from (fancy indexing COPIES, so the
    async device computation never aliases live host state). `on_chunk`
    receives (uploaded_bytes, padded_row_tier) per chunk for the caller's
    upload accounting."""
    if not rows:
        return lane
    import jax.numpy as jnp
    from accord_tpu.ops.kernels import scatter_rows
    for lo in range(0, len(rows), LANE_ROW_TIERS[-1]):
        chunk = rows[lo:lo + LANE_ROW_TIERS[-1]]
        m = lane_row_tier(len(chunk))
        idx = np.full(m, chunk[0], dtype=np.int32)
        idx[:len(chunk)] = chunk
        data = src[idx]
        on_chunk(idx.nbytes + data.nbytes, m)
        if REC.enabled:
            # no node in scope here: the recorder's configured clock (sim
            # time under the cluster/maelstrom) timestamps the upload
            REC.instant(0, "deltas", "lane_upload", REC.now_us(),
                        args={"bytes": idx.nbytes + data.nbytes, "tier": m})
        lane = scatter_rows(lane, jnp.asarray(idx), jnp.asarray(data))
    return lane
