"""The TPU data plane: batched dependency computation and execute-order
closure as JAX/XLA/Pallas tensor programs.

This is the point of the whole exercise (SURVEY.md section 7 step 7,
BASELINE.json north star): the reference implements its deps-calculation hot
loop as hand-optimized flat-array Java scans
(local/cfk/CommandsForKey.java:809-968, utils/SearchableRangeList.java); we
re-express the same queries over *micro-batches* of transactions as

  - interval/key bitmaps over the hash-key domain  (bool[B, K])
  - pairwise conflict = bitmap boolean matmul      (MXU)
  - kind-witness filtering via a 6x6 table lookup  (VPU)
  - started-before via packed-timestamp compares   (VPU)
  - execute-order reachability = iterated boolean matmul closure (MXU)

behind the DepsResolver SPI, differentially tested against the host
CommandStore scan.
"""
from accord_tpu.ops.encoding import TimestampEncoder, WITNESS_TABLE
from accord_tpu.ops.resolver import DepsResolver, HostDepsResolver, BatchDepsResolver

__all__ = ["TimestampEncoder", "WITNESS_TABLE", "DepsResolver",
           "HostDepsResolver", "BatchDepsResolver"]
