"""Node-id batch axis for the cluster-on-mesh burn (sim/mesh_burn.py): the
PR 4 store-id-lane fusion lifted one level up. PR 4 folded every STORE's
pending items on one node into a single device call (fused_deps_resolve's
`subj_store` lane + per-store word spans); this module folds every NODE's
encoded dispatch plans in one cluster tick into a single device call with a
traced `subj_node` lane, so the burn's per-tick device cost stops scaling
with cluster size.

The merge is a pure re-batching, engineered for BIT-IDENTITY with the
per-node launch loop:

  - Each plan's already-encoded subject lanes (the CSR entries, 3-lane
    `before` bounds, kinds, the store-id routing lane) stack row-major into
    one node-major block; CSR entries remap by the plan's row offset.
  - Each plan's arena snapshots enter the kernel as lane blocks exactly as
    `fused_deps_resolve` takes them; every (plan, store-group) pair gets a
    globally unique slot id (`plan_base + local group index`), so a subject
    only ever sees its own plan's arena rows. Plan bases advance by
    `len(groups) + 1`, keeping each plan's padding sentinel
    (`plan_base + len(groups)`) unmatched by construction.
  - The masked bf16 products the MXU contracts are exact 0/1 integers and
    every mask/pack op is exact, so per-plan output slices equal the
    per-plan kernel calls bit for bit regardless of how blocks batch
    together (the same argument that made PR 4's fused path differential
    with the per-store loop). Block caps are 32-row multiples (the arena
    capacity contract), so packed word boundaries never straddle blocks.
  - Demux is the `_Group` row-offset-table pattern: each plan slices
    `[row_off : row_off + b, w_lo : w_hi]` out of the merged packed result,
    and the untouched group spans (g.pk / g.rp / g.kp) keep routing the
    harvest decode inside that slice.

Shape discipline mirrors the rest of ops/: the merged subject axis pads to
NODE_SUBJECT_TIERS, the merged CSR to the shared nnz ladder, and the block
COUNT pads to the resolver's `pad_node_tiers` ladder with cached empty
arena blocks under slot -1 -- node-count churn (crashes, membership change)
re-lands on the same compiled tiers, so steady-state burns mint zero new
jit entries (asserted by bench_mesh_burn via kernels.jit_cache_sizes and
the node-lane cache sizes below).

The merge structures built here (build_key_merge / build_range_merge) are
consumed by THREE launch paths, all bit-identical by the argument above:
the single-device fused kernels, ops/kernels.protocol_tick (the
single-device megakernel inlines _key_resolve_body/_range_resolve_body),
and parallel/mesh.sharded_protocol_tick (the sharded megakernel feeds the
same merge inputs to its shard_map'd resolve stage).
"""
from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from accord_tpu.ops.kernels import (_lex_before, _pack_bits, covered_buckets,
                                    nnz_tier)
from accord_tpu.ops.tiers import snap

# Merged subject-row ladder: a cluster tick at N nodes stacks up to
# N * max_dispatch subject rows, so the named tiers run past SUBJECT_TIERS;
# oversized totals fall onto power-of-two buckets like every other ladder.
NODE_SUBJECT_TIERS = (64, 256, 1024, 4096)

# Default block-count ladder for pad_node_tiers when the resolver doesn't
# pin one: snaps the per-tick (plan, store) block count so node churn of a
# few replicas (crash / restart / membership change) stays on one tier.
NODE_BLOCK_TIERS = (2, 4, 8, 16, 32, 64, 128, 256)


def node_subject_tier(n: int) -> int:
    """Padded merged-subject row count for a cluster tick of n rows."""
    return snap(n, NODE_SUBJECT_TIERS, 8192)


def node_block_tier(n: int, tiers: Optional[Sequence[int]] = None) -> int:
    """Padded lane-block count for a cluster tick of n (plan, group)
    blocks. `tiers` comes from resolver.pad_node_tiers when set (an int is
    treated as a single named tier, mirroring pad_store_tiers)."""
    if tiers is None:
        tiers = NODE_BLOCK_TIERS
    elif isinstance(tiers, int):
        tiers = (tiers,)
    tiers = tuple(tiers)
    return snap(n, tiers, tiers[-1] if tiers else 2)


@jax.jit
def node_fused_deps_resolve(subj_of, subj_keys, subj_node, subj_before,
                            subj_kinds, slots, arenas, witness_table):
    """Cluster-tick twin of kernels.fused_deps_resolve: ONE device call
    answers every node's key-domain deps slice. `arenas` is a tuple of
    (plan, store)-lane blocks in plan-major order (padding blocks last
    under slot -1); `subj_node` routes each stacked subject row to its own
    plan's block via the globally unique slot ids.

    subj_of:     i32[nnz]   merged CSR subject rows (padding entries use B)
    subj_keys:   i32[nnz]   key bucket indices (already % K)
    subj_node:   i32[B]     global (plan, group) slot per subject row
    slots:       i32[S]     the slot each block answers (traced)
    arenas:      tuple of S (bitmaps f32[cap_s, K], ts i32[cap_s, 3],
                 kinds i32[cap_s], valid bool[cap_s])
    -> u32[B, sum(cap_s)/32] packed dependency bitmask, blocks in tuple
       order (each plan's word span is contiguous)
    """
    return _key_resolve_body(subj_of, subj_keys, subj_node, subj_before,
                             subj_kinds, slots, arenas, witness_table)


def _key_resolve_body(subj_of, subj_keys, subj_node, subj_before,
                      subj_kinds, slots, arenas, witness_table):
    """node_fused_deps_resolve's trace body, unjitted so the protocol
    megakernel (kernels.protocol_tick) inlines the same resolve."""
    b = subj_before.shape[0]
    k = arenas[0][0].shape[1]
    subj_bm = jnp.zeros((b, k), jnp.float32) \
        .at[subj_of, subj_keys].max(1.0, mode="drop").astype(jnp.bfloat16)
    outs = []
    for s, (act_bm, act_ts, act_kinds, act_valid) in enumerate(arenas):
        overlap = jax.lax.dot_general(
            subj_bm, act_bm.astype(jnp.bfloat16),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) > 0.5
        witness = witness_table[subj_kinds[:, None], act_kinds[None, :]] == 1
        before = _lex_before(act_ts[None, :, :], subj_before[:, None, :])
        mine = (subj_node == slots[s])[:, None]
        outs.append(_pack_bits(
            overlap & witness & before & act_valid[None, :] & mine))
    return jnp.concatenate(outs, axis=1)


@jax.jit
def node_fused_range_deps_resolve(iv_of, iv_start, iv_end, subj_node,
                                  subj_before, subj_kinds, subj_is_range,
                                  r_slots, rarenas, k_slots, karenas,
                                  witness_table):
    """Cluster-tick twin of kernels.fused_range_deps_resolve: every node's
    range-arena stab and key-arena hull contraction in one call. Slot
    routing and block order work exactly like node_fused_deps_resolve;
    either block tuple may be empty (that side returns a zero-width
    buffer).

    -> (u32[B, sum(rcap_s)/32], u32[B, sum(cap_s)/32])
    """
    return _range_resolve_body(iv_of, iv_start, iv_end, subj_node,
                               subj_before, subj_kinds, subj_is_range,
                               r_slots, rarenas, k_slots, karenas,
                               witness_table)


def _range_resolve_body(iv_of, iv_start, iv_end, subj_node,
                        subj_before, subj_kinds, subj_is_range,
                        r_slots, rarenas, k_slots, karenas, witness_table):
    """node_fused_range_deps_resolve's trace body, unjitted for
    kernels.protocol_tick (see _key_resolve_body)."""
    b = subj_before.shape[0]
    routs = []
    for s, (r_start, r_end, r_ts, r_kinds, r_valid) in enumerate(rarenas):
        rcap = r_start.shape[0]
        hit_r = (iv_start[:, None] < r_end[None, :]) \
            & (r_start[None, :] < iv_end[:, None])
        any_r = jnp.zeros((b, rcap), jnp.int32) \
            .at[iv_of].max(hit_r.astype(jnp.int32), mode="drop") > 0
        witness_r = witness_table[subj_kinds[:, None], r_kinds[None, :]] == 1
        before_r = _lex_before(r_ts[None, :, :], subj_before[:, None, :])
        mine = (subj_node == r_slots[s])[:, None]
        routs.append(_pack_bits(
            any_r & witness_r & before_r & r_valid[None, :] & mine))
    kouts = []
    if karenas:
        k = karenas[0][0].shape[1]
        cov = covered_buckets(iv_of, iv_start, iv_end, b, k, 0, k)
    for s, (k_bm, k_ts, k_kinds, k_valid) in enumerate(karenas):
        any_k = jax.lax.dot_general(
            cov, k_bm.astype(jnp.bfloat16),
            (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32) > 0.5
        witness_k = witness_table[subj_kinds[:, None], k_kinds[None, :]] == 1
        before_k = _lex_before(k_ts[None, :, :], subj_before[:, None, :])
        mine = (subj_node == k_slots[s])[:, None] & subj_is_range[:, None]
        kouts.append(_pack_bits(
            any_k & witness_k & before_k & k_valid[None, :] & mine))
    rpacked = jnp.concatenate(routs, axis=1) if routs \
        else jnp.zeros((b, 0), jnp.uint32)
    kpacked = jnp.concatenate(kouts, axis=1) if kouts \
        else jnp.zeros((b, 0), jnp.uint32)
    return rpacked, kpacked


@functools.partial(jax.jit, static_argnames=("rows", "words"))
def lane_slice(packed, row_off, word_off, rows: int, words: int):
    """Demux one plan's span out of the merged packed result. Offsets are
    traced (plan position in the merge never recompiles); the slice shape
    is static per (plan row tier, plan word width). Both axes ride bounded
    ladders: rows are per-plan subject tiers and multi-block span WIDTHS
    pad to the node-block tier times the block word width (see
    build_key_merge), so lane_slice sits under the same strict
    zero-recompile gates as every other tick kernel."""
    return jax.lax.dynamic_slice(packed, (row_off, word_off), (rows, words))


def node_lane_cache_sizes() -> dict:
    """Compiled-variant counts of the node-lane kernels (the mesh-burn
    bench folds these into its zero-recompile assertion alongside
    kernels.jit_cache_sizes)."""
    return {
        "node_fused_deps_resolve": node_fused_deps_resolve._cache_size(),
        "node_fused_range_deps_resolve":
            node_fused_range_deps_resolve._cache_size(),
        "lane_slice": lane_slice._cache_size(),
    }


class MergedBuffer:
    """One merged device result shared by every plan's MergedView: a single
    async copy, a single host materialization, views slice it host-side.
    This is the megakernel's harvest half -- the readback is ONE contiguous
    transfer and the per-plan demux costs zero device dispatches."""

    __slots__ = ("dev", "_copied", "_host")

    def __init__(self, dev):
        self.dev = dev
        self._copied = False
        self._host = None

    def copy_async(self) -> None:
        if not self._copied:
            self._copied = True
            try:
                self.dev.copy_to_host_async()
            except AttributeError:
                pass

    def is_ready(self) -> bool:
        try:
            return self.dev.is_ready()
        except AttributeError:
            return True

    def host(self):
        if self._host is None:
            self._host = np.asarray(self.dev)
        return self._host


class MergedView:
    """A plan's [row_off:+rows, word_off:+words] window of a MergedBuffer,
    duck-typed to the resolver's device-value protocol (_dev_ready /
    _dev_copy_async / _dev_read / block_until_ready). np.asarray returns a
    COPY of the window: the fault plane may bit-flip one plan's fetched
    arrays (ops/fault_plane.py corrupt draws) and sibling plans sharing the
    merged buffer must never see it."""

    __slots__ = ("buf", "r0", "rows", "w0", "words")

    def __init__(self, buf: MergedBuffer, r0: int, rows: int,
                 w0: int, words: int):
        self.buf = buf
        self.r0 = r0
        self.rows = rows
        self.w0 = w0
        self.words = words

    @property
    def shape(self):
        return (self.rows, self.words)

    def is_ready(self) -> bool:
        return self.buf.is_ready()

    def copy_to_host_async(self) -> None:
        self.buf.copy_async()

    def block_until_ready(self):
        self.buf.host()
        return self

    def __array__(self, dtype=None):
        h = self.buf.host()[self.r0:self.r0 + self.rows,
                            self.w0:self.w0 + self.words]
        return np.array(h, dtype=dtype, copy=True)


class KeyMerge:
    """The stacked inputs + demux spans for one cluster tick's key-domain
    merge. Built host-side from each plan's recorded `key_args` (the exact
    arrays its own kernel call would have consumed); `spans[i]` is plan i's
    (row_off, rows, word_off, words) slice of the merged packed output."""

    __slots__ = ("subj_of", "subj_keys", "subj_node", "sb", "sknd",
                 "slots", "blocks", "spans", "rows_used", "rows_padded")

    def __init__(self, subj_of, subj_keys, subj_node, sb, sknd, slots,
                 blocks, spans, rows_used, rows_padded):
        self.subj_of = subj_of
        self.subj_keys = subj_keys
        self.subj_node = subj_node
        self.sb = sb
        self.sknd = sknd
        self.slots = slots
        self.blocks = blocks
        self.spans = spans
        self.rows_used = rows_used
        self.rows_padded = rows_padded


class RangeMerge:
    """The stacked inputs + demux spans for one cluster tick's range-domain
    merge; `spans[i]` is (row_off, rows, r_word_off, r_words, k_word_off,
    k_words) -- zero-width sides mean the plan had no blocks there."""

    __slots__ = ("iv_of", "iv_s", "iv_e", "subj_node", "sb", "sknd", "srng",
                 "r_slots", "r_blocks", "k_slots", "k_blocks", "spans",
                 "rows_used", "rows_padded")

    def __init__(self, iv_of, iv_s, iv_e, subj_node, sb, sknd, srng,
                 r_slots, r_blocks, k_slots, k_blocks, spans,
                 rows_used, rows_padded):
        self.iv_of = iv_of
        self.iv_s = iv_s
        self.iv_e = iv_e
        self.subj_node = subj_node
        self.sb = sb
        self.sknd = sknd
        self.srng = srng
        self.r_slots = r_slots
        self.r_blocks = r_blocks
        self.k_slots = k_slots
        self.k_blocks = k_blocks
        self.spans = spans
        self.rows_used = rows_used
        self.rows_padded = rows_padded


def _layout(arg_list) -> Tuple[List[int], List[int], int, int]:
    """Common row layout over the plans in merge order: per-plan row
    offsets, per-plan padded widths, the padded total, and the used total.
    Key and range merges share one layout per plan set so subj rows line
    up with both CSRs."""
    offs, widths, off = [], [], 0
    for args in arg_list:
        b = args["sb"].shape[0]
        offs.append(off)
        widths.append(b)
        off += b
    total = node_subject_tier(off) if off else 0
    return offs, widths, off, total


def build_key_merge(entries, pad_block, node_tiers=None) -> KeyMerge:
    """Stack each plan's recorded key_args into one node-major dispatch.
    `entries` is [(plan, key_args)] in launch order; `pad_block(cap)`
    returns a cached empty key-arena 4-tuple (the resolver's
    pad_store_tiers cache, reused as the node-tier pad pool).

    Each fused plan's recorded `pad_tier` mirrors its resolver's
    pad_store_tiers: the baseline `_pad_fused` tops each FUSED call's block
    list up to it at launch time, so each fused plan's packed buffer
    carries those pad word columns. The merge replicates that padding
    INSIDE the plan's span -- the demuxed slice's live word columns equal
    the baseline buffer bit for bit (a multi-block span may then widen to
    its node-block-tier word width with further all-zero columns, which the
    group-span decode never reads -- that's what pins lane_slice's compiled
    shapes to a bounded ladder)."""
    arg_list = [args for _, args in entries]
    offs, widths, used, b_total = _layout(arg_list)
    sb = np.zeros((b_total, 3), np.int32)
    sknd = np.zeros(b_total, np.int32)
    subj_node = np.full(b_total, -9, np.int32)
    # recorded CSRs are already tier-padded per plan; restack only the live
    # entries so the merged nnz tier tracks the real total
    live_of, live_keys = [], []
    slots_all: List[int] = []
    blocks: List[tuple] = []
    spans: List[tuple] = []
    base = 0
    w_off = 0
    for p, (plan, args) in enumerate(entries):
        b = widths[p]
        r0 = offs[p]
        sb[r0:r0 + b] = args["sb"]
        sknd[r0:r0 + b] = args["sknd"]
        ngroups = args["ngroups"]
        # global slot ids: plan_base + local group index; the plan's
        # padding sentinel (plan_base + ngroups) matches no block
        subj_node[r0:r0 + b] = base + args["subj_store"]
        local = args["subj_of"]
        mask = local < b
        live_of.append(np.where(mask, local + r0, 0)[mask])
        live_keys.append(args["subj_keys"][mask])
        w_lo = w_off
        nreal = 0
        nspan = 0
        cap_plan = 0
        caps = set()
        for gslot, snap_ in zip(args["slots"], args["ksnaps"]):
            bm, ts, _ex, kinds, valid = snap_
            blocks.append((bm, ts, kinds, valid))
            slots_all.append(base + int(gslot))
            w_off += bm.shape[0] // 32
            nreal += 1
            caps.add(bm.shape[0])
            cap_plan = max(cap_plan, bm.shape[0])
        nspan = nreal
        tier_p = args["pad_tier"] if args["fused"] else None
        if tier_p and nreal < tier_p:
            pad = pad_block(cap_plan)
            for _ in range(tier_p - nreal):
                blocks.append(pad)
                slots_all.append(-1)
                w_off += cap_plan // 32
            nspan = tier_p
        # demux-span WIDTH tier (the lane_slice zero-recompile fix): pad a
        # multi-block uniform-cap span out to the node-block tier's word
        # width with empty blocks, so harvest slice shapes land on the
        # (subject tier x block tier) ladder instead of minting one shape
        # per participating store count. Single-block spans are already
        # tiered by the arena cap ladder; mixed-cap spans (arenas caught
        # mid-growth) keep their exact width.
        if nspan > 1 and len(caps) == 1 and cap_plan:
            bw = cap_plan // 32
            want = node_block_tier(nspan, node_tiers) * bw
            pad = pad_block(cap_plan)
            while w_off - w_lo < want:
                blocks.append(pad)
                slots_all.append(-1)
                w_off += bw
        spans.append((r0, b, w_lo, w_off - w_lo))
        base += ngroups + 1
    # block-count tier: cached empty blocks under slot -1 (no subject's
    # lane is negative), capacity matching the widest real block so the
    # compiled shape tracks arena growth
    tier = node_block_tier(len(blocks), node_tiers)
    if blocks and len(blocks) < tier:
        cap = max(b[0].shape[0] for b in blocks)
        pad = pad_block(cap)
        while len(blocks) < tier:
            blocks.append(pad)
            slots_all.append(-1)
    total_live = sum(a.shape[0] for a in live_of)
    z = nnz_tier(total_live) if total_live else nnz_tier(1)
    subj_of = np.full(z, b_total, np.int32)
    subj_keys = np.zeros(z, np.int32)
    if total_live:
        subj_of[:total_live] = np.concatenate(live_of)
        subj_keys[:total_live] = np.concatenate(live_keys)
    return KeyMerge(subj_of, subj_keys, subj_node, sb, sknd,
                    np.asarray(slots_all, np.int32), tuple(blocks), spans,
                    used, b_total)


def build_range_merge(entries, pad_key_block, pad_range_block,
                      node_tiers=None) -> RangeMerge:
    """Stack each plan's recorded range_args into one node-major dispatch:
    the merged interval CSR plus plan-major range-arena and key-arena
    block lists (independently tier-padded). Each fused plan's recorded
    `pad_tier` replicates the baseline's per-plan `_pad_fused` padding
    inside that plan's span on BOTH sides (see build_key_merge); a side
    whose baseline result is discarded (has_r/has_k False) contributes no
    blocks at all."""
    arg_list = [args for _, args in entries]
    offs, widths, used, b_total = _layout(arg_list)
    sb = np.zeros((b_total, 3), np.int32)
    sknd = np.zeros(b_total, np.int32)
    srng = np.zeros(b_total, bool)
    subj_node = np.full(b_total, -9, np.int32)
    live_of, live_s, live_e = [], [], []
    r_slots: List[int] = []
    k_slots: List[int] = []
    r_blocks: List[tuple] = []
    k_blocks: List[tuple] = []
    spans: List[tuple] = []
    base = 0
    rw_off = kw_off = 0
    for p, (plan, args) in enumerate(entries):
        b = widths[p]
        r0 = offs[p]
        sb[r0:r0 + b] = args["sb"]
        sknd[r0:r0 + b] = args["sknd"]
        srng[r0:r0 + b] = args["srng"]
        ngroups = args["ngroups"]
        subj_node[r0:r0 + b] = base + args["subj_store"]
        local = args["iv_of"]
        mask = local < b
        live_of.append(np.where(mask, local + r0, 0)[mask])
        live_s.append(args["iv_s"][mask])
        live_e.append(args["iv_e"][mask])
        rw_lo, kw_lo = rw_off, kw_off
        tier_p = args["pad_tier"] if args["fused"] else None
        nreal_r = 0
        rcap_plan = 0
        rcaps = set()
        if args["has_r"]:
            for gslot, snap_ in zip(args["r_slots"], args["rsnaps"]):
                r_blocks.append(snap_)
                r_slots.append(base + int(gslot))
                rw_off += snap_[0].shape[0] // 32
                nreal_r += 1
                rcaps.add(snap_[0].shape[0])
                rcap_plan = max(rcap_plan, snap_[0].shape[0])
            nspan_r = nreal_r
            if tier_p and nreal_r < tier_p:
                pad = pad_range_block(rcap_plan)
                for _ in range(tier_p - nreal_r):
                    r_blocks.append(pad)
                    r_slots.append(-1)
                    rw_off += rcap_plan // 32
                nspan_r = tier_p
            # span-width tier, exactly as build_key_merge
            if nspan_r > 1 and len(rcaps) == 1 and rcap_plan:
                bw = rcap_plan // 32
                want = node_block_tier(nspan_r, node_tiers) * bw
                pad = pad_range_block(rcap_plan)
                while rw_off - rw_lo < want:
                    r_blocks.append(pad)
                    r_slots.append(-1)
                    rw_off += bw
        nreal_k = 0
        kcap_plan = 0
        kcaps = set()
        if args["has_k"]:
            for gslot, snap_ in zip(args["k_slots"], args["ksnaps"]):
                bm, ts, _ex, kinds, valid = snap_
                k_blocks.append((bm, ts, kinds, valid))
                k_slots.append(base + int(gslot))
                kw_off += bm.shape[0] // 32
                nreal_k += 1
                kcaps.add(bm.shape[0])
                kcap_plan = max(kcap_plan, bm.shape[0])
            nspan_k = nreal_k
            if tier_p and nreal_k < tier_p:
                pad = pad_key_block(kcap_plan)
                for _ in range(tier_p - nreal_k):
                    k_blocks.append(pad)
                    k_slots.append(-1)
                    kw_off += kcap_plan // 32
                nspan_k = tier_p
            if nspan_k > 1 and len(kcaps) == 1 and kcap_plan:
                bw = kcap_plan // 32
                want = node_block_tier(nspan_k, node_tiers) * bw
                pad = pad_key_block(kcap_plan)
                while kw_off - kw_lo < want:
                    k_blocks.append(pad)
                    k_slots.append(-1)
                    kw_off += bw
        spans.append((r0, b, rw_lo, rw_off - rw_lo, kw_lo, kw_off - kw_lo))
        base += ngroups + 1
    rtier = node_block_tier(len(r_blocks), node_tiers) if r_blocks else 0
    if r_blocks and len(r_blocks) < rtier:
        cap = max(blk[0].shape[0] for blk in r_blocks)
        pad = pad_range_block(cap)
        while len(r_blocks) < rtier:
            r_blocks.append(pad)
            r_slots.append(-1)
    ktier = node_block_tier(len(k_blocks), node_tiers) if k_blocks else 0
    if k_blocks and len(k_blocks) < ktier:
        cap = max(blk[0].shape[0] for blk in k_blocks)
        pad = pad_key_block(cap)
        while len(k_blocks) < ktier:
            k_blocks.append(pad)
            k_slots.append(-1)
    total_live = sum(a.shape[0] for a in live_of)
    z = nnz_tier(total_live) if total_live else nnz_tier(1)
    iv_of = np.full(z, b_total, np.int32)
    iv_s = np.zeros(z, np.int32)
    iv_e = np.zeros(z, np.int32)
    if total_live:
        iv_of[:total_live] = np.concatenate(live_of)
        iv_s[:total_live] = np.concatenate(live_s)
        iv_e[:total_live] = np.concatenate(live_e)
    return RangeMerge(iv_of, iv_s, iv_e, subj_node, sb, sknd, srng,
                      np.asarray(r_slots, np.int32), tuple(r_blocks),
                      np.asarray(k_slots, np.int32), tuple(k_blocks),
                      spans, used, b_total)


def run_key_merge(merge: KeyMerge, witness_table):
    """Launch the merged key-domain dispatch (single device)."""
    return node_fused_deps_resolve(
        jnp.asarray(merge.subj_of), jnp.asarray(merge.subj_keys),
        jnp.asarray(merge.subj_node), jnp.asarray(merge.sb),
        jnp.asarray(merge.sknd), jnp.asarray(merge.slots),
        merge.blocks, witness_table)


def run_range_merge(merge: RangeMerge, witness_table):
    """Launch the merged range-domain dispatch (single device)."""
    return node_fused_range_deps_resolve(
        jnp.asarray(merge.iv_of), jnp.asarray(merge.iv_s),
        jnp.asarray(merge.iv_e), jnp.asarray(merge.subj_node),
        jnp.asarray(merge.sb), jnp.asarray(merge.sknd),
        jnp.asarray(merge.srng), jnp.asarray(merge.r_slots),
        merge.r_blocks, jnp.asarray(merge.k_slots), merge.k_blocks,
        witness_table)
